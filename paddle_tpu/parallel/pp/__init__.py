"""SPMD pipeline-parallel schedules over the 'pp' mesh axis.

The TPU rewrite of the reference's pipeline runtime
(``python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py``
1F1B schedule + ``pp_utils/p2p_communication.py`` p2p send/recv):

- **Stage-resident weights**: each pp device holds only its stage's slice
  of the stacked layer weights (``in_specs=P('pp')`` on the layer dim) —
  unlike the r1 scan-over-layers layout, weights never stream across
  stages.
- **collective-permute handoffs**: activations move stage s -> s+1 with
  ``lax.ppermute`` — the ICI-neighbor transfer that replaces the
  reference's NCCL ``send_v2``/``recv_v2`` pair (shape metadata handshake
  unnecessary: shapes are static under jit).
- **Microbatch loop**: ``lax.scan`` over M + S - 1 ticks. Differentiating
  through the scan-of-ppermute yields the reverse pipeline automatically —
  the backward pass IS a pipelined schedule with reversed permutes, so the
  1F1B fwd/bwd interleaving the reference hand-schedules falls out of
  autodiff. Pass ``remat=True`` (or checkpoint inside your own stage_fn,
  as the llama model does per-layer) to rematerialize each tick's stage
  body in backward — that bounds live activations at ~one microbatch per
  stage, the 1F1B memory behavior; without remat, scan residuals grow
  linearly in num_microbatches.

Partial-manual ``jax.shard_map``: only 'pp' is manual; dp/sharding/sep/mp
stay in GSPMD's hands inside the stage body, so tensor-parallel layers and
batch sharding compose with the pipeline unchanged.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import mesh as mesh_mod

PP_AXIS = "pp"


def _pp_degree(mesh, axis):
    if mesh is None:
        return 1
    return int(mesh.shape[axis]) if axis in mesh.axis_names else 1


def _run_schedule(apply_fn, params, params_in_specs, x, *, M, S, mesh, axis,
                  remat):
    """Shared microbatch-tick schedule.

    ``apply_fn(params_local, a) -> a`` is the per-device stage computation
    (plain stage_fn, or a lax.switch over heterogeneous branches).
    """
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    mb = B // M
    xs = x.reshape(M, mb, *x.shape[1:])
    T = M + S - 1
    stage = jax.checkpoint(apply_fn) if remat else apply_fn

    def body(params_local, xs):
        s = jax.lax.axis_index(axis)
        fwd = [(i, i + 1) for i in range(S - 1)]

        def tick(a, t):
            # stage 0 pulls microbatch t from the input stream (clipped in
            # the drain phase — those outputs never reach the last stage)
            x_t = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), 0, keepdims=False)
            a_in = jnp.where(s == 0, x_t, a)
            y = stage(params_local, a_in)
            a_next = jax.lax.ppermute(y, axis, fwd)
            return a_next, y

        a0 = jnp.zeros_like(xs[0])
        _, ys = jax.lax.scan(tick, a0, jnp.arange(T))
        return ys[None]  # [1, T, mb, ...] -> global [S, T, mb, ...]

    ys = jax.shard_map(
        body, mesh=mesh, axis_names={axis},
        in_specs=(params_in_specs, P()), out_specs=P(axis),
        check_vma=False)(params, xs)
    # valid outputs: last stage, ticks S-1 .. T-1 == microbatches 0 .. M-1
    out = ys[S - 1, S - 1:]
    return out.reshape(B, *out.shape[2:])


def pipeline_spmd(stage_fn, stacked_params, x, *, num_microbatches,
                  mesh=None, axis=PP_AXIS, remat=False):
    """Pipelined application of a homogeneous layer stack.

    Args:
      stage_fn: ``(local_params, h) -> h`` applying one *stage* — the
        pp-local slice of the stack (leading dim ``L // S``) — to an
        activation microbatch. Typically an inner ``lax.scan`` over the
        local layers.
      stacked_params: pytree of arrays with leading dim L (total layers),
        L % S == 0. Sharded (or shardable) ``P('pp')`` on dim 0 — each
        device keeps only its stage's layers.
      x: activations ``[B, ...]``; B % num_microbatches == 0. Non-batch
        dims may carry auto-axis shardings (mp/sep) — they survive.
      num_microbatches: M. Pipeline bubble fraction is (S-1)/(M+S-1).
      remat: checkpoint the stage body per tick (1F1B memory bound). Leave
        False if stage_fn already remats internally (e.g. per layer).

    Returns ``[B, ...]`` activations after all L layers.
    """
    mesh = mesh if mesh is not None else mesh_mod.get_mesh()
    S = _pp_degree(mesh, axis)
    if S <= 1:
        return stage_fn(stacked_params, x)
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    if L % S != 0:
        raise ValueError(f"layer count {L} not divisible by pp degree {S}")
    return _run_schedule(
        stage_fn, stacked_params,
        jax.tree.map(lambda _: P(axis), stacked_params), x,
        M=int(num_microbatches), S=S, mesh=mesh, axis=axis, remat=remat)


def _pack_stages(params_tuple):
    """Pack arbitrary per-stage pytrees into per-dtype flat buffers.

    Returns (bufs, metas): ``bufs[dtype_key]`` is [S, L_dtype] (each row a
    stage's concatenated raveled leaves of that dtype, zero-padded to the
    longest stage); ``metas[i]`` rebuilds stage i's pytree from row i via
    static (offset, shape) slices. Non-array leaves (python scalars /
    config values) stay static in the meta. Differentiable end-to-end:
    ravel/concat/stack adjoints are slices, so grads land back on the
    caller's original per-stage leaves."""
    import numpy as np

    metas = []
    stage_bufs = []          # per stage: dtype_key -> 1-D array
    for p in params_tuple:
        leaves, treedef = jax.tree.flatten(p)
        parts = {}
        meta_leaves = []
        for leaf in leaves:
            if not isinstance(leaf, (jnp.ndarray, np.ndarray)):
                meta_leaves.append(("static", leaf))
                continue
            arr = jnp.asarray(leaf)
            key = str(arr.dtype)
            off = sum(int(a.size) for a in parts.get(key, []))
            parts.setdefault(key, []).append(arr.reshape(-1))
            meta_leaves.append(("buf", key, off, tuple(arr.shape)))
        stage_bufs.append({k: jnp.concatenate(v) for k, v in parts.items()})
        metas.append((treedef, meta_leaves))
    keys = sorted({k for b in stage_bufs for k in b})
    bufs = {}
    for k in keys:
        lmax = max(int(b[k].size) if k in b else 0 for b in stage_bufs)
        rows = []
        for b in stage_bufs:
            r = b.get(k, jnp.zeros((0,), dtype=jnp.dtype(k)))
            rows.append(jnp.pad(r, (0, lmax - int(r.size))))
        bufs[k] = jnp.stack(rows)
    return bufs, metas


def _unpack_stage(meta, bufs):
    """Rebuild one stage's pytree from its per-dtype flat buffers using
    the static layout recorded by _pack_stages."""
    treedef, meta_leaves = meta
    leaves = []
    for m in meta_leaves:
        if m[0] == "static":
            leaves.append(m[1])
        else:
            _, key, off, shape = m
            size = 1
            for d in shape:
                size *= d
            leaves.append(bufs[key][off:off + size].reshape(shape))
    return jax.tree.unflatten(treedef, leaves)


def pipeline_1f1b(stage_fns, stage_params, x, *, num_microbatches,
                  mesh=None, axis=PP_AXIS, remat=False):
    """Pipelined application of *heterogeneous* stages (general
    PipelineLayer topologies) via ``lax.switch`` on the stage index.

    ``stage_fns[i](stage_params[i], h) -> h`` must all map activations of
    the same shape/dtype (the pipeline handoff contract).

    Weight residency: when every stage's params share ONE pytree structure
    with matching leaf shapes/dtypes (stages differ only in their fn or
    weight values), the per-stage leaves are stacked on a leading stage
    dim sharded ``P('pp')`` — each device HOLDS only its own stage's
    weights, like the reference's per-rank PipelineLayer ownership †; only
    the fn dispatch remains a ``lax.switch``. Structurally heterogeneous
    stages (embed -> blocks -> head) get the same residency through
    per-dtype flat packing: each stage's leaves ravel into zero-padded
    [S, L] buffers sharded ``P('pp')``, and each branch statically
    unpacks its own layout (no replication either way; grads flow back
    through the pack's slice adjoints to the original leaves).
    """
    mesh = mesh if mesh is not None else mesh_mod.get_mesh()
    S = _pp_degree(mesh, axis)
    if S <= 1:
        h = x
        for fn, p in zip(stage_fns, stage_params):
            h = fn(p, h)
        return h
    if len(stage_fns) != S:
        raise ValueError(f"{len(stage_fns)} stage fns for pp degree {S}")
    params_tuple = tuple(stage_params)

    import numpy as np

    def _sig(p):
        # np.shape/result_type tolerate scalar (non-array) leaves, which
        # the replicated fallback has always supported
        return [(np.shape(l), jnp.result_type(l)) for l in jax.tree.leaves(p)]

    struct0 = jax.tree.structure(params_tuple[0])
    sig0 = _sig(params_tuple[0])
    same_structure = all(
        jax.tree.structure(p) == struct0 and _sig(p) == sig0
        for p in params_tuple[1:])

    if same_structure:
        # stack per-stage leaves on a stage dim sharded over 'pp': each
        # device receives a leading-dim-1 slice = its OWN stage's weights
        stacked = jax.tree.map(lambda *ls: jnp.stack(ls), *params_tuple)

        def apply_resident(params_local, a):
            s = jax.lax.axis_index(axis)
            mine = jax.tree.map(lambda p: p[0], params_local)
            branches = [
                (lambda a, i=i: stage_fns[i](mine, a)) for i in range(S)
            ]
            return jax.lax.switch(s, branches, a)

        return _run_schedule(
            apply_resident, stacked,
            jax.tree.map(lambda _: P(axis), stacked), x,
            M=int(num_microbatches), S=S, mesh=mesh, axis=axis, remat=remat)

    # Structurally heterogeneous stages (embed -> blocks -> head): pack
    # each stage's leaves into per-dtype flat buffers, zero-pad to the
    # longest stage, and stack [S, L] sharded P('pp') — each device holds
    # ONLY its own stage's bytes (reference per-rank PipelineLayer
    # ownership †), and every branch statically unpacks ITS stage's
    # (offset, shape) layout from the local buffer. This removes the r4
    # fallback that replicated all stages' weights onto every device.
    bufs, metas = _pack_stages(params_tuple)

    def apply_packed(bufs_local, a):
        s = jax.lax.axis_index(axis)
        mine = {k: b[0] for k, b in bufs_local.items()}
        branches = [
            (lambda a, i=i: stage_fns[i](_unpack_stage(metas[i], mine), a))
            for i in range(S)
        ]
        return jax.lax.switch(s, branches, a)

    return _run_schedule(
        apply_packed, bufs,
        jax.tree.map(lambda _: P(axis), bufs), x,
        M=int(num_microbatches), S=S, mesh=mesh, axis=axis, remat=remat)


# Name referenced by docstrings elsewhere in the tree.
schedule = pipeline_spmd


def pipeline_interleaved(stage_fn, stacked_params, x, *, num_microbatches,
                         num_virtual=2, mesh=None, axis=PP_AXIS, remat=False):
    """Interleaved (virtual-stage) pipeline schedule — the reference's
    ``PipelineParallelWithInterleave``
    (``meta_parallel/pipeline_parallel.py`` †), SPMD-style.

    Layers are split into S·V chunks; device s holds chunks
    ``{s, s+S, ..., s+(V-1)S}`` of K = L/(S·V) layers each, and every
    microbatch makes V passes around the device RING (``ppermute`` with the
    wrap edge S-1 -> 0). The bubble fraction drops from (S-1)/(M+S-1) to
    (S-1)/(M·V+S-1) — shrunk ~by the interleave factor V, which is the
    point of the reference schedule.

    Microbatches are processed in GROUPS of S: group g's microbatch j
    makes pass v through device s at tick ``t = g·S·V + v·S + j + s``.
    The (g, v, j) decomposition of t-s is unique, so every device is busy
    each tick once the fill ends, and the final-pass wrap of group g
    arrives at device 0 exactly on group g+1's injection tick (where the
    injected microbatch overrides it) — conflict-free for any
    ``num_microbatches`` that is ≤ S or a multiple of S (the reference's
    interleave likewise constrains M to multiples of S †).

    ``stage_fn(chunk_params, h) -> h`` applies ONE chunk (leading dim K).
    """
    mesh = mesh if mesh is not None else mesh_mod.get_mesh()
    S = _pp_degree(mesh, axis)
    if S <= 1:
        return stage_fn(stacked_params, x)
    M = int(num_microbatches)
    V = int(num_virtual)
    if M > S and M % S != 0:
        raise ValueError(
            f"interleaved schedule needs num_microbatches ({M}) <= pp "
            f"degree ({S}) or a multiple of it (group injection windows "
            f"must align with pass-wrap ticks)")
    L = jax.tree.leaves(stacked_params)[0].shape[0]
    if L % (S * V) != 0:
        raise ValueError(f"layer count {L} not divisible by S*V = {S * V}")
    K = L // (S * V)
    B = x.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    mb = B // M
    k_groups = max(1, M // S)
    # layer l = (v*S + s)*K + k  ->  [V, S, K, ...]; dim 1 is the stage dim
    params_r = jax.tree.map(
        lambda p: p.reshape(V, S, K, *p.shape[1:]), stacked_params)
    xs = x.reshape(M, mb, *x.shape[1:])
    # exactly one past the last harvest tick ((g+1)SV - 1 + j for the final
    # microbatch): M<=S gives the old M + SV - 1, M=kS gives kSV + S - 1
    T = ((M - 1) // S + 1) * S * V + (M - 1) % S
    stage = jax.checkpoint(stage_fn) if remat else stage_fn
    ring = [(i, (i + 1) % S) for i in range(S)]

    def body(params_local, xs_):
        s = jax.lax.axis_index(axis)
        pl = jax.tree.map(lambda p: p[:, 0], params_local)  # [V, K, ...]

        def tick(a, t):
            rel = jnp.where(t - s >= 0, t - s, 0)
            g = rel // (S * V)           # microbatch group
            r = jnp.mod(rel, S * V)
            v = r // S                   # virtual pass / chunk index
            j = jnp.mod(r, S)            # within-group microbatch
            m = g * S + j                # global microbatch id
            x_t = jax.lax.dynamic_index_in_dim(
                xs_, jnp.clip(m, 0, M - 1), 0, keepdims=False)
            inject = ((s == 0) & (t - s >= 0) & (v == 0) & (m < M)
                      & (g < k_groups))
            a_in = jnp.where(inject, x_t, a)
            chunk_params = jax.tree.map(
                lambda p: jax.lax.dynamic_index_in_dim(p, v, 0,
                                                       keepdims=False), pl)
            y = stage(chunk_params, a_in)
            a_next = jax.lax.ppermute(y, axis, ring)
            return a_next, y

        a0 = jnp.zeros_like(xs_[0])
        _, ys = jax.lax.scan(tick, a0, jnp.arange(T))
        return ys[None]

    ys = jax.shard_map(
        body, mesh=mesh, axis_names={axis},
        in_specs=(jax.tree.map(lambda _: P(None, axis), params_r), P()),
        out_specs=P(axis), check_vma=False)(params_r, xs)
    # microbatch m = (g, j) finishes chunk S*V-1 on device S-1 at tick
    # (g+1)*S*V - 1 + j
    m_ids = jnp.arange(M)
    out_ticks = (m_ids // S + 1) * S * V - 1 + jnp.mod(m_ids, S)
    out = jnp.take(ys[S - 1], out_ticks, axis=0)
    return out.reshape(B, *out.shape[2:])
