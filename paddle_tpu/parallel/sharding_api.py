"""Group-sharded (ZeRO) API (reference:
``python/paddle/distributed/sharding/group_sharded.py`` group_sharded_parallel
+ GroupShardedStage2/3 under meta_parallel/sharding/).

TPU-native ZeRO: stages are *sharding specs*, not wrapper machinery —

- stage 1 (osp): optimizer slots sharded over the 'sharding' axis;
- stage 2 (os+g): + grads reduce-scattered (GSPMD derives this from sharded
  opt-state consumers — the reduce-scatter replaces all-reduce exactly as the
  reference's stage-2 comm pattern does);
- stage 3 (p+os+g): + parameters sharded; XLA all-gathers params at use and
  frees them after (the reference's on-demand gather via layer hooks).

``group_sharded_parallel(model, optimizer, level)`` attaches the spec policy;
jit.TrainStep consumes it when compiling the step.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

SHARDING_AXIS = "sharding"

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def _shardable_dim(shape, axis_size):
    for d, s in enumerate(shape):
        if s % axis_size == 0 and s >= axis_size:
            return d
    return None


def _has_axis(spec, axis):
    if spec is None:
        return False
    for s in spec:
        if s == axis or (isinstance(s, tuple) and axis in s):
            return True
    return False


def param_spec_for_stage(param_shape, base_spec, stage, axis_size):
    """Spec for the parameter itself: stage 3 shards params; stages 1/2
    leave them as-is (replicated across 'sharding')."""
    if stage < 3 or axis_size <= 1 or _has_axis(base_spec, SHARDING_AXIS):
        return base_spec
    spec = list(base_spec) if base_spec is not None else [None] * len(param_shape)
    while len(spec) < len(param_shape):
        spec.append(None)
    for d, s in enumerate(param_shape):
        if spec[d] is None and s % axis_size == 0 and s >= axis_size:
            spec[d] = SHARDING_AXIS
            return P(*spec)
    return P(*spec) if base_spec is not None else None


def opt_state_spec(param_shape, base_spec, stage, axis_size):
    """Spec for optimizer slots: any stage >=1 shards them over 'sharding'."""
    if stage < 1 or axis_size <= 1 or _has_axis(base_spec, SHARDING_AXIS):
        return base_spec
    spec = list(base_spec) if base_spec is not None else [None] * len(param_shape)
    while len(spec) < len(param_shape):
        spec.append(None)
    for d, s in enumerate(param_shape):
        if spec[d] is None and s % axis_size == 0 and s >= axis_size:
            spec[d] = SHARDING_AXIS
            return P(*spec)
    return P(*spec) if base_spec is not None else None


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False,
                           dp_group=None, exclude_layers=None):
    """Attach ZeRO stage metadata (consumed by the compiled train step)."""
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {list(_LEVELS)}")
    stage = _LEVELS[level]
    model._group_sharded_stage = stage
    if hasattr(optimizer, "_inner_opt"):
        optimizer._sharding_stage = stage
    else:
        optimizer._group_sharded_stage = stage
    if offload:
        # XLA host-offload for opt state is a compiler flag policy; record it
        model._group_sharded_offload = True
    if scaler is not None:
        return model, optimizer, scaler
    return model, optimizer


def save_group_sharded_model(model, output, optimizer=None):
    """Reference save_group_sharded_model: gathers sharded state to rank 0.
    Single logical store: plain state_dict save."""
    import os
    from ..framework import io as fio
    os.makedirs(output, exist_ok=True)
    net = getattr(model, "_layers", model)
    fio.save(net.state_dict(), os.path.join(output, "model.pdmodel"))
    if optimizer is not None:
        fio.save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
