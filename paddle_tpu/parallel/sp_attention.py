"""Context parallelism: ring attention + Ulysses (DeepSpeed-style) all-to-all
attention over the 'sep' mesh axis.

The reference keeps a reserved sep axis in core and implements ring/Ulysses
in the PaddleNLP ecosystem over ``batch_isend_irecv`` p2p (SURVEY.md §5.7).
Here both are first-class, TPU-native:

- **Ring attention**: KV chunks rotate around the sep ring via
  ``lax.ppermute`` (ICI-neighbor transfers), with online-softmax combination
  of per-chunk partial results — flash attention's math at the inter-chip
  level, so sequence length scales linearly with ring size and each hop
  overlaps with the local attention compute.
- **Ulysses**: ``lax.all_to_all`` re-shards [seq/n, H] -> [seq, H/n] so each
  chip runs full-sequence attention for a head subset, then back.

Both run inside ``jax.shard_map`` over the global mesh and compose with the
dp/sharding batch axes.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod

SEQ_AXIS = "sep"


def _batch_axes(mesh):
    return tuple(a for a in ("dp", "sharding") if a in mesh.axis_names)


# ------------------------------------------------------------------ ring
def _chunk_attn_stats(q, k, v, rows_g, cols_g, scale, causal):
    """Local block attention returning (o_unnorm [.., S_l, D], m, l)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = rows_g[:, None] >= cols_g[None, :]
        s = jnp.where(mask[None, None], s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)
    # rows with no valid key yet: keep m finite to avoid nan exp
    m_safe = jnp.maximum(m, -1e30 + 1.0)
    p = jnp.exp(s - m_safe)
    if causal:
        p = jnp.where(mask[None, None], p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return o, m_safe, l


def _ring_local(q, k, v, idx_arr, *, axis_name, n, causal, scale):
    """Per-shard body: q/k/v [B, H, S_local, D] (seq-sharded over the ring).

    ``idx_arr`` is this shard's slice of a P(axis)-sharded iota — the ring
    position. Passing it as data instead of calling
    ``jax.lax.axis_index`` keeps the region Shardy-compatible: axis_index
    lowers to an sdy.manual_computation binding every OTHER mesh axis,
    which Shardy rejects inside an enclosing manual region (the pipeline's
    'pp' shard_map); a sharded input has no such lowering."""
    idx = idx_arr[0]
    B, H, S_l, D = q.shape
    rows_g = idx * S_l + jnp.arange(S_l)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(i, carry):
        o_acc, m_acc, l_acc, k_cur, v_cur = carry
        src = (idx - i) % n  # global chunk id currently held
        cols_g = src * S_l + jnp.arange(S_l)
        o_b, m_b, l_b = _chunk_attn_stats(q, k_cur, v_cur, rows_g, cols_g,
                                          scale, causal)
        m_new = jnp.maximum(m_acc, m_b)
        a_old = jnp.exp(m_acc - m_new)
        a_new = jnp.exp(m_b - m_new)
        o_acc = o_acc * a_old + o_b * a_new
        l_acc = l_acc * a_old + l_b * a_new
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return o_acc, m_new, l_acc, k_nxt, v_nxt

    o0 = jnp.zeros((B, H, S_l, D), jnp.float32)
    m0 = jnp.full((B, H, S_l, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, H, S_l, 1), jnp.float32)
    o, m, l, _, _ = jax.lax.fori_loop(0, n, body, (o0, m0, l0, k, v))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)




def _nesting_mesh(mesh, axis_name):
    """The mesh the sep shard_map must bind: inside an enclosing manual
    region (e.g. the pipeline's 'pp' shard_map) that is the context
    AbstractMesh, not the concrete mesh."""
    ctx = jax.sharding.get_abstract_mesh()
    if (ctx is not None and axis_name in getattr(ctx, "axis_names", ())
            and getattr(ctx, "manual_axes", ())):
        return ctx
    return mesh

def ring_attention(q, k, v, causal=True, mesh=None, axis_name=SEQ_AXIS):
    """Global [B, H, S, D] arrays, S sharded over the sep ring."""
    mesh = mesh or mesh_mod.get_mesh()
    if mesh is None or axis_name not in mesh.axis_names or \
            mesh.shape[axis_name] == 1:
        from ..kernels.flash_attention import _ref_attention
        o = _ref_attention(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                           jnp.swapaxes(v, 1, 2), causal)
        return jnp.swapaxes(o, 1, 2)
    scale = 1.0 / math.sqrt(q.shape[-1])
    n = int(mesh.shape[axis_name])
    # partial-manual: only the ring axis is manual; dp/sharding/mp stay in
    # GSPMD's hands so any batch/head sharding composes unchanged
    spec = P(None, None, axis_name, None)
    fn = functools.partial(_ring_local, axis_name=axis_name, n=n,
                           causal=causal, scale=scale)
    return jax.shard_map(fn, mesh=_nesting_mesh(mesh, axis_name),
                         axis_names={axis_name},
                         in_specs=(spec, spec, spec, P(axis_name)),
                         out_specs=spec, check_vma=False)(
        q, k, v, jnp.arange(n, dtype=jnp.int32))


# ------------------------------------------------------------------ ulysses
def _ulysses_local(q, k, v, *, axis_name, causal, scale):
    """q/k/v [B, H, S_local, D] -> all_to_all to [B, H/n, S, D] -> attention
    -> back."""
    def head_scatter(x):
        # [B, H, S_l, D] -> [B, H/n, S, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def head_gather(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qg, kg, vg = head_scatter(q), head_scatter(k), head_scatter(v)
    s = jnp.einsum("bhqd,bhkd->bhqk", qg, kg,
                   preferred_element_type=jnp.float32) * scale
    S = qg.shape[2]
    if causal:
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(vg.dtype)
    og = jnp.einsum("bhqk,bhkd->bhqd", p, vg)
    return head_gather(og)


def ulysses_attention(q, k, v, causal=True, mesh=None, axis_name=SEQ_AXIS):
    """DeepSpeed-Ulysses sequence parallelism: heads scatter / seq gather."""
    mesh = mesh or mesh_mod.get_mesh()
    if mesh is None or axis_name not in mesh.axis_names or \
            mesh.shape[axis_name] == 1:
        return ring_attention(q, k, v, causal, mesh, axis_name)
    scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, None, axis_name, None)
    fn = functools.partial(_ulysses_local, axis_name=axis_name, causal=causal,
                           scale=scale)
    return jax.shard_map(fn, mesh=_nesting_mesh(mesh, axis_name),
                         axis_names={axis_name},
                         in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)


# ------------------------------------------------------------ Tensor surface
def ring_flash_attention(query, key, value, causal=True, axis_name=SEQ_AXIS):
    """Tensor-level API ([B, S, H, D] paddle layout)."""
    from ..ops._op import tensor_op

    @tensor_op(name="ring_flash_attention")
    def _op(q, k, v):
        o = ring_attention(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                           jnp.swapaxes(v, 1, 2), causal=causal,
                           axis_name=axis_name)
        return jnp.swapaxes(o, 1, 2)

    return _op(query, key, value)


def ulysses_flash_attention(query, key, value, causal=True,
                            axis_name=SEQ_AXIS):
    from ..ops._op import tensor_op

    @tensor_op(name="ulysses_flash_attention")
    def _op(q, k, v):
        o = ulysses_attention(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                              jnp.swapaxes(v, 1, 2), causal=causal,
                              axis_name=axis_name)
        return jnp.swapaxes(o, 1, 2)

    return _op(query, key, value)
