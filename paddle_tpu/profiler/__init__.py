"""Profiler (reference: ``python/paddle/profiler/profiler.py`` state-machine
scheduler + ``paddle/fluid/platform/profiler/`` CUPTI tracing).

TPU-native: wraps ``jax.profiler`` (XPlane traces viewable in
TensorBoard/Perfetto/xprof). The paddle-shaped surface is kept: a Profiler
with a step-window scheduler, ``RecordEvent`` ranges (jax.named_scope /
TraceAnnotation), chrome-trace-compatible export directory, and summary
hooks. MFU/throughput accounting lives in :mod:`paddle_tpu.profiler.metrics`.
"""
from __future__ import annotations

import contextlib
import enum
import os
import time

import jax


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    CUSTOM_DEVICE = 2
    TPU = 3


def make_scheduler(closed=0, ready=0, record=0, repeat=0, skip_first=0):
    """Step-window scheduler, reference semantics."""
    cycle = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = s % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof):
        return dir_name

    handler.dir_name = dir_name
    return handler


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(closed=0, ready=0, record=scheduler[1] - scheduler[0],
                           skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else None)
        self._on_trace_ready = on_trace_ready
        self._dir = getattr(on_trace_ready, "dir_name", None) or "./profiler_log"
        self._timer_only = timer_only
        self._step = 0
        self._state = ProfilerState.CLOSED
        self._active = False
        self._step_times = []
        self._last = None

    def start(self):
        self._last = time.perf_counter()
        self._transition()

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            if self._on_trace_ready:
                self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last is not None:
            self._step_times.append(now - self._last)
        self._last = now
        self._step += 1
        self._transition()

    def _transition(self):
        state = (self._scheduler(self._step) if self._scheduler
                 else ProfilerState.RECORD)
        if self._timer_only:
            self._state = state
            return
        recording = state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if recording and not self._active:
            os.makedirs(self._dir, exist_ok=True)
            jax.profiler.start_trace(self._dir)
            self._active = True
        elif not recording and self._active:
            jax.profiler.stop_trace()
            self._active = False
            if self._on_trace_ready:
                self._on_trace_ready(self)
        self._state = state

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        if not self._step_times:
            print("no recorded steps")
            return
        import numpy as np
        ts = np.asarray(self._step_times[1:] or self._step_times)
        print(f"steps: {len(self._step_times)}  "
              f"avg: {ts.mean()*1e3:.2f}ms  p50: {np.median(ts)*1e3:.2f}ms  "
              f"min: {ts.min()*1e3:.2f}ms  max: {ts.max()*1e3:.2f}ms")
        print(f"traces (if recorded) under: {self._dir} — open with "
              f"TensorBoard or Perfetto")


class RecordEvent:
    """User range annotation -> jax.profiler.TraceAnnotation."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ctx = None

    def begin(self):
        self._ctx = jax.profiler.TraceAnnotation(self.name)
        self._ctx.__enter__()

    def end(self):
        if self._ctx is not None:
            self._ctx.__exit__(None, None, None)
            self._ctx = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


def load_profiler_result(path):
    raise NotImplementedError("open XPlane traces with TensorBoard/xprof")


from . import cost  # noqa: E402
from . import metrics  # noqa: E402
from . import tracing  # noqa: E402
from .cost import CostObservatory  # noqa: E402
from .metrics import MFUMeter  # noqa: E402
from .tracing import SpanTracer  # noqa: E402
