"""CLI entry point: ``python -m paddle_tpu.profiler <trace>``.

Two trace formats, auto-detected by what the argument is:

- a DIRECTORY: a ``jax.profiler`` (XPlane) trace dir — per-op time
  aggregation through :mod:`paddle_tpu.profiler.xplane`;
- a FILE: Chrome trace-event JSON, exactly what ``GET /debug/trace``
  serves (README "Tracing & debugging") — per-lane span SELF-time
  summary through :mod:`paddle_tpu.profiler.chrometrace`, so a saved
  serving capture answers "where did the step go" without Perfetto.

    python -m paddle_tpu.profiler /tmp/profile_dir            # op table
    python -m paddle_tpu.profiler trace.json --top 25         # span table
    python -m paddle_tpu.profiler trace.json --json           # machine-readable

Device planes (the XLA op timeline) are summarized by default on the
XPlane path; when a trace has none — CPU-backend traces put the ops on
host planes — the CLI falls back to all planes automatically and says
so (pass ``--all-planes`` to start there). Exit status: 0 when events
were parsed, 1 on unparseable input (no *.xplane.pb, bad JSON, no
traceEvents) so scripts can gate on it.
"""
from __future__ import annotations

import argparse
import json
import os
import sys


def _main_chrome(args):
    from .chrometrace import load_chrome_trace, span_self_times, \
        summarize_chrome
    if args.json:
        try:
            rows = span_self_times(load_chrome_trace(args.trace_dir))
        except ValueError as e:
            print(json.dumps({"error": str(e)}))
            return 1
        if args.top:
            rows = rows[:args.top]
        print(json.dumps({"trace": args.trace_dir, "rows": rows},
                         indent=1))
        return 0 if rows else 1
    try:
        out = summarize_chrome(args.trace_dir, top=args.top)
    except ValueError as e:
        print(f"unparseable trace: {e}")
        return 1
    print(out)
    return 0 if out != "no spans parsed" else 1


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.profiler",
        description="Per-op time aggregation over a jax.profiler "
                    "(XPlane) trace directory, or per-lane span "
                    "self-time over a Chrome trace-event JSON file "
                    "(as served by GET /debug/trace).")
    ap.add_argument("trace_dir", metavar="trace",
                    help="directory jax.profiler.start_trace wrote "
                         "(searched recursively for *.xplane.pb), or a "
                         "Chrome trace-event JSON file")
    ap.add_argument("--top", type=int, default=10,
                    help="rows to report (0 = all)")
    ap.add_argument("--json", action="store_true",
                    help="emit the op table as JSON instead of text")
    ap.add_argument("--all-planes", action="store_true",
                    help="aggregate host planes too (XPlane dirs only; "
                         "default: device planes, with automatic "
                         "fallback when a trace has none)")
    args = ap.parse_args(argv)

    if os.path.isfile(args.trace_dir):
        # a file is the Chrome-trace path; directories stay XPlane
        return _main_chrome(args)

    from .xplane import op_statistics_with_fallback, summarize
    device_only = not args.all_planes
    if args.json:
        rows, fell_back = op_statistics_with_fallback(
            args.trace_dir, device_only=device_only, top=args.top)
        print(json.dumps({"trace_dir": args.trace_dir,
                          "device_only": device_only and not fell_back,
                          "rows": rows}, indent=1))
        return 0 if rows else 1
    # text path: summarize owns the rendering AND the host-plane
    # fallback, so the table format lives in exactly one place
    out = summarize(args.trace_dir, top=args.top,
                    device_only=device_only)
    if out == "no device events parsed":
        print("no events parsed (is this a jax.profiler trace "
              "directory with *.xplane.pb files?)")
        return 1
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
