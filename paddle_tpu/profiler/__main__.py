"""CLI entry point: ``python -m paddle_tpu.profiler <trace_dir>``.

The XPlane parser (:mod:`paddle_tpu.profiler.xplane`) has existed since
it started validating bench traces, but had no command-line surface —
inspecting a ``jax.profiler`` trace directory meant an ad-hoc REPL
session. This wires ``xplane.op_statistics`` / ``xplane.summarize`` to
a command:

    python -m paddle_tpu.profiler /tmp/profile_dir            # op table
    python -m paddle_tpu.profiler /tmp/profile_dir --top 25
    python -m paddle_tpu.profiler /tmp/profile_dir --json     # machine-readable

Device planes (the XLA op timeline) are summarized by default; when a
trace has none — CPU-backend traces put the ops on host planes — the
CLI falls back to all planes automatically and says so (pass
``--all-planes`` to start there). Exit status: 0 when events were
parsed, 1 when the directory held no parseable trace (so scripts can
gate on it).
"""
from __future__ import annotations

import argparse
import json
import sys


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.profiler",
        description="Per-op time aggregation over a jax.profiler "
                    "(XPlane) trace directory.")
    ap.add_argument("trace_dir",
                    help="directory jax.profiler.start_trace wrote "
                         "(searched recursively for *.xplane.pb)")
    ap.add_argument("--top", type=int, default=10,
                    help="rows to report (0 = all)")
    ap.add_argument("--json", action="store_true",
                    help="emit the op table as JSON instead of text")
    ap.add_argument("--all-planes", action="store_true",
                    help="aggregate host planes too (default: device "
                         "planes only, with automatic fallback when a "
                         "trace has none)")
    args = ap.parse_args(argv)

    from .xplane import op_statistics_with_fallback, summarize
    device_only = not args.all_planes
    if args.json:
        rows, fell_back = op_statistics_with_fallback(
            args.trace_dir, device_only=device_only, top=args.top)
        print(json.dumps({"trace_dir": args.trace_dir,
                          "device_only": device_only and not fell_back,
                          "rows": rows}, indent=1))
        return 0 if rows else 1
    # text path: summarize owns the rendering AND the host-plane
    # fallback, so the table format lives in exactly one place
    out = summarize(args.trace_dir, top=args.top,
                    device_only=device_only)
    if out == "no device events parsed":
        print("no events parsed (is this a jax.profiler trace "
              "directory with *.xplane.pb files?)")
        return 1
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
