"""Chrome trace-event JSON analysis — the offline half of the serving
tracer (README "Tracing & debugging" / "Cost attribution &
/debug/profile").

``GET /debug/trace`` serves ``{"traceEvents": [...]}`` documents;
Perfetto graphs them, but a terminal wants numbers. This module gives
the profiler CLI (``python -m paddle_tpu.profiler trace.json``) a
per-lane **span self-time** summary: for every ``(lane, span name)``
pair, how many spans ran, their total duration, and their SELF time —
duration minus the duration of directly nested spans on the same lane
— so "where did the step go" reads straight off a saved capture
(``plan`` vs ``launch`` vs ``host-accept``, or which request lane's
``decode`` dominated) without loading a UI.

Same-lane nesting is the tracer's own invariant (spans on one tid
either nest or are disjoint — pinned by tests/test_tracing.py), so
self-time is well-defined: a sweep with an open-span stack subtracts
each span's duration from its direct parent. Counter events
(``ph:"C"``) and instants carry no duration and are counted but not
timed.

Dependency-free (json + the stdlib), like the tracer that writes these
files.
"""
from __future__ import annotations

import json

from .tracing import TID_ENGINE, TID_GATEWAY, TID_REQ0


def lane_name(tid: int) -> str:
    """Human label for a trace lane (the tracer's fixed tid layout)."""
    if tid == TID_ENGINE:
        return "engine"
    if tid == TID_GATEWAY:
        return "gateway"
    if tid >= TID_REQ0:
        return f"req{tid - TID_REQ0}"
    return f"tid{tid}"


def load_chrome_trace(path: str) -> list:
    """Parse a Chrome trace-event JSON file (the ``/debug/trace``
    document, or a bare event array). Raises ValueError on anything
    unparseable — the CLI's exit-1 contract."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(f"not a readable JSON trace: {e}") from e
    events = doc.get("traceEvents") if isinstance(doc, dict) else doc
    if not isinstance(events, list):
        raise ValueError(
            "no traceEvents array (is this a Chrome trace-event JSON "
            "document, e.g. from GET /debug/trace?)")
    for e in events:
        if not isinstance(e, dict) or "ph" not in e or "ts" not in e:
            raise ValueError(f"malformed trace event: {e!r}")
    return events


def span_self_times(events) -> list:
    """Aggregate X spans per (lane, name): count, total duration and
    self time (total minus direct same-lane children). Returns rows
    sorted by self time descending — the CLI table."""
    by_tid = {}
    for e in events:
        if e.get("ph") == "X":
            by_tid.setdefault(int(e["tid"]), []).append(e)
    agg = {}                       # (tid, name) -> [count, total, self]
    for tid, spans in by_tid.items():
        spans.sort(key=lambda e: (e["ts"], -e.get("dur", 0.0)))
        self_dur = [float(e.get("dur", 0.0)) for e in spans]
        stack = []                 # (end_ts, index) of open spans
        for i, e in enumerate(spans):
            ts, dur = float(e["ts"]), float(e.get("dur", 0.0))
            while stack and ts >= stack[-1][0] - 1e-9:
                stack.pop()
            if stack:              # direct parent loses this child's dur
                self_dur[stack[-1][1]] -= dur
            stack.append((ts + dur, i))
        for e, sd in zip(spans, self_dur):
            key = (tid, e["name"])
            row = agg.setdefault(key, [0, 0.0, 0.0])
            row[0] += 1
            row[1] += float(e.get("dur", 0.0))
            row[2] += max(sd, 0.0)
    rows = [{"lane": lane_name(tid), "tid": tid, "name": name,
             "count": c, "total_ms": round(total / 1e3, 3),
             "self_ms": round(self_us / 1e3, 3),
             "avg_us": round(total / c, 3)}
            for (tid, name), (c, total, self_us) in agg.items()]
    rows.sort(key=lambda r: (-r["self_ms"], -r["total_ms"], r["lane"],
                             r["name"]))
    return rows


def summarize_chrome(path: str, top: int = 10) -> str:
    """Text table over :func:`span_self_times` (the CLI's default
    rendering; ``top=0`` = all rows)."""
    events = load_chrome_trace(path)
    rows = span_self_times(events)
    n_counters = sum(1 for e in events if e.get("ph") == "C")
    n_instants = sum(1 for e in events if e.get("ph") == "i")
    if not rows:
        return "no spans parsed"
    n_spans = sum(r["count"] for r in rows)
    if top:
        rows = rows[:top]
    w = max((len(f"{r['lane']}:{r['name']}") for r in rows), default=4)
    lines = [f"{'span':<{w + 2}}{'count':>7}{'total_ms':>13}"
             f"{'self_ms':>13}{'avg_us':>14}"]
    for r in rows:
        lines.append(f"{r['lane'] + ':' + r['name']:<{w + 2}}"
                     f"{r['count']:>7}{r['total_ms']:>13.3f}"
                     f"{r['self_ms']:>13.3f}{r['avg_us']:>14.3f}")
    lines.append(f"({len(events)} events: {n_spans} spans, "
                 f"{n_instants} instants, {n_counters} counter samples)")
    return "\n".join(lines)
