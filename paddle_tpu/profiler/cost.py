"""Device-boundary cost observatory for the serving stack (README
"Cost attribution & /debug/profile").

PR 9's span tracer says *where wall-time goes*; this module says *what
crosses the host↔device boundary* — the quantity the ROADMAP's
mega-kernel item is gated on ("measured dispatch count per decoded
token drops ≥5×" needs an exact baseline before any optimisation PR can
claim the win, MPK / PAPERS.md). A :class:`CostObservatory` wraps every
jitted program the engine hands out of its shared jit-cache in a
counting facade (:class:`_CountedProgram`) and records, per program
key:

- **dispatches** — exact execution counts (one per facade call; the
  facade IS the call, so the count cannot drift from reality);
- **host→device bytes** — the abstract byte size of every *host-
  resident* argument leaf (numpy arrays / scalars: exactly the leaves
  the runtime must copy to device at dispatch; device-resident
  ``jax.Array`` leaves — weights, the KV pool, carried key state —
  pass by reference and are correctly not charged);
- **device→host bytes** — the abstract byte size of the result leaves
  the engine actually fetches to host (declared per program via
  ``host_out`` at wrap time: the sampled tokens, the tick-0 keys of
  the unified step, the spec key walk — never the functionally-updated
  pool arrays, which are re-adopted device-side);
- **compile events** — ``_cache_size()`` deltas around each call, so a
  retrace is attributed to the program (and the step) that paid it;
- **wall EWMA / total** — per-call wall time on an injectable clock
  (the fault harness's ``VirtualClock`` slots in, making a chaos
  replay's exported accounting byte-identical).

All sizes come from abstract ``shape``/``dtype`` — **no device sync,
no ``.block_until_ready()``, no value reads** — so observing costs
nothing the program wasn't already paying.

Discipline mirrors the tracer's: the observatory is a host-side dict
updated by the single engine-driver thread; scrape-time readers
(``/metrics`` gauges, ``/debug/profile``) read ints under the GIL.
Disabled, every engine instrumentation site reduces to the one
``_co()`` attribute guard — the ≤1.01× property the dispatch bench
pins (DISPATCH_BENCH.json, ``scripts/bench_dispatch.py``).
"""
from __future__ import annotations

import time

import jax
import numpy as np

#: every program kind the serving engine's jit-cache can hand out —
#: the fixed label set of ``serving_dispatches_total{program=...}``
#: (values scrape as 0 until a kind first runs).
PROGRAM_KINDS = ("prefill", "suffix", "psuffix", "decode", "pdecode",
                 "ragged", "mtick", "spec")


def _nbytes(leaf) -> int:
    """Abstract byte size of one pytree leaf — shape × itemsize, no
    device sync (works on jax Arrays, numpy arrays and scalars).

    Always the LOGICAL (global-shape) size: on a tensor-parallel
    engine a replicated host argument is physically broadcast to every
    mesh device and a sharded result leaf is materialized once per
    shard, but the boundary cost attributed here is the one logical
    copy — per-shard leaves must not be double-counted across the mesh
    (the cross-chip traffic TP adds is accounted SEPARATELY, as
    ``serving_collective_bytes_total{dtype}`` via
    :meth:`CostObservatory.record_collective`)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        n = 1
        for d in shape:
            n *= int(d)
        return n * np.dtype(dtype).itemsize
    try:
        return np.dtype(type(leaf)).itemsize
    except TypeError:
        return 8          # opaque python scalar: one word, by convention


def _label(key) -> str:
    """Stable per-program label from a jit-cache key tuple:
    ``("ragged", 8, 72, 1, "jnp")`` → ``"ragged[8,72,1,jnp]"``."""
    if len(key) == 1:
        return str(key[0])
    return f"{key[0]}[{','.join(str(k) for k in key[1:])}]"


# ------------------------------------------------------- jaxpr launch census
#: collective primitives the census bills as cross-chip wire operations
#: (the TP all-reduce pair and every schedule it can lower to)
COLLECTIVE_PRIMITIVES = ("psum", "all_to_all", "all_gather", "ppermute",
                         "reduce_scatter")


def _census_walk(jaxpr):
    """Count ``pallas_call`` and collective eqns in one (open) jaxpr,
    recursively. Returns ``(pallas, collectives, loop_bodies)`` where

    - ``scan`` bodies multiply by the static trip count (a scanned
      layer stack really launches its kernel once per layer);
    - ``while`` bodies count ONCE into the totals (the trip count is a
      runtime value) and additionally append their own PER-ITERATION
      census to ``loop_bodies`` — the multi-tick tail's while body is
      exactly the "launches per decode tick" quantity the mega-kernel
      claim is pinned on;
    - ``cond`` branches contribute their maximum (the worst launch
      count a dispatch can pay);
    - a ``pallas_call``'s inner jaxpr is NEVER recursed into — the
      kernel body's ops run inside the one launch being counted.
    """
    pallas = 0
    coll = 0
    bodies = []

    def _sub(j):
        nonlocal pallas, coll
        p, c, b = _census_walk(j)
        bodies.extend(b)
        return p, c

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "pallas_call":
            pallas += 1
            continue
        if name in COLLECTIVE_PRIMITIVES:
            coll += 1
            continue
        if name == "scan":
            p, c = _sub(eqn.params["jaxpr"].jaxpr)
            n = int(eqn.params["length"])
            pallas += p * n
            coll += c * n
        elif name == "while":
            p, c = _sub(eqn.params["body_jaxpr"].jaxpr)
            bodies.append({"pallas_calls": p, "collectives": c})
            pallas += p
            coll += c
        elif name == "cond":
            per = [_census_walk(br.jaxpr)
                   for br in eqn.params["branches"]]
            for _, _, b in per:
                bodies.extend(b)
            pallas += max(p for p, _, _ in per)
            coll += max(c for _, c, _ in per)
        else:
            # generic containers: pjit, shard_map, custom_{vjp,jvp},
            # remat — recurse every jaxpr-valued param
            for v in eqn.params.values():
                if hasattr(v, "jaxpr") and hasattr(v.jaxpr, "eqns"):
                    p, c = _sub(v.jaxpr)
                    pallas += p
                    coll += c
                elif hasattr(v, "eqns"):
                    p, c = _sub(v)
                    pallas += p
                    coll += c
    return pallas, coll, bodies


def jaxpr_census(fn, *args) -> dict:
    """Launch census of one program: trace ``fn`` over ``args``
    (``jax.make_jaxpr`` — a pure retrace that does NOT touch the pjit
    executable cache, so compile-once pins are undisturbed) and count
    the device-side launch structure. Returns::

        {"pallas_calls": int,     # total, scan bodies × trip count
         "collectives": int,      # psum/all_to_all/all_gather/ppermute
         "loop_bodies": [{"pallas_calls": n, "collectives": n}, ...]}

    ``loop_bodies`` holds the PER-ITERATION census of each
    ``while_loop`` body — for the serving multi-tick program that is
    the per-decode-tick launch count: O(num_layers) for the scanned
    baseline, exactly 1 for the fused whole-tick kernel (README
    "One-kernel decode")."""
    closed = jax.make_jaxpr(fn)(*args)
    pallas, coll, bodies = _census_walk(closed.jaxpr)
    return {"pallas_calls": pallas, "collectives": coll,
            "loop_bodies": bodies}


class CostObservatory:
    """Exact per-program dispatch / transfer / compile accounting.

    One observatory is OWNED BY THE GATEWAY and installed on every
    engine incarnation (``engine.cost``), so its counts are monotonic
    across crash-recovery rebuilds — the same ownership rule as the
    tracer and the ``serving_preemptions_total`` base. ``clock`` is any
    zero-arg monotonic-seconds callable (default ``time.perf_counter``;
    tests and the chaos bench pass a
    :class:`~paddle_tpu.serving.faults.VirtualClock`, under which the
    exported accounting replays byte-identically).

    The engine guards every touch on :attr:`enabled` through its
    ``_co()`` helper — one attribute check when disabled, the same
    discipline as the tracer's ``_tr()``.
    """

    def __init__(self, clock=None, ewma_alpha=0.2):
        self.clock = clock if clock is not None else time.perf_counter
        self.enabled = True
        self.ewma_alpha = float(ewma_alpha)
        # label -> per-program record (insertion-ordered: deterministic
        # under a deterministic workload, so export() is byte-stable)
        self.programs = {}
        # step-phase attribution (the engine names the current phase:
        # admit | plan | launch | host-accept): where dispatches land
        self.phases = {}
        self._phase = None
        self.totals = {"dispatches": 0, "h2d_bytes": 0, "d2h_bytes": 0,
                       "compiles": 0, "wall_s": 0.0}
        # cross-chip collective traffic by wire dtype (tensor-parallel
        # engines; README "Tensor-parallel serving") — deliberately a
        # SEPARATE ledger from h2d/d2h: all-reduce bytes never cross
        # the host boundary, and folding them into transfer totals
        # would corrupt the banked dispatch-bench baselines
        self.collectives = {}
        # KV-tier traffic by direction (host-RAM spill tier; README
        # "Tiered KV prefix cache") — the same separate-ledger rule as
        # collectives: spill/readmit bytes ARE host-boundary transfers,
        # but they are cache-plane traffic, not per-program compute
        # I/O, and folding them into the per-program h2d/d2h records
        # would corrupt the banked DISPATCH_BENCH.json baselines.
        # Directions: "d2h" (spill), "h2d" (readmit), "peer" (fleet
        # host-to-host transfer in).
        self.tiers = {}
        # label -> jaxpr launch census (one per program, recorded
        # lazily on the program's FIRST dispatch through the counting
        # facade — the same chokepoint as every other column, so the
        # in-program launch structure of exactly the programs that ran
        # is what exports)
        self.censuses = {}

    # ------------------------------------------------------------- control
    def enable(self):
        self.enabled = True
        return self

    def disable(self):
        self.enabled = False
        return self

    def set_phase(self, phase):
        """Name the step phase subsequent dispatches are attributed to
        (None between steps)."""
        self._phase = phase

    # ------------------------------------------------------------ recording
    def wrap(self, key, fn, host_out=()):
        """Counting facade over one jitted program handed out of the
        jit-cache. ``key`` is the cache key (its first element is the
        program kind); ``host_out`` names the result indices the engine
        fetches to host — the exact device→host surface."""
        return _CountedProgram(self, _label(key), str(key[0]), fn,
                               tuple(host_out))

    def _record(self, label, kind, args, out, host_out, compiles, dt):
        h2d = sum(_nbytes(leaf)
                  for leaf in jax.tree_util.tree_leaves(args)
                  if not isinstance(leaf, jax.Array))
        d2h = sum(_nbytes(leaf) for i in host_out
                  for leaf in jax.tree_util.tree_leaves(out[i]))
        rec = self.programs.get(label)
        if rec is None:
            rec = {"kind": kind, "calls": 0, "h2d_bytes": 0,
                   "d2h_bytes": 0, "compiles": 0, "wall_s": 0.0,
                   "wall_ewma_s": None}
            self.programs[label] = rec
        rec["calls"] += 1
        rec["h2d_bytes"] += h2d
        rec["d2h_bytes"] += d2h
        rec["compiles"] += compiles
        rec["wall_s"] += dt
        rec["wall_ewma_s"] = dt if rec["wall_ewma_s"] is None else \
            (1 - self.ewma_alpha) * rec["wall_ewma_s"] + self.ewma_alpha * dt
        t = self.totals
        t["dispatches"] += 1
        t["h2d_bytes"] += h2d
        t["d2h_bytes"] += d2h
        t["compiles"] += compiles
        t["wall_s"] += dt
        ph = self.phases.get(self._phase)
        if ph is None:
            ph = {"dispatches": 0, "h2d_bytes": 0, "d2h_bytes": 0,
                  "wall_s": 0.0}
            self.phases[self._phase] = ph
        ph["dispatches"] += 1
        ph["h2d_bytes"] += h2d
        ph["d2h_bytes"] += d2h
        ph["wall_s"] += dt

    def record_census(self, label, fn, args):
        """Record one program's jaxpr launch census (idempotent per
        label; called by the counting facade on the program's first
        dispatch). The retrace is pure — the pjit executable cache is
        untouched — but it is a retrace, so it runs ONCE per program
        label, never per call. A program whose trace fails under
        ``make_jaxpr`` records ``None`` rather than killing the serving
        step that triggered the census."""
        if label in self.censuses:
            return
        try:
            self.censuses[label] = jaxpr_census(fn, *args)
        except Exception:            # noqa: BLE001 — census is advisory
            self.censuses[label] = None

    def record_collective(self, dtype, ops, nbytes):
        """Account one sharded launch's cross-chip all-reduce traffic:
        ``ops`` collective operations moving ``nbytes`` wire bytes per
        device, under wire-dtype label ``dtype`` (``fp`` | ``int8``).
        Shape-derived by the caller (the engine's
        ``_record_collectives``) — exact and deterministic, no network
        probe. The ``serving_collective_bytes_total{dtype}`` counter
        and the ``/debug/profile`` collectives section read this."""
        rec = self.collectives.get(dtype)
        if rec is None:
            rec = {"ops": 0, "bytes": 0}
            self.collectives[dtype] = rec
        rec["ops"] += int(ops)
        rec["bytes"] += int(nbytes)

    def collective_bytes(self, dtype) -> int:
        """Total wire bytes recorded under one collective dtype (0 for
        a dtype that never ran — tp=1 engines scrape explicit zeros)."""
        rec = self.collectives.get(dtype)
        return int(rec["bytes"]) if rec else 0

    def record_tier(self, direction, blocks, nbytes):
        """Account KV-tier cache-plane traffic: ``blocks`` pool blocks
        moving ``nbytes`` bytes under ``direction`` (``d2h`` spill |
        ``h2d`` readmit | ``peer`` fleet transfer in). Shape-derived by
        the caller (the prefix cache's spill/readmit paths) — exact and
        deterministic. The ``serving_tier_bytes_total{direction}``
        counter and the ``/debug/profile`` tiers section read this."""
        rec = self.tiers.get(direction)
        if rec is None:
            rec = {"blocks": 0, "bytes": 0}
            self.tiers[direction] = rec
        rec["blocks"] += int(blocks)
        rec["bytes"] += int(nbytes)

    def tier_bytes(self, direction) -> int:
        """Total bytes recorded under one tier direction (0 for a
        direction that never moved — tierless engines scrape explicit
        zeros)."""
        rec = self.tiers.get(direction)
        return int(rec["bytes"]) if rec else 0

    # -------------------------------------------------------------- reading
    def kind_calls(self, kind) -> int:
        """Total dispatches of one program kind (the
        ``serving_dispatches_total{program}`` series). ``list()``
        snapshots the dict before iterating: scrapes run on HTTP
        handler threads while the driver may be inserting a new
        program label, and bare dict iteration would raise
        "changed size during iteration"."""
        return sum(rec["calls"] for rec in list(self.programs.values())
                   if rec["kind"] == kind)

    def snapshot(self) -> dict:
        """Cheap totals copy — the engine's per-step delta base."""
        return dict(self.totals)

    def delta(self, base) -> dict:
        """Totals accrued since ``base`` (a prior :meth:`snapshot`)."""
        return {k: self.totals[k] - base[k]
                for k in ("dispatches", "h2d_bytes", "d2h_bytes",
                          "compiles")}

    def snapshot_full(self) -> dict:
        """Deep copy of the whole accounting — the base (or frozen end)
        of a step-bounded ``/debug/profile`` capture window. ``list()``
        snapshots each dict before iterating (see :meth:`kind_calls`);
        concurrent driver updates can tear a single in-flight record,
        never crash."""
        return {"programs": {k: dict(v)
                             for k, v in list(self.programs.items())},
                "phases": {k: dict(v)
                           for k, v in list(self.phases.items())},
                "totals": dict(self.totals),
                "collectives": {k: dict(v)
                                for k, v in list(
                                    self.collectives.items())},
                "tiers": {k: dict(v)
                          for k, v in list(self.tiers.items())},
                "censuses": {k: (dict(v) if v is not None else None)
                             for k, v in list(self.censuses.items())}}

    def export(self, base=None, at=None) -> dict:
        """The cost-attribution document: aggregate, the delta since
        ``base``, or the ``base``→``at`` window (both prior
        :meth:`snapshot_full` snapshots — ``at`` is how a step-bounded
        capture freezes its END at the exact step boundary instead of
        leaking later steps into the window). Deterministic for a
        deterministic workload: insertion-ordered programs, rounded
        floats, no wall-clock reads."""
        state = at if at is not None else self.snapshot_full()
        base_p = (base or {}).get("programs", {})
        base_t = (base or {}).get("totals", {})
        base_ph = (base or {}).get("phases", {})
        wall_total = state["totals"]["wall_s"] - base_t.get("wall_s", 0.0)
        programs = []
        for label, rec in state["programs"].items():
            b = base_p.get(label, {})
            calls = rec["calls"] - b.get("calls", 0)
            if calls <= 0:
                continue
            wall = rec["wall_s"] - b.get("wall_s", 0.0)
            entry = {
                "program": label, "kind": rec["kind"], "calls": calls,
                "h2d_bytes": rec["h2d_bytes"] - b.get("h2d_bytes", 0),
                "d2h_bytes": rec["d2h_bytes"] - b.get("d2h_bytes", 0),
                "compiles": rec["compiles"] - b.get("compiles", 0),
                "wall_s": round(wall, 9),
                "wall_ewma_s": round(rec["wall_ewma_s"] or 0.0, 9),
                "share_of_wall": round(wall / wall_total, 6)
                if wall_total > 0 else 0.0,
            }
            census = state.get("censuses", {}).get(label)
            if census is not None:
                entry["census"] = census
            programs.append(entry)
        programs.sort(key=lambda r: (-r["wall_s"], -r["calls"],
                                     r["program"]))
        phases = {}
        for name, rec in state["phases"].items():
            b = base_ph.get(name, {})
            d = rec["dispatches"] - b.get("dispatches", 0)
            if d <= 0:
                continue
            phases[str(name)] = {
                "dispatches": d,
                "h2d_bytes": rec["h2d_bytes"] - b.get("h2d_bytes", 0),
                "d2h_bytes": rec["d2h_bytes"] - b.get("d2h_bytes", 0),
                "wall_s": round(rec["wall_s"] - b.get("wall_s", 0.0), 9),
            }
        totals = {k: state["totals"][k] - base_t.get(k, 0)
                  for k in ("dispatches", "h2d_bytes", "d2h_bytes",
                            "compiles")}
        totals["wall_s"] = round(wall_total, 9)
        base_c = (base or {}).get("collectives", {})
        collectives = {}
        for dtype, rec in state.get("collectives", {}).items():
            b = base_c.get(dtype, {})
            d_ops = rec["ops"] - b.get("ops", 0)
            d_bytes = rec["bytes"] - b.get("bytes", 0)
            if d_ops <= 0 and d_bytes <= 0:
                continue
            collectives[dtype] = {"ops": d_ops, "bytes": d_bytes}
        base_tr = (base or {}).get("tiers", {})
        tiers = {}
        for direction, rec in state.get("tiers", {}).items():
            b = base_tr.get(direction, {})
            d_blocks = rec["blocks"] - b.get("blocks", 0)
            d_bytes = rec["bytes"] - b.get("bytes", 0)
            if d_blocks <= 0 and d_bytes <= 0:
                continue
            tiers[direction] = {"blocks": d_blocks, "bytes": d_bytes}
        return {"programs": programs, "phases": phases, "totals": totals,
                "collectives": collectives, "tiers": tiers}


class _CountedProgram:
    """The counting facade: calls the wrapped jitted program and
    records exact dispatch/byte/compile/wall accounting. Handed out
    fresh per accessor call (the jit-cache keeps the RAW jitted fn, so
    ``decode_compilations()`` / shared-cache semantics are
    untouched)."""

    __slots__ = ("_co", "_label", "_kind", "_fn", "_host_out")

    def __init__(self, co, label, kind, fn, host_out):
        self._co = co
        self._label = label
        self._kind = kind
        self._fn = fn
        self._host_out = host_out

    def _cache_size(self):
        # transparent to compile-count assertions made on a handout
        return self._fn._cache_size()

    def __call__(self, *args):
        co = self._co
        fn = self._fn
        t0 = co.clock()
        c0 = fn._cache_size()
        out = fn(*args)
        co._record(self._label, self._kind, args, out, self._host_out,
                   fn._cache_size() - c0, co.clock() - t0)
        # jaxpr launch census, once per program label (idempotent):
        # the facade call IS the chokepoint every jit-cache handout
        # funnels through, so the census covers exactly the programs
        # that dispatched — and the retrace it costs is paid once,
        # after the real call, never on the steady-state path
        if self._label not in co.censuses:
            co.record_census(self._label, fn, args)
        return out
