"""First-class training metrics: tokens/sec/chip and MFU (SURVEY.md §5.5 —
the north-star metric must be a training-loop output).

MFU = achieved model FLOP/s / peak chip FLOP/s. The FLOP formula is stated
explicitly (BASELINE.md requirement): ``6 * n_params * tokens`` for
transformer training (fwd+bwd), optionally + attention term
``12 * n_layers * hidden * seq`` per token when ``include_attention``.

Also here: a dependency-free Prometheus text-exposition layer
(:class:`Counter` / :class:`Gauge` / :class:`Histogram` collected by a
:class:`MetricsRegistry`) — the serving gateway's ``GET /metrics``
endpoint renders through it, and anything else (training loops, bench
scripts) can register series the same way.
"""
from __future__ import annotations

import threading
import time

import jax

# bf16 peak FLOP/s per chip by TPU generation
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "trillium": 918e12,
}


def peak_flops_per_chip(device=None) -> float:
    device = device or jax.devices()[0]
    kind = device.device_kind.lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return 197e12  # conservative default


def transformer_flops_per_token(n_params, n_layers=0, hidden=0, seq_len=0,
                                include_attention=False) -> float:
    f = 6.0 * n_params
    if include_attention and n_layers and hidden and seq_len:
        f += 12.0 * n_layers * hidden * seq_len
    return f


class MFUMeter:
    """Accumulates step timings and reports tokens/s/chip + MFU."""

    def __init__(self, flops_per_token=None, n_params=None, n_chips=None,
                 include_attention=False, n_layers=0, hidden=0, seq_len=0):
        if flops_per_token is None:
            flops_per_token = transformer_flops_per_token(
                n_params, n_layers, hidden, seq_len, include_attention)
        self.flops_per_token = flops_per_token
        self.n_chips = n_chips or jax.device_count()
        self.peak = peak_flops_per_chip()
        self.reset()

    def reset(self):
        self._tokens = 0
        self._time = 0.0
        self._t0 = None

    def start_step(self):
        self._t0 = time.perf_counter()

    def end_step(self, tokens):
        self._time += time.perf_counter() - self._t0
        self._tokens += tokens

    @property
    def tokens_per_sec(self):
        return self._tokens / self._time if self._time else 0.0

    @property
    def tokens_per_sec_per_chip(self):
        return self.tokens_per_sec / self.n_chips

    @property
    def mfu(self):
        return (self.tokens_per_sec * self.flops_per_token /
                (self.n_chips * self.peak))

    def report(self):
        return {
            "tokens_per_sec": self.tokens_per_sec,
            "tokens_per_sec_per_chip": self.tokens_per_sec_per_chip,
            "mfu": self.mfu,
            "flop_formula": f"{self.flops_per_token:.3e} FLOP/token",
            "peak_flops_per_chip": self.peak,
            "n_chips": self.n_chips,
        }


class DecodeMeter:
    """Decode-throughput meter (SURVEY §3.5 / L7): tokens/sec and ms/token
    for autoregressive generation, per-phase (prefill vs decode).

    Decode FLOPs/token ≈ 2·N (forward only), so ``mbu`` reports the
    memory-bandwidth-bound utilization proxy instead of MFU: decode is
    weight-streaming-bound, tokens/s · bytes_per_param / HBM_BW.
    """

    def __init__(self, n_params=None, n_chips=None, bytes_per_param=2.0,
                 hbm_bw_per_chip=8.1e11):
        self.n_params = n_params
        self.n_chips = n_chips or jax.device_count()
        self.bytes_per_param = bytes_per_param
        self.hbm_bw = hbm_bw_per_chip
        self.reset()

    def reset(self):
        self._prefill_tokens = 0
        self._prefill_time = 0.0
        self._decode_tokens = 0
        self._decode_time = 0.0
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def end_prefill(self, tokens):
        self._prefill_time += time.perf_counter() - self._t0
        self._prefill_tokens += tokens

    def end_decode(self, tokens=1):
        self._decode_time += time.perf_counter() - self._t0
        self._decode_tokens += tokens

    @property
    def decode_tokens_per_sec(self):
        return (self._decode_tokens / self._decode_time
                if self._decode_time else 0.0)

    @property
    def prefill_tokens_per_sec(self):
        return (self._prefill_tokens / self._prefill_time
                if self._prefill_time else 0.0)

    def report(self):
        out = {
            "prefill_tokens_per_sec": self.prefill_tokens_per_sec,
            "decode_tokens_per_sec": self.decode_tokens_per_sec,
            "decode_ms_per_token": (1000.0 / self.decode_tokens_per_sec
                                    if self.decode_tokens_per_sec else 0.0),
            "n_chips": self.n_chips,
        }
        if self.n_params:
            bw = (self.decode_tokens_per_sec * self.n_params *
                  self.bytes_per_param)
            out["decode_mbu"] = bw / (self.n_chips * self.hbm_bw)
        return out


# --------------------------------------------------- prometheus exposition
# Text format per the Prometheus exposition spec v0.0.4: one HELP + TYPE
# comment per metric family, then one sample line per (label set), with
# histograms expanded to cumulative ``_bucket{le=...}`` series plus
# ``_sum``/``_count``. No client_golang-style background machinery — a
# scrape renders the current values under one registry lock.

def _escape_help(s):
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s):
    return (str(s).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _fmt_value(v):
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _label_str(labels):
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class _Metric:
    """Base: one metric family, keyed by label values. Thread-safe —
    the serving gateway increments from its driver thread while HTTP
    handler threads render scrapes."""

    kind = None

    def __init__(self, name, help=""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series = {}  # label-items tuple -> value/state

    def _key(self, labels):
        return tuple(sorted(labels.items()))

    def expose(self):
        """Exposition lines for this family (HELP/TYPE + samples).
        Samples render UNDER the lock: a histogram's counts/sum/count
        must come from one consistent instant or a concurrent observe()
        can produce a non-cumulative (corrupt-looking) scrape."""
        lines = [f"# HELP {self.name} {_escape_help(self.help)}",
                 f"# TYPE {self.name} {self.kind}"]
        with self._lock:
            for key, state in sorted(self._series.items()):
                lines.extend(self._sample_lines(dict(key), state))
        return lines

    def _sample_lines(self, labels, state):
        raise NotImplementedError


class Counter(_Metric):
    """Monotonically increasing value (e.g. total tokens generated).

    ``set_fn`` registers a callable sampled at scrape time, for counters
    whose source of truth is an existing monotonic count elsewhere (the
    serving gateway points the prefix-cache hit/miss/eviction counters
    at the cache's own stats dict this way). The callable must be
    monotonically non-decreasing — Prometheus counter semantics — and a
    series is either incremented or fn-backed, never both."""

    kind = "counter"

    def inc(self, value=1, **labels):
        if value < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = self._key(labels)
        with self._lock:
            cur = self._series.get(key, 0)
            if callable(cur):
                raise ValueError(
                    f"counter {self.name} series is scrape-time (set_fn); "
                    f"inc() would fork its source of truth")
            self._series[key] = cur + value

    def set_fn(self, fn, **labels):
        key = self._key(labels)
        with self._lock:
            cur = self._series.get(key)
            if cur is not None and not callable(cur) and cur != 0:
                # the registry dedupes by name, so a second component
                # can reach a counter someone else already inc()'d;
                # silently replacing its accumulated count would scrape
                # as a spurious counter reset
                raise ValueError(
                    f"counter {self.name} series already holds "
                    f"incremented value {cur}; set_fn() would discard it")
            self._series[key] = fn

    def value(self, **labels):
        with self._lock:
            v = self._series.get(self._key(labels), 0)
        return v() if callable(v) else v

    def _sample_lines(self, labels, state):
        v = state() if callable(state) else state
        return [f"{self.name}{_label_str(labels)} {_fmt_value(v)}"]


class Gauge(_Metric):
    """Point-in-time value (e.g. queue depth, active slots). ``set_fn``
    registers a callable sampled at scrape time so the gauge can't go
    stale between updates."""

    kind = "gauge"

    def set(self, value, **labels):
        with self._lock:
            self._series[self._key(labels)] = value

    def inc(self, value=1, **labels):
        key = self._key(labels)
        with self._lock:
            cur = self._series.get(key, 0)
            self._series[key] = (cur() if callable(cur) else cur) + value

    def dec(self, value=1, **labels):
        self.inc(-value, **labels)

    def set_fn(self, fn, **labels):
        with self._lock:
            self._series[self._key(labels)] = fn

    def value(self, **labels):
        with self._lock:
            v = self._series.get(self._key(labels), 0)
        return v() if callable(v) else v

    def _sample_lines(self, labels, state):
        v = state() if callable(state) else state
        return [f"{self.name}{_label_str(labels)} {_fmt_value(v)}"]


# request latencies span ~ms (CPU tiny model) to minutes (long decodes)
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0, 60.0, 120.0)

# time-to-first-token ladder (``serving_ttft_seconds``): TTFT is the
# latency chunked prefill exists to bound, so its low end needs sub-ms
# resolution (a CPU tiny-model decode tick is ~1 ms; a healthy TTFT on
# real chips is tens of ms) while the tail still distinguishes a
# 1 s stall from a 10 s one.
TTFT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

# engine-step duration ladder (``serving_step_duration_seconds``): one
# unified serving step is ~sub-ms on real chips and tens of ms on the
# CPU tiny models; the top distinguishes a chunk-heavy 1 s step from a
# wedged 10 s one. These observations are the same signal the engine's
# headroom EWMAs (the adaptive chunk budget) read.
STEP_BUCKETS = (0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                0.1, 0.25, 0.5, 1.0, 2.5, 10.0)

# time-per-output-token ladder (``serving_tpot_seconds``): the
# steady-state decode cadence one request observes — (finish - first
# token) / (tokens - 1). Sub-ms resolution at the bottom (a healthy
# TPOT on real chips is single-digit ms; the CPU tiny models sit at
# ~1-30 ms), a tail that separates a 100 ms-per-token crawl from a
# seconds-per-token stall. This histogram is the SLO substrate the
# multi-tenant scheduler's TPOT targets will read (ROADMAP item b).
TPOT_BUCKETS = (0.0002, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                0.1, 0.25, 0.5, 1.0, 2.5, 10.0)

# queue-wait ladder (``serving_queue_wait_seconds``): submit-to-slot
# latency — the admission-control half of TTFT (TTFT = queue wait +
# prefill). Same sub-ms-to-tens-of-seconds span as the TTFT ladder: an
# uncontended admission is instant, a saturated waiting room is
# seconds, and the top separates "waited a while" from "starved".
QUEUE_WAIT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                      0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

# speculative-decode acceptance-length ladder
# (``serving_spec_accept_length``): tokens emitted per verify span —
# integer-valued, 1 = nothing accepted (the guaranteed correction
# token), spec_k + 1 = a fully accepted draft. Whole-number bounds so
# each count lands in its own bucket for any practical spec_k.
SPEC_ACCEPT_BUCKETS = (1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 8.0, 12.0, 16.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (latency distributions).

    :meth:`quantile` estimates order statistics from the bucket counts
    (the ``histogram_quantile``-style interpolation) — good enough for
    p95 acceptance gates (scripts/bench_chunked.py) without recording
    raw observations.
    """

    kind = "histogram"

    def __init__(self, name, help="", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help)
        b = sorted(float(x) for x in buckets)
        if not b:
            raise ValueError(f"histogram {name} needs at least one bucket")
        self.buckets = tuple(b)

    def observe(self, value, **labels):
        key = self._key(labels)
        with self._lock:
            state = self._series.get(key)
            if state is None:
                state = {"counts": [0] * len(self.buckets),
                         "sum": 0.0, "count": 0}
                self._series[key] = state
            for i, ub in enumerate(self.buckets):
                if value <= ub:
                    state["counts"][i] += 1
            state["sum"] += value
            state["count"] += 1

    def quantile(self, q, **labels):
        """Estimate the ``q``-quantile (0 < q <= 1) from the bucket
        counts, Prometheus ``histogram_quantile`` style: find the
        bucket the target rank lands in and interpolate linearly inside
        it (lower edge = previous bucket bound, 0 below the first).
        Observations above the last finite bucket clamp to that bound —
        same behavior as PromQL, and the reason the ladder's top bucket
        should sit above any latency you care to distinguish. Returns
        0.0 for an empty series."""
        if not 0.0 < q <= 1.0:
            raise ValueError(f"quantile q must be in (0, 1], got {q}")
        with self._lock:
            state = self._series.get(self._key(labels))
            if state is None or not state["count"]:
                return 0.0
            counts = list(state["counts"])
            total = state["count"]
        target = q * total
        prev_count, lower = 0, 0.0
        for ub, c in zip(self.buckets, counts):
            if c >= target:
                if c == prev_count:   # empty bucket can't be hit; guard
                    return ub
                frac = (target - prev_count) / (c - prev_count)
                return lower + (ub - lower) * frac
            prev_count, lower = c, ub
        return self.buckets[-1]       # rank beyond the last finite bound

    def _sample_lines(self, labels, state):
        lines = []
        for ub, c in zip(self.buckets, state["counts"]):
            bl = dict(labels, le=_fmt_value(ub))
            lines.append(f"{self.name}_bucket{_label_str(bl)} {c}")
        bl = dict(labels, le="+Inf")
        lines.append(f"{self.name}_bucket{_label_str(bl)} {state['count']}")
        lines.append(f"{self.name}_sum{_label_str(labels)} "
                     f"{_fmt_value(state['sum'])}")
        lines.append(f"{self.name}_count{_label_str(labels)} "
                     f"{state['count']}")
        return lines


class _BoundMetric:
    """A metric family viewed through a fixed label set: every
    operation merges the bound labels into its call — the mechanism
    behind the fleet's ``replica=\"i\"`` series (one shared registry,
    N gateways, no series collisions). Explicit per-call labels win on
    a key clash (they are more specific)."""

    __slots__ = ("_metric", "_labels")

    def __init__(self, metric, labels):
        self._metric = metric
        self._labels = dict(labels)

    @property
    def name(self):
        return self._metric.name

    @property
    def buckets(self):
        return self._metric.buckets

    def _merge(self, labels):
        return {**self._labels, **labels}

    def inc(self, value=1, **labels):
        return self._metric.inc(value, **self._merge(labels))

    def dec(self, value=1, **labels):
        return self._metric.dec(value, **self._merge(labels))

    def set(self, value, **labels):
        return self._metric.set(value, **self._merge(labels))

    def set_fn(self, fn, **labels):
        return self._metric.set_fn(fn, **self._merge(labels))

    def observe(self, value, **labels):
        return self._metric.observe(value, **self._merge(labels))

    def value(self, **labels):
        return self._metric.value(**self._merge(labels))

    def quantile(self, q, **labels):
        return self._metric.quantile(q, **self._merge(labels))


class _LabeledRegistry:
    """A :class:`MetricsRegistry` view that stamps every series
    registered through it with fixed labels (see
    :meth:`MetricsRegistry.labeled`). Families are still created in —
    and rendered by — the underlying registry, so N views over one
    registry expose one coherent ``/metrics`` document with each
    component's series distinguished by its labels."""

    def __init__(self, base, labels):
        self._base = base
        self._labels = dict(labels)

    def counter(self, name, help=""):
        return _BoundMetric(self._base.counter(name, help), self._labels)

    def gauge(self, name, help=""):
        return _BoundMetric(self._base.gauge(name, help), self._labels)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS):
        return _BoundMetric(self._base.histogram(name, help,
                                                 buckets=buckets),
                            self._labels)

    def labeled(self, **labels):
        return _LabeledRegistry(self._base, {**self._labels, **labels})

    def render(self) -> str:
        return self._base.render()


class MetricsRegistry:
    """Named collection of metric families; ``render()`` is the whole
    ``GET /metrics`` response body."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}

    def _register(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered as {m.kind}")
                return m
            m = cls(name, help, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help="") -> Counter:
        return self._register(Counter, name, help)

    def gauge(self, name, help="") -> Gauge:
        return self._register(Gauge, name, help)

    def histogram(self, name, help="",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._register(Histogram, name, help, buckets=buckets)

    def labeled(self, **labels) -> _LabeledRegistry:
        """A view of this registry that stamps every series registered
        through it with ``labels`` — how the engine-fleet gives each
        replica's gateway its own ``replica=\"i\"`` series in ONE
        shared registry (one ``/metrics`` scrape covers the fleet, and
        each replica's carried counter bases stay per-replica, so any
        single replica rebuild keeps every series monotonic)."""
        return _LabeledRegistry(self, labels)

    def render(self) -> str:
        with self._lock:
            fams = [self._metrics[k] for k in sorted(self._metrics)]
        lines = []
        for m in fams:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"
