"""First-class training metrics: tokens/sec/chip and MFU (SURVEY.md §5.5 —
the north-star metric must be a training-loop output).

MFU = achieved model FLOP/s / peak chip FLOP/s. The FLOP formula is stated
explicitly (BASELINE.md requirement): ``6 * n_params * tokens`` for
transformer training (fwd+bwd), optionally + attention term
``12 * n_layers * hidden * seq`` per token when ``include_attention``.
"""
from __future__ import annotations

import time

import jax

# bf16 peak FLOP/s per chip by TPU generation
PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5litepod": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
    "trillium": 918e12,
}


def peak_flops_per_chip(device=None) -> float:
    device = device or jax.devices()[0]
    kind = device.device_kind.lower()
    for k, v in PEAK_FLOPS.items():
        if k in kind:
            return v
    return 197e12  # conservative default


def transformer_flops_per_token(n_params, n_layers=0, hidden=0, seq_len=0,
                                include_attention=False) -> float:
    f = 6.0 * n_params
    if include_attention and n_layers and hidden and seq_len:
        f += 12.0 * n_layers * hidden * seq_len
    return f


class MFUMeter:
    """Accumulates step timings and reports tokens/s/chip + MFU."""

    def __init__(self, flops_per_token=None, n_params=None, n_chips=None,
                 include_attention=False, n_layers=0, hidden=0, seq_len=0):
        if flops_per_token is None:
            flops_per_token = transformer_flops_per_token(
                n_params, n_layers, hidden, seq_len, include_attention)
        self.flops_per_token = flops_per_token
        self.n_chips = n_chips or jax.device_count()
        self.peak = peak_flops_per_chip()
        self.reset()

    def reset(self):
        self._tokens = 0
        self._time = 0.0
        self._t0 = None

    def start_step(self):
        self._t0 = time.perf_counter()

    def end_step(self, tokens):
        self._time += time.perf_counter() - self._t0
        self._tokens += tokens

    @property
    def tokens_per_sec(self):
        return self._tokens / self._time if self._time else 0.0

    @property
    def tokens_per_sec_per_chip(self):
        return self.tokens_per_sec / self.n_chips

    @property
    def mfu(self):
        return (self.tokens_per_sec * self.flops_per_token /
                (self.n_chips * self.peak))

    def report(self):
        return {
            "tokens_per_sec": self.tokens_per_sec,
            "tokens_per_sec_per_chip": self.tokens_per_sec_per_chip,
            "mfu": self.mfu,
            "flop_formula": f"{self.flops_per_token:.3e} FLOP/token",
            "peak_flops_per_chip": self.peak,
            "n_chips": self.n_chips,
        }


class DecodeMeter:
    """Decode-throughput meter (SURVEY §3.5 / L7): tokens/sec and ms/token
    for autoregressive generation, per-phase (prefill vs decode).

    Decode FLOPs/token ≈ 2·N (forward only), so ``mbu`` reports the
    memory-bandwidth-bound utilization proxy instead of MFU: decode is
    weight-streaming-bound, tokens/s · bytes_per_param / HBM_BW.
    """

    def __init__(self, n_params=None, n_chips=None, bytes_per_param=2.0,
                 hbm_bw_per_chip=8.1e11):
        self.n_params = n_params
        self.n_chips = n_chips or jax.device_count()
        self.bytes_per_param = bytes_per_param
        self.hbm_bw = hbm_bw_per_chip
        self.reset()

    def reset(self):
        self._prefill_tokens = 0
        self._prefill_time = 0.0
        self._decode_tokens = 0
        self._decode_time = 0.0
        self._t0 = None

    def start(self):
        self._t0 = time.perf_counter()

    def end_prefill(self, tokens):
        self._prefill_time += time.perf_counter() - self._t0
        self._prefill_tokens += tokens

    def end_decode(self, tokens=1):
        self._decode_time += time.perf_counter() - self._t0
        self._decode_tokens += tokens

    @property
    def decode_tokens_per_sec(self):
        return (self._decode_tokens / self._decode_time
                if self._decode_time else 0.0)

    @property
    def prefill_tokens_per_sec(self):
        return (self._prefill_tokens / self._prefill_time
                if self._prefill_time else 0.0)

    def report(self):
        out = {
            "prefill_tokens_per_sec": self.prefill_tokens_per_sec,
            "decode_tokens_per_sec": self.decode_tokens_per_sec,
            "decode_ms_per_token": (1000.0 / self.decode_tokens_per_sec
                                    if self.decode_tokens_per_sec else 0.0),
            "n_chips": self.n_chips,
        }
        if self.n_params:
            bw = (self.decode_tokens_per_sec * self.n_params *
                  self.bytes_per_param)
            out["decode_mbu"] = bw / (self.n_chips * self.hbm_bw)
        return out
