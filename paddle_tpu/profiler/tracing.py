"""Dependency-free span tracer for the serving stack (README "Tracing &
debugging").

The serving column's aggregate counters (``/metrics``) say *that* a
request was slow; this module says *where* its time went. A
:class:`SpanTracer` records spans and instant events into a bounded
host-side ring buffer and renders them as Chrome trace-event JSON —
the ``{"traceEvents": [...]}`` format Perfetto / ``chrome://tracing``
load directly — so one capture shows the whole request lifecycle
(``queued → prefill_chunk[i] → decode → finished``), the engine's
per-step phases (``plan / launch / host-accept / donate``) and the
gateway supervisor's fault/rebuild/recovery instants on one timeline.

Design constraints, in order:

- **Zero-cost when off.** Production engines run with tracing disabled;
  every instrumentation site guards on one attribute check
  (``tracer.enabled``) before building any args, and the recording
  methods themselves return immediately when disabled. Nothing is
  allocated, no clock is read.
- **Deterministic.** The clock is injectable (the fault harness's
  :class:`~paddle_tpu.serving.faults.VirtualClock` slots straight in),
  timestamps are relative to a capture epoch, the pid is a constant,
  and request identities are normalized to dense first-seen indices —
  so a chaos replay under a virtual clock produces a byte-identical
  trace (pinned by tests/test_tracing.py).
- **Bounded.** The buffer is a ring of ``capacity`` events; overflow
  drops the OLDEST events and counts them (``dropped``), so a
  long-running server with persistent tracing holds a sliding window,
  never an unbounded log.
- **Dependency-free and host-only.** Plain dicts and a lock; no device
  work, no new packages. The tracer never touches jax — it is safe to
  import anywhere, including the HTTP layer.

Event vocabulary (Chrome trace phases): spans are COMPLETE events
(``ph="X"`` with ``ts``/``dur`` in microseconds) — simpler to validate
than begin/end pairs and immune to unbalanced nesting when the ring
drops events; instants are ``ph="i"`` with thread scope; counter
tracks are ``ph="C"`` events whose ``args`` carry one sample per
series — Perfetto renders them as stacked graphs alongside the spans,
which is how the cost observatory's dispatches/step, transfer
bytes/step and KV-pool occupancy ride the same timeline as PR 9's
phases. Every event carries ``name/ph/ts/pid/tid`` (the schema tests
pin exactly this); ``args`` holds the payload (prefix-hit tokens,
accepted-draft lengths, fault kinds, finish reasons, counter
samples).

Thread model: the engine-driver thread is the only writer during
serving; HTTP handler threads only snapshot (``export``). Both paths
take the buffer lock, so concurrent capture control
(``clear``/``enable``/``disable`` from a handler) is safe too.
"""
from __future__ import annotations

import threading
import time
from collections import deque


class _NullSpan:
    """Shared no-op context manager — the disabled ``span()`` path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()

#: fixed trace tids: one engine lane, one gateway/supervisor lane, then
#: one lane per request (dense first-seen order, starting at TID_REQ0).
#: pid is constant — a real os.getpid() would break byte-stable replays.
PID = 1
TID_ENGINE = 1
TID_GATEWAY = 2
TID_REQ0 = 8


class SpanTracer:
    """Bounded ring-buffer span recorder emitting Chrome trace JSON.

    ``clock`` is any zero-arg monotonic-seconds callable (default
    ``time.perf_counter``; tests pass a
    :class:`~paddle_tpu.serving.faults.VirtualClock`). ``capacity``
    bounds the ring. The tracer starts DISABLED: recording methods
    no-op until :meth:`enable`, and instrumentation sites are expected
    to guard on :attr:`enabled` before building event args — that one
    attribute read is the entire disabled-path cost.
    """

    def __init__(self, capacity=65536, clock=None):
        if int(capacity) < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.clock = clock if clock is not None else time.perf_counter
        self._lock = threading.Lock()
        self._events = deque(maxlen=self.capacity)
        self._enabled = False
        self._epoch = 0.0
        self._req_tids = {}          # request_id -> dense tid
        self._req_seq = 0            # tids ever assigned this window
        self.dropped = 0

    # ------------------------------------------------------------- control
    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self):
        """Start recording. The first enable (or any :meth:`clear`)
        sets the timestamp epoch, so ts starts near 0."""
        if not self._enabled and not self._events and self.dropped == 0:
            self._epoch = self.clock()
        self._enabled = True
        return self

    def disable(self):
        self._enabled = False
        return self

    def clear(self):
        """Drop the buffer and restart the capture window: epoch resets
        to now, request tids re-normalize from the next event."""
        with self._lock:
            self._events.clear()
            self._req_tids.clear()
            self._req_seq = 0
            self.dropped = 0
            self._epoch = self.clock()
        return self

    # -------------------------------------------------------------- clocks
    def now(self) -> float:
        """The tracer's clock — instrumentation sites snapshot span
        starts with this so t0 and ts share one timebase."""
        return self.clock()

    def since_epoch(self, mark):
        """A span-start for state that predates the capture window:
        ``mark`` if it was recorded, else the capture epoch (the span
        truthfully says "in this phase since at least capture start")."""
        return self._epoch if mark is None else mark

    def _ts(self, t) -> float:
        # microseconds relative to the capture epoch; clamp below at 0
        # so a stale pre-capture mark cannot produce a negative ts.
        # round() keeps the float stable through JSON round-trips.
        return round(max(t - self._epoch, 0.0) * 1e6, 3)

    def req_tid(self, request_id) -> int:
        """Dense, first-seen-order tid for a request — the
        normalization that keeps replayed traces byte-identical even
        though ``Sequence.request_id`` is a process-global counter."""
        with self._lock:
            tid = self._req_tids.get(request_id)
            if tid is None:
                tid = TID_REQ0 + self._req_seq
                self._req_seq += 1
                self._req_tids[request_id] = tid
                if len(self._req_tids) > self.capacity:
                    # a capacity-event ring can reference at most
                    # `capacity` distinct requests: dropping the
                    # oldest-seen mapping keeps the map bounded under
                    # persistent tracing (its events left the ring
                    # long ago; dicts preserve insertion order)
                    self._req_tids.pop(next(iter(self._req_tids)))
            return tid

    # ------------------------------------------------------------ recording
    def _append(self, ev):
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    def instant(self, name, tid=TID_ENGINE, args=None, t=None):
        """One instant event (``ph="i"``, thread scope)."""
        if not self._enabled:
            return
        ev = {"name": name, "ph": "i",
              "ts": self._ts(self.clock() if t is None else t),
              "pid": PID, "tid": int(tid), "s": "t"}
        if args:
            ev["args"] = args
        self._append(ev)

    def counter(self, name, values, tid=TID_ENGINE, t=None):
        """One counter-track sample (``ph="C"``): ``values`` is a dict
        of series-name → number, graphed by Perfetto as a stacked
        counter under ``name`` on the lane's timeline (the cost
        observatory's dispatches/step, transfer-bytes/step and KV-pool
        occupancy tracks)."""
        if not self._enabled:
            return
        self._append({"name": name, "ph": "C",
                      "ts": self._ts(self.clock() if t is None else t),
                      "pid": PID, "tid": int(tid), "args": dict(values)})

    def complete(self, name, t0, tid=TID_ENGINE, args=None, t1=None):
        """One complete span (``ph="X"``) from ``t0`` (a prior
        :meth:`now` — or None, meaning the capture epoch) to ``t1``
        (default: now)."""
        if not self._enabled:
            return
        if t1 is None:
            t1 = self.clock()
        # floor at the capture epoch: a stale mark from BEFORE this
        # window (a prior capture, or tracing enabled mid-flight) must
        # not stretch dur across inter-capture time — ts clamps to 0
        # in _ts, and the duration must clamp with it or the span ends
        # past every concurrent event (an impossible timeline)
        t0 = max(self.since_epoch(t0), self._epoch)
        ev = {"name": name, "ph": "X", "ts": self._ts(t0),
              "dur": round(max(t1 - t0, 0.0) * 1e6, 3),
              "pid": PID, "tid": int(tid)}
        if args:
            ev["args"] = args
        self._append(ev)

    def span(self, name, tid=TID_ENGINE, args=None):
        """Context manager emitting one complete span around the body.
        Returns a shared no-op when disabled (nothing allocated)."""
        if not self._enabled:
            return NULL_SPAN
        return _Span(self, name, tid, args)

    # ------------------------------------------------------------- reading
    def events(self):
        """Snapshot of the buffered events (oldest first)."""
        with self._lock:
            return list(self._events)

    def export(self) -> dict:
        """The whole capture as a Chrome trace document — serialize
        with ``json.dumps`` and load in Perfetto."""
        return {"traceEvents": self.events(),
                "displayTimeUnit": "ms",
                "otherData": {"clock": "injectable-monotonic",
                              "dropped_events": self.dropped}}


class _Span:
    __slots__ = ("_tracer", "_name", "_tid", "_args", "_t0")

    def __init__(self, tracer, name, tid, args):
        self._tracer = tracer
        self._name = name
        self._tid = tid
        self._args = args
        self._t0 = tracer.clock()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self._tracer.complete(self._name, self._t0, tid=self._tid,
                              args=self._args)
        return False
