"""XPlane trace parsing — per-op time aggregation from real traces
(reference: ``python/paddle/profiler/profiler_statistic.py`` † builds its
op tables from the chrome-trace/记录 events; here the source of truth is
the XSpace protobuf ``jax.profiler`` writes).

No TensorFlow/protobuf dependency: the reader walks the protobuf WIRE
FORMAT generically (varints + length-delimited fields) against the stable
field numbers of tsl's ``xplane.proto``:

  XSpace.planes = 1
  XPlane: id=1, name=2, lines=3, event_metadata=4 (map), stat_metadata=5
  XLine:  id=1, name=2, timestamp_ns=3, events=4
  XEvent: metadata_id=1, offset_ps=2, duration_ps=3, stats=4
  XEventMetadata: id=1, name=2, display_name=3
  map entry: key=1, value=2

Validated in CI by parsing an actual CPU-backend trace
(tests/test_profiler_xplane.py), so a schema drift breaks a test, not a
bench run.
"""
from __future__ import annotations

import gzip
import os
from typing import Dict, Iterator, List, Tuple


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over one message's bytes.
    wire 0 -> int, wire 2 -> bytes; wire 1/5 skipped (unused here)."""
    i, n = 0, len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
            yield fno, wt, v
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            yield fno, wt, buf[i:i + ln]
            i += ln
        elif wt == 1:
            yield fno, wt, buf[i:i + 8]
            i += 8
        elif wt == 5:
            yield fno, wt, buf[i:i + 4]
            i += 4
        else:  # wire types 3/4 (groups) never appear in xplane
            raise ValueError(f"unsupported wire type {wt}")


def _parse_event(buf: bytes) -> Tuple[int, int]:
    mid = dur = 0
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == 0:
            mid = v
        elif fno == 3 and wt == 0:
            dur = v
    return mid, dur


def _parse_line(buf: bytes) -> List[Tuple[int, int]]:
    events = []
    for fno, wt, v in _fields(buf):
        if fno == 4 and wt == 2:
            events.append(_parse_event(v))
    return events


def _parse_metadata_entry(buf: bytes) -> Tuple[int, str]:
    """map<int64, XEventMetadata> entry -> (id, name)."""
    key, name = 0, ""
    for fno, wt, v in _fields(buf):
        if fno == 1 and wt == 0:
            key = v
        elif fno == 2 and wt == 2:
            nm = dn = ""
            for f2, w2, v2 in _fields(v):
                if f2 == 2 and w2 == 2:
                    nm = v2.decode("utf-8", errors="replace")
                elif f2 == 3 and w2 == 2:
                    dn = v2.decode("utf-8", errors="replace")
            name = dn or nm
    return key, name


def parse_xplane(path: str) -> List[dict]:
    """Parse one .xplane.pb file -> [{name, events: [(meta_name, dur_ps)]}]"""
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:2] == b"\x1f\x8b":
        raw = gzip.decompress(raw)
    planes = []
    for fno, wt, v in _fields(raw):
        if fno != 1 or wt != 2:
            continue
        name = ""
        meta: Dict[int, str] = {}
        line_bufs = []
        for f2, w2, v2 in _fields(v):
            if f2 == 2 and w2 == 2:
                name = v2.decode("utf-8", errors="replace")
            elif f2 == 3 and w2 == 2:
                line_bufs.append(v2)
            elif f2 == 4 and w2 == 2:
                k, nm = _parse_metadata_entry(v2)
                meta[k] = nm
        events = []
        for lb in line_bufs:
            for mid, dur in _parse_line(lb):
                events.append((meta.get(mid, f"#{mid}"), dur))
        planes.append({"name": name, "events": events})
    return planes


def _trace_files(trace_dir: str) -> List[str]:
    out = []
    for root, _dirs, files in os.walk(trace_dir):
        for fn in files:
            if fn.endswith(".xplane.pb"):
                out.append(os.path.join(root, fn))
    return sorted(out)


def op_statistics(trace_dir: str, device_only: bool = True,
                  top: int = 0) -> List[dict]:
    """Aggregate per-op totals across a trace directory (the reference's
    ``profiler_statistic`` op table). Returns entries sorted by total
    time: {name, total_ms, count, avg_us, plane}."""
    agg: Dict[Tuple[str, str], List[float]] = {}
    for path in _trace_files(trace_dir):
        for plane in parse_xplane(path):
            pname = plane["name"]
            # device planes carry the XLA op timeline; host planes are
            # python/runtime threads
            if device_only and "TPU" not in pname and "GPU" not in pname \
                    and "/device" not in pname:
                continue
            for name, dur_ps in plane["events"]:
                key = (pname, name)
                cur = agg.setdefault(key, [0.0, 0])
                cur[0] += dur_ps
                cur[1] += 1
    rows = [{"plane": p, "name": n, "total_ms": t / 1e9, "count": c,
             "avg_us": t / 1e6 / c if c else 0.0}
            for (p, n), (t, c) in agg.items()]
    rows.sort(key=lambda r: -r["total_ms"])
    return rows[:top] if top else rows


def op_statistics_with_fallback(trace_dir: str, device_only: bool = True,
                                top: int = 0):
    """:func:`op_statistics` plus THE host-plane fallback rule in one
    place: a device-only aggregation that finds nothing (CPU-backend
    traces carry the XLA ops on host planes) retries over all planes.
    Returns ``(rows, fell_back)``; both :func:`summarize` and the
    ``python -m paddle_tpu.profiler`` CLI's ``--json`` branch call
    this, so the rule cannot drift between the two outputs."""
    rows = op_statistics(trace_dir, device_only=device_only, top=top)
    if rows or not device_only:
        return rows, False
    rows = op_statistics(trace_dir, device_only=False, top=top)
    return rows, bool(rows)


def summarize(trace_dir: str, top: int = 10,
              device_only: bool = True) -> str:
    """Render the op table as text. Device planes by default, with the
    shared host-plane fallback announced on the first line."""
    rows, fell_back = op_statistics_with_fallback(
        trace_dir, device_only=device_only, top=top)
    note = "# no device planes in this trace; showing all planes\n" \
        if fell_back else ""
    if not rows:
        return "no device events parsed"
    width = max(len(r["name"][:60]) for r in rows)
    lines = [f"{'op':<{width}}  total_ms  count  avg_us"]
    for r in rows:
        lines.append(f"{r['name'][:60]:<{width}}  {r['total_ms']:8.3f}  "
                     f"{r['count']:5d}  {r['avg_us']:7.1f}")
    return note + "\n".join(lines)
