"""paddle.quantization — QAT/PTQ (reference: ``python/paddle/quantization/``
— QuantConfig + QAT.quantize (fake-quant insertion) + PTQ.quantize
(observers) + convert).

TPU-native: fake-quant is a pure jnp op with a straight-through-estimator
custom VJP — it fuses into the surrounding XLA program (no special kernels;
int8 inference on TPU is a matter of emitting int8 dots, which `convert`
models by baking quantized-dequantized weights). Observers are functional
state on the layer.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.tensor import Tensor
from ..ops._op import tensor_op

__all__ = ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver", "quanted_linear",
           "fake_quant", "FakeQuanterWithAbsMaxObserver", "QuantedLinear",
           "quantize_weight_int8", "convert_weights_int8",
           "quantize_collective_int8", "quantized_psum_int8",
           "collective_wire_bytes"]


def quantize_weight_int8(w, reduce_axis, bits=8):
    """Symmetric per-channel int8 weight-only quantization — THE shared
    machinery behind :class:`ConvertedLinear` and the serving engine's
    ``quantize_weights=True`` decode path
    (``serving/decode.quantize_decode_params``, README "Quantized
    serving").

    ``w`` is the raw weight array; ``reduce_axis`` names the
    contraction axis of the matmul the weight feeds (the "in" dim), so
    each OUTPUT channel gets its own absmax scale — per-channel, not
    per-tensor, because one outlier channel must not flatten every
    other channel's resolution. Returns ``(q int8, scale f32)`` with
    ``scale`` keeping the reduced axis as size 1 (broadcasts straight
    back against ``q`` for the dequant ``q * scale``). Symmetric range
    [-127, 127]: -128 is never emitted so ``|q * scale| <= absmax``
    exactly. All-zero channels carry scale 0 and dequantize to exact
    zeros (the quantize guard divides by a tiny floor instead).
    ``bits < 8`` narrows the grid inside the same int8 storage (the
    PTQ 4-bit convert path); ``bits > 8`` cannot fit int8 and raises.
    """
    if not 2 <= int(bits) <= 8:
        raise ValueError(
            f"int8 storage holds 2..8-bit symmetric grids, got "
            f"bits={bits}")
    qmax = float(2 ** (int(bits) - 1) - 1)
    w = jnp.asarray(w)
    scale = (jnp.max(jnp.abs(w.astype(jnp.float32)), axis=reduce_axis,
                     keepdims=True) / qmax)
    q = jnp.clip(jnp.round(w.astype(jnp.float32)
                           / jnp.maximum(scale, 1e-30)),
                 -qmax, qmax).astype(jnp.int8)
    return q, scale


# ------------------------------------------- quantized all-reduce (EQuARX)
def quantize_collective_int8(x):
    """Symmetric per-row int8 quantization of a collective payload —
    THE wire format of the serving stack's ``collective_dtype="int8"``
    tensor-parallel all-reduce (README "Tensor-parallel serving",
    EQuARX / PAPERS.md). Each row (absmax over the LAST axis) gets its
    own fp32 scale, so one outlier activation cannot flatten a whole
    chunk's resolution; all-zero rows carry scale 0 and dequantize to
    exact zeros. Returns ``(q int8, scale f32 [..., 1])``."""
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1, keepdims=True) / 127.0
    q = jnp.clip(jnp.round(xf / jnp.maximum(scale, 1e-30)),
                 -127, 127).astype(jnp.int8)
    return q, scale


def quantized_psum_int8(x, axis_name, tp):
    """EQuARX-style block-quantized all-reduce over mesh axis
    ``axis_name`` (size ``tp``): both communication phases move int8
    payloads plus per-row fp32 scales instead of full-precision
    activations, cutting wire bytes ~``itemsize / (1 + tp·4/H)``-fold
    (~3.5–3.9x for fp32 at serving hidden sizes).

    Phase 1 (reduce-scatter): split the partial sum into ``tp`` chunks
    along the last axis, quantize, ``all_to_all`` so shard ``i``
    receives every shard's quantized chunk ``i``, dequantize and sum
    in fp32 — a FIXED summation order, so the result (and therefore
    the token stream) is deterministic and identical on every shard.
    Phase 2 (all-gather): requantize the reduced chunk, ``all_gather``,
    dequantize and reassemble. The double quantization is the quality
    price the serving bench MEASURES (greedy divergence in
    TP_BENCH.json) rather than assumes away.

    Requires the last axis divisible by ``tp`` (the engine validates
    ``hidden_size % tp == 0`` at build). Shapes/dtype are preserved."""
    shp = x.shape
    hidden = shp[-1]
    chunk = hidden // tp
    xc = jnp.moveaxis(x.reshape(shp[:-1] + (tp, chunk)), -2, 0)
    q, s = quantize_collective_int8(xc)            # [tp, ..., chunk]
    q2 = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    s2 = jax.lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)
    red = jnp.sum(q2.astype(jnp.float32) * s2, axis=0)   # [..., chunk]
    qr, sr = quantize_collective_int8(red)
    qg = jax.lax.all_gather(qr, axis_name, axis=0)       # [tp, ..., chunk]
    sg = jax.lax.all_gather(sr, axis_name, axis=0)
    full = jnp.moveaxis(qg.astype(jnp.float32) * sg, 0, -2)
    return full.reshape(shp).astype(x.dtype)


def collective_wire_bytes(rows, hidden, tp, collective_dtype,
                          fp_itemsize=4):
    """EXACT per-device wire bytes of ONE per-layer all-reduce of a
    ``[rows, hidden]`` activation on a ``tp``-way mesh — the counter
    model behind ``serving_collective_bytes_total{dtype}`` (README
    "Tensor-parallel serving") and the bench's >=3x acceptance gate.

    Both dtypes are priced on the same ring reduce-scatter +
    all-gather schedule (each phase moves ``(tp-1)/tp`` of the payload
    per device), so the fp-vs-int8 ratio isolates the WIRE FORMAT:

    - ``"fp"``: payload = ``rows · hidden · fp_itemsize``;
    - ``"int8"``: payload = ``rows · hidden`` int8 bytes plus one fp32
      scale per (row, chunk) — ``rows · tp`` scales per phase — the
      exact layout :func:`quantized_psum_int8` moves.

    Deterministic, shape-derived, no measurement noise. Returns 0 for
    ``tp <= 1`` (no mesh, no wire)."""
    if tp <= 1:
        return 0
    if collective_dtype == "int8":
        payload = rows * hidden + rows * tp * 4
    else:
        payload = rows * hidden * fp_itemsize
    return int(2 * payload * (tp - 1) // tp)


# ------------------------------------------------------------- fake quant
@jax.custom_vjp
def _fake_quant_ste(x, scale, bits):
    qmax = 2.0 ** (bits - 1) - 1
    s = jnp.maximum(scale, 1e-8) / qmax
    return jnp.clip(jnp.round(x / s), -qmax - 1, qmax) * s


def _fq_fwd(x, scale, bits):
    return _fake_quant_ste(x, scale, bits), (x, scale, bits)


def _fq_bwd(res, g):
    x, scale, bits = res
    qmax = 2.0 ** (bits - 1) - 1
    lim = jnp.maximum(scale, 1e-8)
    # straight-through inside the clip range, zero outside
    pass_thru = (jnp.abs(x) <= lim).astype(g.dtype)
    return g * pass_thru, jnp.zeros_like(scale), None


_fake_quant_ste.defvjp(_fq_fwd, _fq_bwd)


@tensor_op
def fake_quant(x, scale, bits=8):
    """Quantize-dequantize with STE gradient (reference
    FakeQuanterWithAbsMaxObserver forward)."""
    return _fake_quant_ste(x, jnp.asarray(scale, jnp.float32), int(bits))


class FakeQuanterWithAbsMaxObserver(nn.Layer):
    """Activation quanter: tracks a running absmax, fake-quants with STE
    (reference ``paddle.quantization.quanters.FakeQuanterWithAbsMaxObserver``)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None):
        super().__init__()
        self.moving_rate = float(moving_rate)
        self.bits = int(bit_length)
        self.register_buffer("scale", Tensor(jnp.ones((), jnp.float32)))
        self._seen = False

    def forward(self, x):
        cur = jnp.max(jnp.abs(x.value)).astype(jnp.float32)
        if self.training:
            m = self.moving_rate
            prev = self.scale.value
            new = jnp.where(jnp.asarray(self._seen), m * prev + (1 - m) * cur,
                            cur)
            self.scale.set_value(new)
            self._seen = True
        return fake_quant(x, self.scale.value, self.bits)


class QuantedLinear(nn.Layer):
    """Linear with fake-quanted weight + activation (QAT execution form)."""

    def __init__(self, linear: nn.Linear, q_config):
        super().__init__()
        self.weight = linear.weight
        self.bias = linear.bias
        self.weight_bits = q_config.weight_bits
        self.act_quanter = (FakeQuanterWithAbsMaxObserver(
            bit_length=q_config.activation_bits)
            if q_config.activation_bits else None)

    def forward(self, x):
        if self.act_quanter is not None:
            x = self.act_quanter(x)
        w = self.weight
        wq = fake_quant(w, jnp.max(jnp.abs(w.value)), self.weight_bits)
        from ..nn import functional as F
        return F.linear(x, wq, self.bias)


# ------------------------------------------------------------- observers
class AbsmaxObserver(nn.Layer):
    """PTQ observer: records absmax over calibration batches (reference
    ``paddle.quantization.observers.AbsmaxObserver``)."""

    def __init__(self, quant_bits=8):
        super().__init__()
        self.bits = int(quant_bits)
        self.register_buffer("scale", Tensor(jnp.zeros((), jnp.float32)))

    def forward(self, x):
        cur = jnp.max(jnp.abs(x.value)).astype(jnp.float32)
        self.scale.set_value(jnp.maximum(self.scale.value, cur))
        return x


class ObservedLinear(nn.Layer):
    def __init__(self, linear: nn.Linear, q_config):
        super().__init__()
        self.weight = linear.weight
        self.bias = linear.bias
        self.observer = AbsmaxObserver(q_config.activation_bits or 8)
        self.weight_bits = q_config.weight_bits

    def forward(self, x):
        x = self.observer(x)
        from ..nn import functional as F
        return F.linear(x, self.weight, self.bias)


class ConvertedLinear(nn.Layer):
    """Inference form: weights stored int8 + scale, dequantized on the fly
    (on TPU the int8 weight halves HBM traffic; XLA emits the dequant as a
    fused convert on the way into the MXU).

    Scales are PER OUTPUT CHANNEL and computed ONCE here, at convert
    time (``quantize_weight_int8``) — the forward only applies them.
    Per-tensor absmax let a single outlier channel flatten every other
    channel's resolution, and deriving scales inside ``__call__`` both
    re-paid the reduction on every step and made the quantization grid
    drift with whatever dtype autocast handed in. ``w_scale`` has shape
    ``[1, out_features]`` (paddle's ``[in, out]`` weight layout)."""

    def __init__(self, weight, bias, weight_bits=8):
        super().__init__()
        q, scale = quantize_weight_int8(weight.value, reduce_axis=0,
                                        bits=weight_bits)
        self.register_buffer("qweight", Tensor(q))
        self.register_buffer("w_scale", Tensor(scale))
        self.bias = bias

    def forward(self, x):
        # dequantize to the INPUT's dtype, not hard-coded fp32: under
        # amp.auto_cast(dtype="bfloat16") a bf16 activation must meet a
        # bf16 weight or the matmul silently promotes back to fp32
        # (breaking the int8 + autocast composition); integer inputs
        # (never valid for linear anyway) fall back to fp32
        dt = x.value.dtype
        if not jnp.issubdtype(dt, jnp.floating):
            dt = jnp.float32
        w = (self.qweight.value.astype(dt)
             * self.w_scale.value.astype(dt))
        b = self.bias
        if b is not None and b.dtype != dt:
            b = Tensor(b.value.astype(dt))  # fp32 bias would re-promote
        from ..nn import functional as F
        return F.linear(x, Tensor(w), b)


# ------------------------------------------------------------- config/API
class QuantConfig:
    """Reference ``paddle.quantization.QuantConfig`` (subset): global
    weight/activation quanter settings."""

    def __init__(self, activation=None, weight=None, weight_bits=8,
                 activation_bits=8):
        self.activation = activation
        self.weight = weight
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits) if activation_bits else 0
        self._types = (nn.Linear,)

    def add_type_config(self, layer_types, activation=None, weight=None):
        if not isinstance(layer_types, (list, tuple)):
            layer_types = [layer_types]
        self._types = tuple(layer_types)
        return self


def _swap_matching(model, match_fn, factory):
    """Replace sublayers where match_fn(child); skips subtrees of already-
    replaced layers (their old child paths no longer resolve)."""
    replaced = []
    for name, _ in list(model.named_sublayers()):
        if any(name.startswith(r + ".") for r in replaced):
            continue
        parent = model
        parts = name.split(".")
        for p in parts[:-1]:
            parent = getattr(parent, p)
        leaf = parts[-1]
        child = getattr(parent, leaf)
        if match_fn(child):
            setattr(parent, leaf, factory(child))
            replaced.append(name)
    return model


def _swap_layers(model, cfg, factory):
    return _swap_matching(
        model,
        lambda child: isinstance(child, nn.Linear) and not isinstance(
            child, (QuantedLinear, ObservedLinear, ConvertedLinear)),
        lambda child: factory(child, cfg))


class QAT:
    """Quantization-aware training driver (reference ``paddle.quantization.QAT``)."""

    def __init__(self, q_config: QuantConfig):
        self.cfg = q_config

    def quantize(self, model, inplace=False):
        return _swap_layers(model, self.cfg,
                            lambda lin, cfg: QuantedLinear(lin, cfg))

    def convert(self, model, inplace=False):
        return _swap_layers(
            model, self.cfg,
            lambda lin, cfg: lin)  # QuantedLinear already executes quantized


class PTQ:
    """Post-training quantization driver (reference ``paddle.quantization.PTQ``)."""

    def __init__(self, q_config: QuantConfig):
        self.cfg = q_config

    def quantize(self, model, inplace=False):
        return _swap_layers(model, self.cfg,
                            lambda lin, cfg: ObservedLinear(lin, cfg))

    def convert(self, model, inplace=False):
        return _swap_matching(
            model,
            lambda child: isinstance(child, ObservedLinear),
            lambda child: ConvertedLinear(child.weight, child.bias,
                                          self.cfg.weight_bits))


def convert_weights_int8(model):
    """One-call weight-only int8 conversion (no observers, no
    calibration): swap every ``nn.Linear`` for a
    :class:`ConvertedLinear` with per-channel scales baked at convert
    time. IDEMPOTENT: already-converted layers (and QAT/observed ones)
    are skipped, so ``convert_weights_int8(convert_weights_int8(m))``
    is a no-op — the second pass finds nothing to swap and never
    re-quantizes an int8 weight (which would double the quantization
    error). The serving engine's ``quantize_weights=True`` knob is the
    raw-array twin of this layer-level surface."""
    return _swap_layers(
        model, None, lambda lin, _cfg: ConvertedLinear(lin.weight,
                                                       lin.bias))


def quanted_linear(x, weight, bias=None, w_bits=8, a_scale=None, a_bits=8):
    """Functional QAT linear."""
    if a_scale is not None:
        x = fake_quant(x, a_scale, a_bits)
    wq = fake_quant(weight, jnp.max(jnp.abs(weight.value)), w_bits)
    from ..nn import functional as F
    return F.linear(x, wq, bias)
