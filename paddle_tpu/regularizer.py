"""Weight-decay regularizers (reference: python/paddle/regularizer.py †,
applied by the optimizer into the gradient before the update — the
"L2-regularization-into-grad" style, as opposed to AdamW's decoupled decay).

A regularizer is a callable ``penalty_grad = reg(param)`` plus a ``_coeff``
attribute; optimizers accept one as ``weight_decay=`` and add the penalty
term to the gradient inside both the eager ``step()`` and the pure
``apply_gradients`` (jit/TrainStep) paths.
"""
import jax.numpy as jnp

__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    """Base class; subclasses define the per-parameter gradient penalty."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __call__(self, param):
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    """d/dp of coeff * |p| = coeff * sign(p) added to the gradient."""

    def __call__(self, param):
        return self._coeff * jnp.sign(param)


class L2Decay(WeightDecayRegularizer):
    """d/dp of (coeff/2) * ||p||^2 = coeff * p added to the gradient.

    Matches the numeric ``weight_decay=float`` spelling exactly (the
    reference treats a bare float as L2Decay(float))."""

    def __call__(self, param):
        return self._coeff * param
