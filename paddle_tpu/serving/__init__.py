"""Continuous-batching serving subsystem (L7, SURVEY §3.5 / PAPERS.md).

Orca-style iteration-level scheduling on top of a slot-based paged KV
cache: one compiled single-token ``decode_step_fn`` whose shapes depend
only on ``(num_slots, max_seq_len)`` serves every request mix; requests
are admitted into free cache slots mid-flight, and a slot is freed the
moment its sequence hits EOS or its token budget — the ragged Pallas
decode kernel (``kernels/pallas_decode.py``) already skips KV blocks past
``lengths[b]``, so a freed slot's stale cache costs no HBM traffic.

Public surface:

- :class:`GenerationRequest` / :class:`Sequence` — request & in-flight
  state (per-request deadlines via ``timeout_s``; ``finish_reason`` ∈
  :data:`FINISH_REASONS` = stop|length|cancelled|timeout|error)
- :class:`GenerationResult` — array-like generate() output + finish_reason
- :class:`SlotKVCache` — the dense per-slot KV cache (legacy
  compatibility shim, ``paged_attn=False``)
- :class:`PagedKVCache` — true block-table paged attention, THE
  default: the :class:`BlockManager` pool IS the cache, slots address
  it through per-slot block tables, prefix hits are zero-copy
  references and retirement donates prompt AND generated blocks to the
  trie (README "Paged attention")
- :class:`FIFOScheduler` — admission + fused-chunk step policy +
  chunked-prefill token budgeting
- :class:`PriorityClass` / :class:`ClassTable` /
  :class:`PolicyScheduler` — multi-tenant SLO policy (README
  "Multi-tenant SLO serving"): priority classes with TTFT/TPOT
  targets, deadline-aware admission with per-class headroom and
  anti-starvation aging, SLO-driven preemption of lower-class work
  (engine ``priority_classes=...``; the default single class keeps
  the FIFO baseline byte-identical)
- :class:`ContinuousBatchingEngine` — the step-function serving API
  (``cancel()``, deadline sweeps, ``on_token``/``on_finish`` streaming
  hooks; ``prefix_cache=True`` turns on automatic prefix caching;
  ``prefill_chunk`` interleaves long cold-prompt prefills with decode
  steps to bound TTFT — README "Chunked prefill")
- :class:`BlockManager` / :class:`PrefixCache` — the block-granular
  prefix-cache subsystem: ref-counted KV block pool + hash-trie over
  prompt token blocks with LRU eviction (README "Automatic prefix
  caching")
- :class:`Drafter` / :class:`NgramDrafter` / :class:`ModelDrafter` —
  speculative-decode proposers (engine ``spec_decode=True``, README
  "Speculative decoding"): draft tokens verified as ragged spans
  through the paged kernel, rejected K/V rolled back by
  ``PagedKVCache.truncate``, streams byte-identical to speculation off

Fault tolerance (README "Fault tolerance & chaos testing"):
:class:`PoolExhausted` is the typed KV-pool-pressure signal the engine
repairs by preempting the youngest sequence (recompute, donated chain);
``engine.restore()`` re-enqueues a live sequence after a crash so the
supervised gateway driver can rebuild and continue streams
byte-identically; :mod:`.faults` is the deterministic fault-injection
harness (:class:`FaultPlan` / :class:`VirtualClock`) the chaos tests
and ``scripts/bench_chaos.py`` drive.

Scale-out: :mod:`paddle_tpu.serving.fleet` (README "Engine fleet")
replicates the whole stack — N shared-nothing supervised engines
behind one routed front door with prefix-affinity routing,
failover-to-sibling on replica death, and live request migration
built on :meth:`ContinuousBatchingEngine.evict` + ``restore()``.

The HTTP layer on top lives in :mod:`paddle_tpu.serving.server`
(imported lazily — the engine has no HTTP dependency).
"""
from .block_manager import BlockManager
from .drafter import Drafter, ModelDrafter, NgramDrafter
from .engine import ContinuousBatchingEngine
from .faults import (FatalFault, FaultError, FaultPlan, TransientFault,
                     VirtualClock)
from .kv_cache import PagedKVCache, PoolExhausted, SlotKVCache
from .policy import ClassTable, PolicyScheduler, PriorityClass
from .prefix_cache import HostTier, PrefixCache
from .request import (FINISH_REASONS, GenerationRequest, GenerationResult,
                      Sequence)
from .scheduler import FIFOScheduler

__all__ = [
    "ContinuousBatchingEngine", "GenerationRequest", "GenerationResult",
    "Sequence", "SlotKVCache", "PagedKVCache", "PoolExhausted",
    "FIFOScheduler", "FINISH_REASONS", "BlockManager", "PrefixCache",
    "HostTier", "PriorityClass", "ClassTable", "PolicyScheduler",
    "FaultPlan", "FaultError", "TransientFault", "FatalFault",
    "VirtualClock", "Drafter", "NgramDrafter", "ModelDrafter",
]
