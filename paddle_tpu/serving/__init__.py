"""Continuous-batching serving subsystem (L7, SURVEY §3.5 / PAPERS.md).

Orca-style iteration-level scheduling on top of a slot-based paged KV
cache: one compiled single-token ``decode_step_fn`` whose shapes depend
only on ``(num_slots, max_seq_len)`` serves every request mix; requests
are admitted into free cache slots mid-flight, and a slot is freed the
moment its sequence hits EOS or its token budget — the ragged Pallas
decode kernel (``kernels/pallas_decode.py``) already skips KV blocks past
``lengths[b]``, so a freed slot's stale cache costs no HBM traffic.

Public surface:

- :class:`GenerationRequest` / :class:`Sequence` — request & in-flight state
- :class:`SlotKVCache` — the paged per-slot KV cache manager
- :class:`FIFOScheduler` — admission + fused-chunk step policy
- :class:`ContinuousBatchingEngine` — the step-function serving API
"""
from .engine import ContinuousBatchingEngine
from .kv_cache import SlotKVCache
from .request import GenerationRequest, Sequence
from .scheduler import FIFOScheduler

__all__ = [
    "ContinuousBatchingEngine", "GenerationRequest", "Sequence",
    "SlotKVCache", "FIFOScheduler",
]
