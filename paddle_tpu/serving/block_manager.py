"""Ref-counted block pool backing the automatic prefix cache.

The pool is the block-granular half of the serving KV story ("Ragged
Paged Attention", PAPERS.md): two dense device arrays
``[L, num_blocks, block_size, Hkv, D]`` holding published prompt-prefix
KV blocks, plus host-side bookkeeping — a free-block min-heap (same
O(log n) allocator discipline as :class:`~.kv_cache.SlotKVCache`) and a
per-block reference count.

Division of labor: this class owns *physical* blocks (allocation,
refcounts, storage); :class:`~.prefix_cache.PrefixCache` owns *logical*
identity (the hash-trie from token content to block id, LRU eviction
order, hit/miss accounting). On the dense engine blocks move between
the pool and the slot cache through the compile-once copy programs in
``kv_cache.py``; on the paged engine (:class:`~.kv_cache.PagedKVCache`)
the pool IS the KV cache — live sequences reference blocks through
per-slot block tables, published blocks are shared zero-copy (one
block, N refs), and divergence is safe because writes only ever land
in blocks the writing sequence privately owns (the COW fork: a table
is shared-prefix + private-tail, and the tail is allocated fresh, never
forked in place).

Ownership discipline for table-referenced blocks: every block in a
live table holds >= 1 ref — shared prefix blocks are pinned via
:meth:`PrefixCache.acquire`, private tail blocks carry the owning
sequence's pin from :meth:`alloc` + :meth:`ref`. :meth:`drop` releases
one pin and returns the block to the free heap exactly when the count
hits zero, so a mid-decode cancel frees the private tail while the
shared prefix (still pinned by the trie's other readers) survives.
"""
from __future__ import annotations

import heapq

import jax
import jax.numpy as jnp
import numpy as np


class StagingPool:
    """Reusable pageable host buffers for tier spills, one free list
    per (shape, dtype).

    Every spill used to land in freshly-allocated numpy per block, so a
    long-running tiered engine paid an allocator round-trip (and a page
    fault on first touch) per spilled block forever. A spill's staging
    need is EXACTLY the pool's per-block shapes — a handful of keys —
    so the steady state is one buffer per shape in flight:
    :meth:`take` pops a free buffer or allocates the shape's first,
    recycling (tier drop / readmission) gives it back, and
    ``allocations`` counts real ``np.empty`` calls per shape — the
    regression pin is one per shape, not one per spill."""

    def __init__(self):
        self._free = {}          # (plane, shape, dtype str) -> [buffers]
        #: (plane, shape, dtype str) -> np.empty count (the test pin)
        self.allocations = {}

    @staticmethod
    def _key(plane, shape, dtype):
        return (plane, tuple(int(s) for s in shape), np.dtype(dtype).str)

    def take(self, plane, shape, dtype):
        """A writable host buffer for the named plane (``k`` / ``v`` /
        scale) of the shape, reused when one is free. The plane name
        joins the key so the k and v planes — same shape — each own
        exactly one steady-state buffer instead of contending for one
        free list."""
        key = self._key(plane, shape, dtype)
        free = self._free.get(key)
        if free:
            return free.pop()
        self.allocations[key] = self.allocations.get(key, 0) + 1
        return np.empty(key[1], np.dtype(dtype))

    def give(self, bufs):
        """Return a spill's buffers (a ``read_block``-shaped dict) to
        the free lists. Only call once NOTHING can read them again —
        an alias held by a tier entry or an in-flight h2d would read
        the next spill's bytes."""
        for plane, b in bufs.items():
            self._free.setdefault(self._key(plane, b.shape, b.dtype),
                                  []).append(b)


class BlockManager:
    """Physical block pool: device arrays + free heap + refcounts.

    ``kv_dtype="int8"`` stores the pool block-quantized (README
    "Quantized serving"): ``k``/``v`` become int8 and each block
    carries a per-row-per-head fp32 SCALE PLANE alongside it —
    ``k_scale``/``v_scale`` ``[L, num_blocks, block_size, Hkv]``,
    indexed by the SAME physical block id as the data, so every
    lifecycle move (alloc/free/ref/drop, trie donation, speculative
    truncation) carries a block's scales with it for free: there is no
    separate scale bookkeeping to drift. Appends quantize on the way
    in (``serving/decode.quantize_kv_rows``); the attention kernels
    dequantize right after the table-indirect DMA, so HBM block bytes
    are int8 (a ~4x cut vs fp32 at head_dim 64; scales cost
    ``4 / head_dim`` of the int8 data) while the matmuls stay
    full-precision.

    ``kv_dtype="fp8"`` stores ``float8_e4m3fn`` with PER-BLOCK
    per-head scale planes ``[L, num_blocks, Hkv]`` — ``block_size``×
    fewer scale bytes than int8's per-row planes. The block scale is
    the constant 1.0 by construction: e4m3's own exponent is the
    per-value scale, and a data-dependent block scale would make a
    block's bytes depend on WHICH program first wrote it (a decode
    append covers one row, a prefill chunk covers the whole block), so
    restore()-by-recompute could not replay byte-identically. The
    planes still ride the same physical block id through every
    lifecycle move and the kernels still apply them post-dot — the
    structural (data, scale) plumbing is identical to int8's, only the
    write rule differs (``kv_cache.quantize_kv_rows_fp8``: saturating
    cast, no scale write)."""

    def __init__(self, num_layers, num_blocks, block_size, num_kv_heads,
                 head_dim, dtype=jnp.float32, kv_dtype=None, mesh=None):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if kv_dtype not in (None, "int8", "fp8"):
            raise ValueError(
                f"kv_dtype must be None (store at pool dtype), 'int8' or "
                f"'fp8', got {kv_dtype!r}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.kv_dtype = kv_dtype
        self.quantized = kv_dtype is not None
        self.fp8 = kv_dtype == "fp8"
        shape = (num_layers, self.num_blocks, self.block_size,
                 num_kv_heads, head_dim)
        store = (jnp.float8_e4m3fn if self.fp8
                 else jnp.int8 if self.quantized else dtype)
        self.k = jnp.zeros(shape, store)
        self.v = jnp.zeros(shape, store)
        if self.fp8:
            # per-BLOCK planes, constant 1.0 (class docstring): never
            # rewritten by appends, only read by the kernels' post-dot
            # rescale — initialized to ones so a fresh block
            # dequantizes as identity
            sshape = (num_layers, self.num_blocks, num_kv_heads)
            self.k_scale = jnp.ones(sshape, jnp.float32)
            self.v_scale = jnp.ones(sshape, jnp.float32)
        elif self.quantized:
            self.k_scale = jnp.zeros(shape[:-1], jnp.float32)
            self.v_scale = jnp.zeros(shape[:-1], jnp.float32)
        else:
            self.k_scale = self.v_scale = None
        # tensor-parallel pool partition (README "Tensor-parallel
        # serving"): commit the arrays head-sharded over the ("tp",)
        # mesh — each shard owns Hkv/tp heads of EVERY physical block,
        # scale planes on the same axis, so ALL the host bookkeeping
        # below (heap, refcounts, tables) stays replicated-by-identity
        # and every lifecycle move carries the shards for free.
        self.tp = 1
        if mesh is not None:
            from jax.sharding import NamedSharding
            from .decode import _pool_pspec
            self.tp = mesh.devices.size
            if num_kv_heads % self.tp:
                raise ValueError(
                    f"pool of {num_kv_heads} KV heads cannot partition "
                    f"over a {self.tp}-device mesh")
            # THE pool spec (serving/decode._pool_pspec), not a local
            # re-spelling: a spelling difference here would read as a
            # fresh sharding to the pjit cache every step
            if self.quantized:
                data_spec, scale_spec = _pool_pspec(self.kv_dtype)
                scale_s = NamedSharding(mesh, scale_spec)
                self.k_scale = jax.device_put(self.k_scale, scale_s)
                self.v_scale = jax.device_put(self.v_scale, scale_s)
            else:
                data_spec = _pool_pspec(False)
            data_s = NamedSharding(mesh, data_spec)
            self.k = jax.device_put(self.k, data_s)
            self.v = jax.device_put(self.v, data_s)
        self._free_heap = list(range(self.num_blocks))
        self._free_set = set(self._free_heap)
        self._ref = np.zeros(self.num_blocks, np.int32)
        self._peak_used = 0
        # spill staging (README "Tiered KV prefix cache"): per-shape
        # reusable host buffers for read_block copies, recycled by the
        # host tier's drop/readmit paths through recycle_staging
        self.staging = StagingPool()

    # ---------------------------------------------------------- allocator
    @property
    def num_free(self) -> int:
        return len(self._free_set)

    @property
    def num_used(self) -> int:
        """Live blocks (published + pinned) — the ``kv_prefix_blocks``
        gauge on ``/metrics``."""
        return self.num_blocks - self.num_free

    @property
    def peak_used(self) -> int:
        """High-water mark of :attr:`num_used` — the paged-vs-dense
        bench's HBM-footprint metric (scripts/bench_paged.py)."""
        return self._peak_used

    @property
    def num_shared(self) -> int:
        """Blocks with refcount >= 2 (physically shared by concurrent
        readers) — the ``kv_blocks_shared`` gauge on ``/metrics``."""
        return int((self._ref >= 2).sum())

    @property
    def block_nbytes(self) -> int:
        """HBM bytes one block's K/V DATA holds across all layers — the
        unit of the ``/debug/requests`` per-request KV-bytes column and
        the cost observatory's occupancy-to-bytes conversion. Abstract
        (shape × itemsize): no device sync. Dtype-aware by
        construction: an int8 pool reports int8 bytes (scale planes are
        accounted separately, :attr:`scale_block_nbytes`)."""
        per = self.k.size * np.dtype(self.k.dtype).itemsize
        return 2 * per // self.num_blocks

    @property
    def scale_block_nbytes(self) -> int:
        """HBM bytes one block's SCALE PLANES hold across all layers,
        K and V (0 on an unquantized pool) — the ``kind="scales"``
        half of the ``kv_pool_bytes`` gauge."""
        if not self.quantized:
            return 0
        per = self.k_scale.size * np.dtype(self.k_scale.dtype).itemsize
        return 2 * per // self.num_blocks

    def alloc(self):
        """Claim a free block (lowest id first, deterministic); None when
        the pool is exhausted (the caller evicts or skips publishing)."""
        if not self._free_set:
            return None
        block = heapq.heappop(self._free_heap)
        self._free_set.discard(block)
        self._peak_used = max(self._peak_used, self.num_used)
        return block

    def free(self, block: int):
        if block in self._free_set:
            raise ValueError(f"block {block} double-freed")
        if self._ref[block]:
            raise ValueError(
                f"block {block} freed with refcount {int(self._ref[block])}")
        heapq.heappush(self._free_heap, block)
        self._free_set.add(block)

    # ---------------------------------------------------------- refcounts
    def ref(self, block: int):
        """Pin a block (a sequence's admission matched it)."""
        self._ref[block] += 1

    def unref(self, block: int) -> int:
        """Release one pin; returns the remaining count."""
        if self._ref[block] <= 0:
            raise ValueError(f"block {block} unref'd below zero")
        self._ref[block] -= 1
        return int(self._ref[block])

    def refcount(self, block: int) -> int:
        return int(self._ref[block])

    # --------------------------------------------------- tier transfers
    def read_block(self, block: int) -> dict:
        """Copy one block device→host for the spill tier: numpy buffers
        keyed like the pool's own arrays (``k``/``v`` and, on an int8
        pool, ``k_scale``/``v_scale`` — the scale planes ride the same
        block id, README "Quantized serving"). One jitted fetch program
        per (quantized, tp) — the block id is a runtime scalar
        (``kv_cache._tier_fetch``), so spilling never adds a trace."""
        from .kv_cache import _tier_fetch
        bid = np.int32(block)
        if self.quantized:
            # kv_dtype keys the program: the fp8 pool's per-BLOCK scale
            # planes are a different rank (and TP spec) than int8's
            # per-row planes
            bk, bv, bks, bvs = _tier_fetch(self.kv_dtype, self.tp)(
                self.k, self.v, self.k_scale, self.v_scale, bid)
            return self._stage(k=bk, v=bv, k_scale=bks, v_scale=bvs)
        bk, bv = _tier_fetch(False, self.tp)(self.k, self.v, bid)
        return self._stage(k=bk, v=bv)

    def _stage(self, **arrays):
        """Land the fetched block in staging-pool buffers (one real
        allocation per shape over the pool's lifetime, not per spill):
        ``np.asarray`` on the device result may be a zero-copy view of
        the device buffer, so the copy into the reusable buffer is also
        what unpins the spill bytes from XLA-owned memory."""
        out = {}
        for name, arr in arrays.items():
            host = np.asarray(arr)
            buf = self.staging.take(name, host.shape, host.dtype)
            np.copyto(buf, host)
            out[name] = buf
        return out

    def recycle_staging(self, bufs):
        """Hand a spill's staging buffers back for reuse once their
        tier entry is dead (dropped, replaced, or readmitted and
        injected). The sync makes the readmission case safe: the
        injection program may still be reading the host buffers under
        async dispatch, and a recycled buffer's next spill would race
        it — waiting on the pool arrays (the injection's outputs)
        fences every pending read."""
        jax.block_until_ready(self.k)
        jax.block_until_ready(self.v)
        if self.quantized:
            jax.block_until_ready(self.k_scale)
            jax.block_until_ready(self.v_scale)
        self.staging.give(bufs)

    def write_block(self, block: int, bufs: dict):
        """Stream one spilled block's host buffers back h2d into pool
        block ``block`` (readmission). Donates the pool arrays off-CPU —
        an in-place scatter, same discipline as the paged prefill
        writer; on a tensor-parallel pool the program runs under
        shard_map so the pool comes back exactly as the sharded step
        programs expect it."""
        from .kv_cache import _tier_inject
        donate = jax.default_backend() != "cpu"
        bid = np.int32(block)
        if self.quantized:
            self.k, self.v, self.k_scale, self.v_scale = _tier_inject(
                donate, self.kv_dtype, self.tp)(
                    self.k, self.v, self.k_scale, self.v_scale,
                    jnp.asarray(bufs["k"]), jnp.asarray(bufs["v"]),
                    jnp.asarray(bufs["k_scale"]),
                    jnp.asarray(bufs["v_scale"]), bid)
        else:
            self.k, self.v = _tier_inject(donate, False, self.tp)(
                self.k, self.v, jnp.asarray(bufs["k"]),
                jnp.asarray(bufs["v"]), bid)

    def drop(self, block: int) -> bool:
        """Release one pin and return the block to the free heap iff the
        count hit zero. The paged cache's private-tail release: the heap
        gets the block back EXACTLY once (a second drop raises through
        :meth:`unref`'s below-zero guard), and a block still pinned by
        other readers — a donated prefix block with live hits — merely
        loses this reader. Returns whether the block was freed."""
        if self.unref(block) == 0:
            self.free(block)
            return True
        return False
