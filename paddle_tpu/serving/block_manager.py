"""Ref-counted block pool backing the automatic prefix cache.

The pool is the block-granular half of the serving KV story ("Ragged
Paged Attention", PAPERS.md): two dense device arrays
``[L, num_blocks, block_size, Hkv, D]`` holding published prompt-prefix
KV blocks, plus host-side bookkeeping — a free-block min-heap (same
O(log n) allocator discipline as :class:`~.kv_cache.SlotKVCache`) and a
per-block reference count.

Division of labor: this class owns *physical* blocks (allocation,
refcounts, storage); :class:`~.prefix_cache.PrefixCache` owns *logical*
identity (the hash-trie from token content to block id, LRU eviction
order, hit/miss accounting). Blocks move between them only through the
compile-once copy programs in ``kv_cache.py`` — a published block is
written exactly once (at publish) and only ever read afterwards, so
sharing a block between concurrent sequences can never alias their
divergent continuations (each hit COPIES the block into the private
slot; see the COW note in ``prefix_cache.py``).
"""
from __future__ import annotations

import heapq

import jax.numpy as jnp
import numpy as np


class BlockManager:
    """Physical block pool: device arrays + free heap + refcounts."""

    def __init__(self, num_layers, num_blocks, block_size, num_kv_heads,
                 head_dim, dtype=jnp.float32):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        shape = (num_layers, self.num_blocks, self.block_size,
                 num_kv_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        self._free_heap = list(range(self.num_blocks))
        self._free_set = set(self._free_heap)
        self._ref = np.zeros(self.num_blocks, np.int32)

    # ---------------------------------------------------------- allocator
    @property
    def num_free(self) -> int:
        return len(self._free_set)

    @property
    def num_used(self) -> int:
        """Live blocks (published + pinned) — the ``kv_prefix_blocks``
        gauge on ``/metrics``."""
        return self.num_blocks - self.num_free

    def alloc(self):
        """Claim a free block (lowest id first, deterministic); None when
        the pool is exhausted (the caller evicts or skips publishing)."""
        if not self._free_set:
            return None
        block = heapq.heappop(self._free_heap)
        self._free_set.discard(block)
        return block

    def free(self, block: int):
        if block in self._free_set:
            raise ValueError(f"block {block} double-freed")
        if self._ref[block]:
            raise ValueError(
                f"block {block} freed with refcount {int(self._ref[block])}")
        heapq.heappush(self._free_heap, block)
        self._free_set.add(block)

    # ---------------------------------------------------------- refcounts
    def ref(self, block: int):
        """Pin a block (a sequence's admission matched it)."""
        self._ref[block] += 1

    def unref(self, block: int) -> int:
        """Release one pin; returns the remaining count."""
        if self._ref[block] <= 0:
            raise ValueError(f"block {block} unref'd below zero")
        self._ref[block] -= 1
        return int(self._ref[block])

    def refcount(self, block: int) -> int:
        return int(self._ref[block])
