"""Jitted prefill / decode-step functions for the LLaMA decode path.

This is the split of the old monolithic ``_llama_generate_fn``
(models/llama.py) into the two programs a continuous-batching engine
needs:

- ``prefill`` — one prompt's full forward, returning its per-layer K/V
  (to be installed into a cache slot), the first sampled token, and the
  advanced PRNG key. Prompt lengths are padded to buckets by the engine,
  so compilations are bounded by the bucket count, not the prompt count.
- ``decode_steps`` — ``n_steps`` single-token ticks over ALL slots in
  one device call. Shapes depend only on ``(num_slots, max_seq_len)``:
  per-slot sampling knobs (temperature / top-k / PRNG key) and per-slot
  ragged ``lengths`` are runtime ARRAYS, not trace constants, so one
  compilation serves every request mix — the old path recompiled per
  ``(max_new_tokens, temperature, top_k)`` tuple.

Per-row raggedness: each slot writes its new K/V at its own
``lengths[b]`` (scatter) and attends over ``lengths[b]+1`` entries —
through the ragged Pallas kernel (``decode_attention_pallas``) or the
jnp oracle with identical semantics. Rows of freed/empty slots compute
garbage that is never read (their scatter lands in row 0 of a dead slot
and the engine never surfaces their sampled tokens).

Sampling is row-vectorized: greedy where ``temps <= 0``, else top-k
temperature sampling with a per-row ``jax.random.categorical`` under a
per-row key; keys advance by the same split-per-token walk the old path
used, so a request's token stream depends only on its own key — not on
batch composition, admission timing, or the other slots (the property
the mid-flight-admission tests pin down).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ..kernels.flash_attention import attention as _attention
from ..kernels.pallas_decode import (decode_attention_pallas,
                                     decode_attention_reference)
from ..kernels.pallas_paged_decode import (paged_decode_attention_pallas,
                                           paged_decode_attention_reference)
from ..kernels.pallas_ragged_attention import (ragged_attention_reference,
                                               ragged_paged_attention_pallas)
from ..models.llama import _apply_rope, _qkv_bshd, _rms, _rope_tables, \
    _swiglu_raw
from .kv_cache import quantize_kv_rows, quantize_kv_rows_fp8

NEG_INF = -1e30

_STACK_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
               "input_ln", "post_ln")

#: the decode-path projection matmuls quantize_weights=True converts
#: (README "Quantized serving"); norms and the embedding gather stay
#: full-precision (a gather reads one row — there is no bandwidth to
#: win — and norm weights are tiny but numerically load-bearing)
_WEIGHT_QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def llama_decode_params(model):
    """Raw-array param pytree (+tied flag) for the decode programs."""
    p = dict(
        embed=model.embed_tokens.value, wq=model.wq.value,
        wk=model.wk.value, wv=model.wv.value, wo=model.wo.value,
        w_gate=model.w_gate.value, w_up=model.w_up.value,
        w_down=model.w_down.value, input_ln=model.input_ln.value,
        post_ln=model.post_ln.value, final_norm=model.final_norm.value,
        lm_head=(model.embed_tokens.value if model.lm_head is None
                 else model.lm_head.value))
    return p, model.lm_head is None


# --------------------------------------------- int8 weight-only decode
def quantize_decode_params(params, tied):
    """Convert the decode param pytree to int8 weight-only form — the
    engine's ``quantize_weights=True`` knob (README "Quantized
    serving"), riding the same per-channel absmax machinery as
    ``quantization.ConvertedLinear`` (``quantize_weight_int8``). Each
    projection weight becomes a ``(q int8, scale f32)`` pair — a
    pytree-structure change, so quantized engines key their programs
    apart in a shared jit cache — dequantized per layer inside the
    programs (``_dq_layer``): HBM streams int8, the MXU sees the
    dequantized convert. ``lm_head`` quantizes over its contraction
    axis for the orientation it is used in (tied heads run
    ``embed.T``); the embedding table itself stays full-precision for
    the token gather."""
    from ..quantization import quantize_weight_int8
    out = dict(params)
    for k in _WEIGHT_QUANT_KEYS:
        out[k] = quantize_weight_int8(params[k], reduce_axis=1)
    out["lm_head"] = quantize_weight_int8(params["lm_head"],
                                          reduce_axis=1 if tied else 0)
    return out


def _dq(w, dt):
    """Dequantize one int8 weight-only ``(q, scale)`` pair to ``dt``;
    full-precision arrays pass through untouched (the one branch every
    decode program shares, so quantized and raw params run the same
    impl — the pytree structure IS the trace variant)."""
    if isinstance(w, tuple):
        q, s = w
        return (q.astype(jnp.float32) * s).astype(dt)
    return w


def _dq_layer(lp, dt, a8=False):
    """Per-layer weight handoff: dequantize the 7 projection entries of
    one scanned layer tuple IN the layer body — one layer materializes
    at a time, so the weight stack still streams int8 from HBM — and
    pass everything after them (norm weights, cache slices) through
    untouched. Under ``a8`` (quantize_activations, README "Quantized
    serving") NOTHING dequantizes: the ``(q, scale)`` pairs flow
    straight to the int8×int8 projection helpers (``_a8_apply``), so
    no dequantized weight copy is ever materialized in the layer
    body."""
    if a8:
        return lp
    return tuple(_dq(w, dt) for w in lp[:7]) + tuple(lp[7:])


def _dq_head(params, tied, dt, a8=False):
    """The lm-head matmul operand, dequantized when quantized (tied
    heads transpose AFTER dequant — the scales were laid out for the
    stored orientation). Under ``a8`` the int8 pair passes through for
    the int8×int8 head matmul, pre-oriented: tied pairs transpose data
    AND scales — one int8 transpose, traced once outside the scan."""
    head = params["lm_head"]
    if a8 and isinstance(head, tuple):
        q, s = head
        return (q.T, s.T) if tied else (q, s)
    head = _dq(head, dt)
    return head.T if tied else head


# ------------------------------------------- int8×int8 activation path
# The ``quantize_activations=True`` decode path (README "Quantized
# serving"): every projection input is quantized per-row AT RUNTIME
# (the shared absmax rule, ``quantization.quantize_collective_int8``)
# and the matmul runs int8×int8 on the MXU — ``dot_general`` over the
# narrow operands with int32 accumulate, then ONE fused
# ``(act_scale ⊗ weight_scale)`` rescale post-dot. The projection
# helpers below dispatch on the weight's pytree structure, so the
# dense/w8 paths trace the exact same ops as before (the structure IS
# the trace variant) and the a8 layer body never materializes a
# dequantized weight.
def quantize_act_rows(x):
    """Per-row dynamic int8 activation quantization — each row (absmax
    over the last axis) gets its own fp32 scale. Returns ``(q int8,
    scale f32 [..., 1])``."""
    from ..quantization import quantize_collective_int8
    return quantize_collective_int8(x)


def _a8_apply(qx, sx, w):
    """One int8×int8 projection: quantized activations ``(qx, sx)``
    against an int8 weight-only ``(q, scale)`` pair — int32-accumulate
    dot, fused post-dot rescale. Returns fp32."""
    qw, sw = w
    acc = jax.lax.dot_general(qx, qw, (((qx.ndim - 1,), (0,)), ((), ())),
                              preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * sx * sw.reshape(-1)


def _a8_dot(x, w):
    """Quantize ``x`` per-row and run one int8×int8 projection."""
    qx, sx = quantize_act_rows(x)
    return _a8_apply(qx, sx, w).astype(x.dtype)


def _qkv_proj(hn, lwq, lwk, lwv, nh, nkv, hd):
    """The QKV projections — ``models.llama._qkv_bshd`` verbatim on
    dense weights; under quantize_activations the input quantizes
    per-row ONCE and feeds three int8×int8 dots."""
    if isinstance(lwq, tuple):
        B, S = hn.shape[0], hn.shape[1]
        dt = hn.dtype
        qx, sx = quantize_act_rows(hn)
        q = _a8_apply(qx, sx, lwq).astype(dt).reshape(B, S, nh, hd)
        k = _a8_apply(qx, sx, lwk).astype(dt).reshape(B, S, nkv, hd)
        v = _a8_apply(qx, sx, lwv).astype(dt).reshape(B, S, nkv, hd)
        return q, k, v
    return _qkv_bshd(hn, lwq, lwk, lwv, nh, nkv, hd)


def _swiglu_proj(hn, lg, lu, ld):
    """The SwiGLU MLP — ``models.llama._swiglu_raw`` verbatim on dense
    weights; under quantize_activations gate/up share one per-row act
    quant and down re-quantizes the gated product."""
    if isinstance(lg, tuple):
        qx, sx = quantize_act_rows(hn)
        g = jax.nn.silu(_a8_apply(qx, sx, lg))
        u = _a8_apply(qx, sx, lu)
        return _a8_dot(g * u, ld).astype(hn.dtype)
    return _swiglu_raw(hn, lg, lu, ld)


def _o_proj(attn2, lwo):
    """The attention output projection ``[B, S, nh*hd] @ wo``."""
    if isinstance(lwo, tuple):
        return _a8_dot(attn2, lwo)
    return jnp.einsum("bsd,dh->bsh", attn2, lwo)


def _head_logits(last_h, head):
    """The lm-head matmul ``[B, H] @ head`` (the pair arrives
    pre-oriented from ``_dq_head`` under a8)."""
    if isinstance(head, tuple):
        return _a8_dot(last_h, head)
    return jnp.einsum("bh,hv->bv", last_h, head)


# ------------------------------------------------- int8 block-pool view
# A quantized pool arrives as ONE pytree argument per side —
# ``(data int8, scale f32)`` — so every program signature (and its
# donation spec) is unchanged; these four helpers are the only places
# the programs touch the difference. Appends quantize on write through
# ``kv_cache.quantize_kv_rows`` (THE quantization rule — shared with
# the prefill scatter); attention dequantizes inside the kernels
# (``k_scale``/``v_scale``) or right after the oracle gather.
def _kv_data(pool):
    """The raw storage array of a pool side (shape/dtype queries)."""
    return pool[0] if isinstance(pool, tuple) else pool


def _kv_attn_args(pool_k, pool_v):
    """Unpack both pool sides for an attention call: ``(k, v,
    k_scale, v_scale)`` with None scales on a full-precision pool."""
    if isinstance(pool_k, tuple):
        return pool_k[0], pool_v[0], pool_k[1], pool_v[1]
    return pool_k, pool_v, None, None


def _kv_write(pool_l, phys, row, x):
    """Scatter K/V rows ``x [..., Hkv, D]`` into one layer's pool slice
    at ``(phys, row)`` — quantizing on write on a quantized pool.
    int8 writes data + per-row-per-head scales to the SAME
    coordinates; fp8 is a data-only saturating cast
    (``quantize_kv_rows_fp8``) — its per-BLOCK scale planes are the
    constant 1.0 and are never written by appends (the determinism
    argument in ``BlockManager``'s docstring). Drop-mode both ways: a
    dead row vanishes from data and scales alike."""
    if isinstance(pool_l, tuple):
        data, sc = pool_l
        if data.dtype == jnp.float8_e4m3fn:
            return (data.at[phys, row].set(quantize_kv_rows_fp8(x),
                                           mode="drop"), sc)
        q, s = quantize_kv_rows(x)
        return (data.at[phys, row].set(q, mode="drop"),
                sc.at[phys, row].set(s, mode="drop"))
    return pool_l.at[phys, row].set(x, mode="drop")


def _kv_gather_rows(pool_l, tables, shape4):
    """Gather per-row logical caches through the block tables
    (clip-mode; the suffix-prefill oracle path). ``shape4`` is the
    target ``(G, s_tot, Hkv, D)``. On a quantized pool the rows come
    back in the pool's NATIVE narrow dtype — no dequantized fp copy is
    materialized; the upcast fuses into the attention dots and the
    scales return separately (normalized to ``[G, s_tot, Hkv]``; fp8's
    per-block planes broadcast over each block's rows) for the
    post-dot rescale (``_row_scale_bhqk``). Returns
    ``(rows, scale_rows_or_None)``."""
    if isinstance(pool_l, tuple):
        data, sc = pool_l
        rows = jnp.take(data, tables, axis=0,
                        mode="clip").reshape(shape4)
        if sc.ndim == 2:         # fp8 per-block planes [nb, Hkv]
            srows = jnp.repeat(jnp.take(sc, tables, axis=0, mode="clip"),
                               shape4[-3] // tables.shape[1], axis=1)
        else:                    # int8 per-row planes [nb, bs, Hkv]
            srows = jnp.take(sc, tables, axis=0,
                             mode="clip").reshape(shape4[:-1])
        return rows, srows
    rows = jnp.take(pool_l, tables, axis=0, mode="clip").reshape(shape4)
    return rows, None


def _row_scale_bhqk(srows, grp):
    """Reshape gathered per-KV-row scales ``[G, s_tot, Hkv]`` into the
    ``[G, H, 1, s_tot]`` factor the suffix path's post-dot rescale
    broadcasts against its ``bhqk`` logits/probs — the gather-path
    twin of the kernels' head one-hot trick (each query head h reads
    its KV group's scale)."""
    sf = jnp.repeat(srows, grp, axis=2) if grp > 1 else srows
    return jnp.transpose(sf, (0, 2, 1))[:, :, None, :]


# --------------------------------------------- tensor parallel (TP) plumbing
# Multi-chip tensor-parallel serving (README "Tensor-parallel serving"):
# the engine's ``tp=N`` knob wraps the serving programs in ``shard_map``
# over a 1-D ``("tp",)`` mesh sharded OVER HEADS — wq/wk/wv (and the MLP
# gate/up) column-sharded so each shard computes ``nh/tp`` query heads
# and ``nkv/tp`` KV heads, wo/w_down row-sharded so their matmuls yield
# partial sums, and the paged KV pool partitioned on its head axis (each
# shard owns ``Hkv/tp`` heads of EVERY physical block — int8 scale
# planes partition on the same axis, so the host-side block tables /
# BlockManager / trie bookkeeping stay replicated and untouched).
# Exactly ONE all-reduce site pair per layer — post o-proj and post
# down-proj (``tp_reduce``) — is the only cross-chip traffic;
# ``collective_dtype="int8"`` runs it EQuARX-style block-quantized
# (``quantization.quantized_psum_int8``), cutting wire bytes ~3.5x.
# Attention (the ragged paged kernel or its jnp oracle) runs fully
# local: GQA group ratio nh/nkv is preserved per shard, the span/table
# metadata is replicated, and K/V appends land in the shard's own head
# slice — so donate/truncate/preempt/restore/trie-hit carry shards for
# free. Everything after the final all-reduce (final norm, lm head,
# sampling, the PRNG walk) is replicated math: every shard computes the
# same tokens, which is what lets the host read any one shard's copy.
TP_AXIS = "tp"

_COL_KEYS = ("wq", "wk", "wv", "w_gate", "w_up")   # shard output features
_ROW_KEYS = ("wo", "w_down")                       # shard input features


@functools.lru_cache(maxsize=None)
def _tp_mesh(tp):
    """The serving TP mesh: the first ``tp`` visible devices on one
    ``("tp",)`` axis (CPU-mesh development uses
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``; the test
    suite's conftest forces 8 virtual devices)."""
    devs = jax.devices()
    if tp > len(devs):
        raise ValueError(
            f"tp={tp} exceeds the {len(devs)} visible device(s); on CPU "
            f"set XLA_FLAGS=--xla_force_host_platform_device_count={tp}")
    return Mesh(np.array(devs[:tp]), (TP_AXIS,))


def _tp_validate(nh, nkv, tp):
    if nh % tp or nkv % tp:
        raise ValueError(
            f"tp={tp} must divide num_attention_heads ({nh}) and "
            f"num_key_value_heads ({nkv}): the mesh shards over heads")


#: row chunks per overlapped tp_reduce site — each chunk's collective
#: issues independently so its wire time hides under the neighbouring
#: chunks' (and the next projection's) compute on hardware; rows-only
#: chunking keeps per-row quantization scales (and the byte ledger)
#: exact
_OVERLAP_CHUNKS = 2


def _permute_allreduce(x, tp):
    """Ring reduce-scatter + all-gather over ``collective-permute``
    steps — the fp wire schedule of ``collective_overlap=True`` (README
    "One-kernel decode"; "Fused Computation-Collective Operations",
    PAPERS.md). The hidden axis splits into ``tp`` pieces; ``tp - 1``
    ``ppermute`` hops accumulate each piece's cross-shard sum around
    the ring (reduce-scatter), ``tp - 1`` more hops gather the summed
    pieces back (all-gather). Per device the wire bytes are exactly
    ``2 * (tp-1)/tp`` of the payload — the same model
    ``quantization.collective_wire_bytes`` prices, so the collective
    ledger stays exact to the byte. Accumulation order is fixed by the
    ring (deterministic); at ``tp=2`` every output element is one
    commutative add, bit-equal to ``psum``."""
    idx = jax.lax.axis_index(TP_AXIS)
    shape = x.shape
    hid = shape[-1]
    pieces = jnp.moveaxis(
        x.reshape(shape[:-1] + (tp, hid // tp)), -2, 0)

    def _piece(i):
        return jax.lax.dynamic_index_in_dim(pieces, i % tp, 0,
                                            keepdims=False)

    ring = [(j, (j + 1) % tp) for j in range(tp)]
    # reduce-scatter: after step s, this device's accumulator holds
    # piece (idx + 1 - s) summed over s + 1 consecutive ring devices
    acc = _piece(idx + 1)
    for s in range(1, tp):
        acc = jax.lax.ppermute(acc, TP_AXIS, ring)
        acc = acc + _piece(idx + 1 - s)
    # all-gather: circulate the summed pieces back around the ring,
    # then reorder into hidden order (gathered[s] came from device
    # idx - s, which owns summed piece idx - s + 2 - tp)
    gathered = [acc]
    g = acc
    for _ in range(1, tp):
        g = jax.lax.ppermute(g, TP_AXIS, ring)
        gathered.append(g)
    order = (idx + 2 - tp - jnp.arange(tp)) % tp
    out = jnp.take(jnp.stack(gathered, 0), order, axis=0)
    return jnp.moveaxis(out, 0, -2).reshape(shape).astype(x.dtype)


def _overlap_reduce(base, tp, x):
    """Chunked compute/collective-overlap schedule for one
    ``tp_reduce`` site (``collective_overlap=True``): the partial-sum
    rows split into ``_OVERLAP_CHUNKS`` row chunks and each chunk's
    reduction issues independently — int8 runs the EQuARX quantized
    all-reduce per chunk (wire format preserved), fp runs the chunked
    collective-permute ring — so on hardware each chunk's wire time
    hides under the next chunk's and the following projection's
    compute. Chunking along ROWS only: every row's absmax scale, wire
    payload and reduced value are computed exactly as unchunked, so
    streams AND the ``serving_collective_bytes_total`` ledger are
    byte-identical to the unoverlapped schedule."""
    lead = x.shape[:-1]
    rows = 1
    for d in lead:
        rows *= int(d)
    flat = x.reshape((rows, x.shape[-1]))
    n = max(1, min(_OVERLAP_CHUNKS, rows))
    bounds = [(i * rows) // n for i in range(n + 1)]
    parts = [base(flat[lo:hi])
             for lo, hi in zip(bounds[:-1], bounds[1:])]
    return jnp.concatenate(parts, axis=0).reshape(x.shape)


def _tp_allreduce(collective_dtype, tp, overlap=False):
    """The per-layer cross-shard reduction — ``tp_reduce`` in the layer
    bodies. ``"fp"`` is a plain ``psum``; ``"int8"`` is the EQuARX-style
    block-quantized all-reduce (README "Tensor-parallel serving":
    measured greedy divergence, not assumed zero). ``overlap=True``
    (the engine's ``collective_overlap`` knob) swaps in the chunked
    schedule of :func:`_overlap_reduce` — fp additionally switches from
    one ``psum`` to the ring collective-permute reduce-scatter/
    all-gather (:func:`_permute_allreduce`), byte-identical at tp=2 and
    byte-exact on the wire ledger at every tp."""
    if collective_dtype == "int8":
        from ..quantization import quantized_psum_int8
        base = functools.partial(quantized_psum_int8, axis_name=TP_AXIS,
                                 tp=tp)
    elif overlap:
        base = functools.partial(_permute_allreduce, tp=tp)
    else:
        base = functools.partial(jax.lax.psum, axis_name=TP_AXIS)
    if not overlap:
        return base
    return functools.partial(_overlap_reduce, base, tp)


def _params_pspec(wq8):
    """PartitionSpec pytree matching the decode param dict:
    column-sharded QKV/gate/up, row-sharded o/down, everything else
    (embedding, norms, lm head) replicated. ``wq8`` mirrors the
    int8 weight-only pytree — each quantized leaf is a ``(q, scale)``
    pair whose scale keeps the contraction axis as size 1, so a
    column-sharded weight's per-output-channel scales shard with it
    while a row-sharded weight's scales stay replicated."""
    # NOTE: trailing-None-free specs throughout this module — jax
    # normalizes PartitionSpec(..., "tp", None) to (..., "tp") on
    # program OUTPUTS, and a pool array fed back next step under the
    # un-normalized spelling would read as a different sharding to the
    # pjit cache (one spurious re-specialization per program, breaking
    # the compile-once pin).
    col = PartitionSpec(None, None, TP_AXIS)
    row = PartitionSpec(None, TP_AXIS)
    rep = PartitionSpec()
    spec = dict(embed=rep, input_ln=rep, post_ln=rep, final_norm=rep,
                lm_head=rep)
    for k in _COL_KEYS:
        spec[k] = col
    for k in _ROW_KEYS:
        spec[k] = row
    if wq8:
        for k in _COL_KEYS:
            spec[k] = (col, col)       # scale [L, 1, out] shards with q
        for k in _ROW_KEYS:
            spec[k] = (row, rep)       # scale [L, 1, H] is replicated
        spec["lm_head"] = (rep, rep)
    return spec


def _pool_pspec(kv_quant):
    """PartitionSpec for one pool side: blocks replicated, HEADS
    sharded (axis 3 of ``[L, nb, bs, Hkv, D]``). A quantized pool's
    scale planes partition on the same head axis — int8's per-row
    planes ``[L, nb, bs, Hkv]`` on axis 3, fp8's per-BLOCK planes
    ``[L, nb, Hkv]`` on axis 2. ``kv_quant``: False, "int8"/"fp8", or
    True (int8 back-compat)."""
    data = PartitionSpec(None, None, None, TP_AXIS)
    if kv_quant == "fp8":
        return (data, PartitionSpec(None, None, TP_AXIS))
    if kv_quant:
        return (data, PartitionSpec(None, None, None, TP_AXIS))
    return data


def _prefill_kv_pspec():
    """Spec of the cold prefill's returned K/V ``[L, G, S, Hkv, D]`` —
    always full-precision (quantize-on-write happens in the pool
    scatter, not here), heads sharded on axis 3."""
    return PartitionSpec(None, None, None, TP_AXIS)


def _tp_shard(impl, tp, in_specs, out_specs):
    """shard_map over the serving TP mesh. ``check_vma=False``: the
    replicated outputs (tokens, keys) are replicated by construction —
    every shard runs the same post-all-reduce math — and the sampling
    primitives defeat the automatic replication checker."""
    return jax.shard_map(impl, mesh=_tp_mesh(tp), in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)


def place_tp_params(params, tp, wq8):
    """Commit the decode param pytree onto the TP mesh per
    :func:`_params_pspec` — done ONCE per (model, tp, wq8) by the
    engine (cached model-resident, so rebuilds and fleet replicas share
    the placed arrays and the jit cache never re-uploads)."""
    mesh = _tp_mesh(tp)
    spec = _params_pspec(wq8)

    def _put(leaf, s):
        return jax.device_put(leaf, NamedSharding(mesh, s))

    out = {}
    for k, v in params.items():
        s = spec[k]
        if isinstance(v, tuple):
            out[k] = tuple(_put(leaf, ls) for leaf, ls in zip(v, s))
        else:
            out[k] = _put(v, s)
    return out


def _apply_rope_rows(x, sin_p, cos_p):
    """Rope with a DIFFERENT position per batch row (ragged decode).

    x: [B, 1, H, D]; sin_p/cos_p: [B, D] gathered at each row's position.
    """
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (x * cos_p[:, None, None, :]
            + rotated * sin_p[:, None, None, :]).astype(x.dtype)


def sample_rows(logits, keys, temps, top_ks):
    """Per-row sampling: greedy where temps<=0, else top-k temperature.

    logits: [B, V]; keys: [B, 2] uint32; temps: [B] f32; top_ks: [B] i32
    (<=0 = no top-k filter). All knobs are runtime values — no retrace.
    """
    V = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def _sampled(_):
        lg = logits.astype(jnp.float32) / jnp.maximum(temps, 1e-6)[:, None]
        k_eff = jnp.clip(jnp.where(top_ks <= 0, V, top_ks), 1, V)
        srt = jnp.sort(lg, axis=-1)  # ascending; kth-largest = srt[V - k]
        kth = jnp.take_along_axis(srt, (V - k_eff)[:, None], axis=-1)
        lg = jnp.where(lg < kth, NEG_INF, lg)
        sampled = jax.vmap(jax.random.categorical)(keys, lg)
        return jnp.where(temps > 0.0, sampled.astype(jnp.int32), greedy)

    # all-greedy batches (the model.generate default) must not pay the
    # [B, V] sort + categorical every tick just to discard the result
    return jax.lax.cond(jnp.any(temps > 0.0), _sampled,
                        lambda _: greedy, None)


# ------------------------------------------------------------------ prefill
def _prefill_impl(params, ids, lengths, keys, temps, top_ks, *, nh, nkv,
                  hd, eps, theta, tied, tp_reduce=None, a8=False):
    """Batched prefill: ids [G, S_pad] (right-padded prompts), lengths
    [G] real token counts, per-row keys/temps/top_ks.

    Returns (pk, pv, tok0, keys') with pk/pv: [L, G, S_pad, Hkv, D] —
    one admission group in one device call (the engine pads G to a power
    of two so the compile count stays bounded). Padding rows/columns
    produce K/V garbage past each row's ``lengths`` — causal masking
    keeps it out of every real position's attention, and the cache slot
    masks it by ``lengths`` until decode overwrites it.
    """
    B, S = ids.shape
    sin, cos = _rope_tables(S, hd, theta)
    stack = tuple(params[k] for k in _STACK_KEYS)
    wdt = params["embed"].dtype
    head = _dq_head(params, tied, wdt, a8)

    def prefill_layer(h, lp):
        (lwq, lwk, lwv, lwo, lg, lu, ld, lin, lpost) = \
            _dq_layer(lp, wdt, a8)
        hn = _rms(h, lin, eps)
        q, k, v = _qkv_proj(hn, lwq, lwk, lwv, nh, nkv, hd)
        q = _apply_rope(q, sin, cos)
        k = _apply_rope(k, sin, cos)
        attn = _attention(q, k, v, causal=True)
        o = _o_proj(attn.reshape(B, S, nh * hd), lwo)
        h = h + (o if tp_reduce is None else tp_reduce(o))
        m = _swiglu_proj(_rms(h, lpost, eps), lg, lu, ld)
        h = h + (m if tp_reduce is None else tp_reduce(m))
        return h, (k, v)

    x = jnp.take(params["embed"], ids, axis=0)
    x, (pk, pv) = jax.lax.scan(prefill_layer, x, stack)
    last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None], axis=1)[:, 0]  # [G, H]
    last_h = _rms(last, params["final_norm"], eps)
    logits = _head_logits(last_h, head)
    both = jax.vmap(jax.random.split)(keys)  # [G, 2, 2]
    tok0 = sample_rows(logits, both[:, 1], temps, top_ks)
    return pk, pv, tok0, both[:, 0]


def build_prefill_fn(*, nh, nkv, hd, eps, theta, tied, tp=1,
                     collective_dtype="fp", wq8=False, a8=False):
    """One jitted prefill; jax retraces per (group, prompt-bucket)
    shape — both padded to powers of two by the engine. ``tp > 1``
    wraps it in shard_map over the heads-sharded mesh (README
    "Tensor-parallel serving"): the returned K/V carries each shard's
    ``Hkv/tp`` heads, partitioned exactly like the pool it is about to
    be scattered into."""
    if int(tp) > 1:
        tp = int(tp)
        _tp_validate(nh, nkv, tp)
        impl = functools.partial(
            _prefill_impl, nh=nh // tp, nkv=nkv // tp, hd=hd, eps=eps,
            theta=theta, tied=tied,
            tp_reduce=_tp_allreduce(collective_dtype, tp), a8=a8)
        rep = PartitionSpec()
        return jax.jit(_tp_shard(
            impl, tp,
            in_specs=(_params_pspec(wq8),) + (rep,) * 5,
            out_specs=(_prefill_kv_pspec(), _prefill_kv_pspec(),
                       rep, rep)))
    return jax.jit(functools.partial(
        _prefill_impl, nh=nh, nkv=nkv, hd=hd, eps=eps, theta=theta,
        tied=tied, a8=a8))


# ------------------------------------------------------------ suffix prefill
def _apply_rope_grid(x, sin_p, cos_p):
    """Rope with a different position per (row, column) — suffix prefill.

    x: [G, S, H, D]; sin_p/cos_p: [G, S, D] gathered at each token's
    global position (prefix offset + column).
    """
    d = x.shape[-1]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    rotated = jnp.concatenate([-x2, x1], axis=-1)
    return (x * cos_p[:, :, None, :]
            + rotated * sin_p[:, :, None, :]).astype(x.dtype)


def _suffix_prefill_impl(params, cache_k, cache_v, slots, prefix_lens, ids,
                         suffix_lens, keys, temps, top_ks, *, nh, nkv, hd,
                         eps, theta, tied):
    """Prefill only the UNCOVERED suffix of prompts whose leading blocks
    a prefix-cache hit already installed into their slots.

    ids: [G, S_pad] right-padded suffix token ids; prefix_lens: [G] rows
    already valid in each row's slot (the installed cached blocks);
    suffix_lens: [G] real suffix token counts; slots: [G] slot indices
    (padding rows carry ``num_slots`` so their writes drop).

    Each suffix token at column i lives at global position
    ``prefix_lens[g] + i``: its K/V scatter into the slot at that row
    (rope'd at that position) and its query attends over rows
    ``0..pos`` — cached prefix plus the suffix written so far, exactly
    the rows a cold full prefill would attend. Shapes depend only on
    (G_pad, S_pad, cache geometry): prefix/suffix lengths, slot ids, and
    sampling knobs are runtime arrays, so compilations stay bounded by
    the same pow2 buckets as the cold prefill.

    Returns (cache_k', cache_v', tok0, keys').
    """
    G, S = ids.shape
    num_slots, s_max = cache_k.shape[1], cache_k.shape[2]
    sin, cos = _rope_tables(s_max, hd, theta)
    stack = tuple(params[k] for k in _STACK_KEYS)
    wdt = params["embed"].dtype
    head = _dq_head(params, tied, wdt)

    # gather each row's slot cache: [L, G, s_max, Hkv, D]. Padding rows
    # point at slot index num_slots — the gather clips (harmless read of
    # the last slot), every write below drops.
    kc0 = jnp.take(cache_k, slots, axis=1, mode="clip")
    vc0 = jnp.take(cache_v, slots, axis=1, mode="clip")
    pos = prefix_lens[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    sin_p = jnp.take(sin, pos, axis=0, mode="clip")   # [G, S, D]
    cos_p = jnp.take(cos, pos, axis=0, mode="clip")
    g_idx = jnp.arange(G)[:, None]
    rows = jnp.arange(s_max, dtype=jnp.int32)
    # causal-over-ragged mask: query at global pos p sees rows r <= p
    mask = rows[None, None, :] <= pos[:, :, None]        # [G, S, s_max]
    # rows ever valid in this slot (prefix + the S suffix writes); rows
    # past that may hold a prior sequence's garbage — zeroed out of PV
    row_valid = rows[None, :] < (prefix_lens + S)[:, None]  # [G, s_max]
    grp = nh // nkv
    scale = 1.0 / (hd ** 0.5)

    def layer(h, lp):
        (lwq, lwk, lwv, lwo, lg, lu, ld, lin, lpost, ck, cv) = \
            _dq_layer(lp, wdt)
        hn = _rms(h, lin, eps)
        q, k, v = _qkv_bshd(hn, lwq, lwk, lwv, nh, nkv, hd)
        q = _apply_rope_grid(q, sin_p, cos_p)
        k = _apply_rope_grid(k, sin_p, cos_p)
        # ragged scatter: column i appends at its row's prefix_len + i;
        # out-of-range positions (padding rows, clamped tails) drop
        ck = ck.at[g_idx, pos].set(k, mode="drop")
        cv = cv.at[g_idx, pos].set(v, mode="drop")
        kf = jnp.repeat(ck, grp, axis=2) if grp > 1 else ck
        vf = jnp.repeat(cv, grp, axis=2) if grp > 1 else cv
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kf,
                            preferred_element_type=jnp.float32) * scale
        logits = jnp.where(mask[:, None], logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        # exact zeros on masked cols + zeroed garbage rows: stale cache
        # rows can be anything (0 * NaN = NaN)
        probs = jnp.where(mask[:, None], probs, 0.0)
        vf = jnp.where(row_valid[:, :, None, None], vf, 0.0)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), vf)
        h = h + jnp.einsum("bsd,dh->bsh", attn.reshape(G, S, nh * hd), lwo)
        h = h + _swiglu_raw(_rms(h, lpost, eps), lg, lu, ld)
        return h, (ck, cv)

    x = jnp.take(params["embed"], ids, axis=0)
    x, (nkc, nvc) = jax.lax.scan(layer, x, stack + (kc0, vc0))
    last = jnp.take_along_axis(
        x, (suffix_lens - 1)[:, None, None], axis=1)[:, 0]  # [G, H]
    last_h = _rms(last, params["final_norm"], eps)
    logits = jnp.einsum("bh,hv->bv", last_h, head)
    both = jax.vmap(jax.random.split)(keys)  # [G, 2, 2]
    tok0 = sample_rows(logits, both[:, 1], temps, top_ks)
    # scatter the updated per-slot caches back (padding rows drop)
    cache_k = cache_k.at[:, slots].set(nkc, mode="drop")
    cache_v = cache_v.at[:, slots].set(nvc, mode="drop")
    return cache_k, cache_v, tok0, both[:, 0]


def build_suffix_prefill_fn(*, nh, nkv, hd, eps, theta, tied, donate=None):
    """One jitted suffix prefill; retraces per (group, suffix-bucket)
    shape — both padded to powers of two by the engine, same bounded
    compile set as the cold prefill."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    return jax.jit(
        functools.partial(_suffix_prefill_impl, nh=nh, nkv=nkv, hd=hd,
                          eps=eps, theta=theta, tied=tied),
        donate_argnums=(1, 2) if donate else ())


# ----------------------------------------------------- paged suffix prefill
def _paged_suffix_prefill_impl(params, pool_k, pool_v, tables, prefix_lens,
                               ids, suffix_lens, keys, temps, top_ks, *,
                               nh, nkv, hd, eps, theta, tied,
                               tp_reduce=None, a8=False):
    """Suffix prefill through per-row block tables: the paged twin of
    ``_suffix_prefill_impl``, reading/writing the BlockManager pool
    instead of per-slot dense caches.

    tables: [G, max_blocks] int32 physical block ids (sentinel
    ``num_blocks`` marks unmapped entries and padding rows). Suffix
    token K/V at column i lands at logical position
    ``prefix_lens[g] + i`` -> physical ``(tables[g, pos//bs], pos%bs)``
    — always a block the row privately owns, because the covered prefix
    is block-aligned and everything past it was freshly allocated. The
    shared prefix blocks are READ through the same table but never
    written: that is the zero-copy COW discipline in one line.

    This program is ALSO the chunked-prefill program (engine
    ``prefill_chunk``, README "Chunked prefill"): a long cold prompt's
    chunk c is just a "suffix" whose ``prefix_lens`` is the host resume
    offset of the rows chunks 0..c-1 already wrote through the table —
    the offset machinery is row-exact, so nothing new is needed at this
    layer. The engine buckets chunk lengths on ``prefill_chunk`` (full
    chunks share ONE bucket; only final remainders ride the pow2 grid)
    and discards tok0/keys' for every non-final chunk, so the PRNG
    advances exactly once per prompt — token streams stay byte-identical
    to a one-shot prefill.

    Shapes depend only on (G_pad, S_pad, pool geometry, max_blocks);
    tables/lengths/knobs are runtime arrays, so the compile set stays
    the same pow2 (group, bucket) grid as the dense suffix path.

    Returns (pool_k', pool_v', tok0, keys'). On a quantized pool
    (int8 or fp8) each side arrives (and returns) as a
    ``(data, scale)`` pair: suffix K/V quantize on write
    (``_kv_write``) and the in-program attention reads the pool
    NATIVELY — the table gather keeps the narrow dtype
    (``_kv_gather_rows``), the upcast fuses into the attention dots,
    and the scales apply post-dot — no materialized fp round-trip.
    """
    G, S = ids.shape
    nb, bs = _kv_data(pool_k).shape[1], _kv_data(pool_k).shape[2]
    mb = tables.shape[1]
    s_tot = mb * bs
    sin, cos = _rope_tables(s_tot, hd, theta)
    stack = tuple(params[k] for k in _STACK_KEYS)
    wdt = params["embed"].dtype
    head = _dq_head(params, tied, wdt, a8)

    pos = prefix_lens[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
    sin_p = jnp.take(sin, pos, axis=0, mode="clip")   # [G, S, D]
    cos_p = jnp.take(cos, pos, axis=0, mode="clip")
    rows = jnp.arange(s_tot, dtype=jnp.int32)
    # causal-over-ragged mask: query at global pos p sees rows r <= p
    mask = rows[None, None, :] <= pos[:, :, None]        # [G, S, s_tot]
    # rows ever valid for this row's attention; later rows may hold
    # clip-gathered garbage from sentinel entries — zeroed out of PV
    row_valid = rows[None, :] < (prefix_lens + S)[:, None]  # [G, s_tot]
    grp = nh // nkv
    scale = 1.0 / (hd ** 0.5)
    # pool write coordinates. Unlike the dense path (which scatters all
    # S columns into the slot and relies on lengths-masking), padding
    # columns here MUST drop — a junk write into the pool could land in
    # a block another sequence owns only via a bug, but dropping keeps
    # the invariant airtight: only (col < suffix_len) positions write.
    bi = jnp.minimum(pos // bs, mb - 1)
    phys = jnp.take_along_axis(tables, bi, axis=1)        # [G, S]
    cols = jnp.arange(S, dtype=jnp.int32)[None, :]
    phys = jnp.where(cols < suffix_lens[:, None], phys, nb)
    prow = pos % bs

    def layer(h, lp):
        (lwq, lwk, lwv, lwo, lg, lu, ld, lin, lpost, pk_l, pv_l) = \
            _dq_layer(lp, wdt, a8)
        hn = _rms(h, lin, eps)
        q, k, v = _qkv_proj(hn, lwq, lwk, lwv, nh, nkv, hd)
        q = _apply_rope_grid(q, sin_p, cos_p)
        k = _apply_rope_grid(k, sin_p, cos_p)
        # write the suffix K/V through the table (quantize-on-write on
        # a quantized pool), then gather each row's logical cache
        # (shared prefix + own suffix) in the pool's NATIVE dtype — the
        # upcast fuses into the attention dots and the scales apply
        # POST-dot (``_row_scale_bhqk``), so a quantized pool never
        # round-trips through a materialized fp copy; the causal mask
        # keeps columns from seeing rows past their position
        pk_l = _kv_write(pk_l, phys, prow, k)
        pv_l = _kv_write(pv_l, phys, prow, v)
        ck, ksr = _kv_gather_rows(pk_l, tables, (G, s_tot, nkv, hd))
        cv, vsr = _kv_gather_rows(pv_l, tables, (G, s_tot, nkv, hd))
        kf = jnp.repeat(ck, grp, axis=2) if grp > 1 else ck
        vf = jnp.repeat(cv, grp, axis=2) if grp > 1 else cv
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kf.astype(q.dtype),
                            preferred_element_type=jnp.float32) * scale
        if ksr is not None:
            logits = logits * _row_scale_bhqk(ksr, grp)
        logits = jnp.where(mask[:, None], logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        probs = jnp.where(mask[:, None], probs, 0.0)
        if vsr is not None:
            probs = probs * _row_scale_bhqk(vsr, grp)
        vf = jnp.where(row_valid[:, :, None, None], vf,
                       jnp.zeros_like(vf))
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype),
                          vf.astype(q.dtype))
        o = _o_proj(attn.reshape(G, S, nh * hd), lwo)
        h = h + (o if tp_reduce is None else tp_reduce(o))
        m = _swiglu_proj(_rms(h, lpost, eps), lg, lu, ld)
        h = h + (m if tp_reduce is None else tp_reduce(m))
        return h, (pk_l, pv_l)

    x = jnp.take(params["embed"], ids, axis=0)
    x, (npk, npv) = jax.lax.scan(layer, x, stack + (pool_k, pool_v))
    last = jnp.take_along_axis(
        x, (suffix_lens - 1)[:, None, None], axis=1)[:, 0]  # [G, H]
    last_h = _rms(last, params["final_norm"], eps)
    logits = _head_logits(last_h, head)
    both = jax.vmap(jax.random.split)(keys)  # [G, 2, 2]
    tok0 = sample_rows(logits, both[:, 1], temps, top_ks)
    return npk, npv, tok0, both[:, 0]


def build_paged_suffix_prefill_fn(*, nh, nkv, hd, eps, theta, tied,
                                  donate=None, tp=1,
                                  collective_dtype="fp", kv_quant=False,
                                  wq8=False, a8=False):
    """One jitted paged suffix prefill — doubling as THE chunked-prefill
    program (see ``_paged_suffix_prefill_impl``); retraces per (group,
    bucket) shape — same bounded pow2 grid as the dense suffix path.
    ``tp > 1`` runs it sharded over heads with the pool partitioned per
    shard (README "Tensor-parallel serving")."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    if int(tp) > 1:
        tp = int(tp)
        _tp_validate(nh, nkv, tp)
        impl = functools.partial(
            _paged_suffix_prefill_impl, nh=nh // tp, nkv=nkv // tp,
            hd=hd, eps=eps, theta=theta, tied=tied,
            tp_reduce=_tp_allreduce(collective_dtype, tp), a8=a8)
        rep = PartitionSpec()
        pool = _pool_pspec(kv_quant)
        return jax.jit(_tp_shard(
            impl, tp,
            in_specs=(_params_pspec(wq8), pool, pool) + (rep,) * 7,
            out_specs=(pool, pool, rep, rep)),
            donate_argnums=(1, 2) if donate else ())
    return jax.jit(
        functools.partial(_paged_suffix_prefill_impl, nh=nh, nkv=nkv, hd=hd,
                          eps=eps, theta=theta, tied=tied, a8=a8),
        donate_argnums=(1, 2) if donate else ())


# -------------------------------------------------------------- decode step
def _decode_steps_impl(params, cache_k, cache_v, tokens, lengths, keys,
                       temps, top_ks, *, n_steps, nh, nkv, hd, eps, theta,
                       tied, decode_attn):
    """``n_steps`` fused single-token decode ticks over all slots.

    tokens:  [B] int32 — each slot's last sampled token
    lengths: [B] int32 — valid cache rows per slot (ragged)
    keys:    [B, 2] uint32; temps: [B] f32; top_ks: [B] int32

    Returns (toks [n_steps, B], cache_k', cache_v', keys').
    """
    B = tokens.shape[0]
    s_max = cache_k.shape[2]
    sin, cos = _rope_tables(s_max, hd, theta)
    stack = tuple(params[k] for k in _STACK_KEYS)
    wdt = params["embed"].dtype
    head = _dq_head(params, tied, wdt)

    def one_step(carry, _):
        tok, ck_all, cv_all, lens, kys = carry
        x = jnp.take(params["embed"], tok[:, None], axis=0)  # [B,1,H]
        sin_p = jnp.take(sin, lens, axis=0)  # [B, D] per-row position
        cos_p = jnp.take(cos, lens, axis=0)

        def layer(h, xs):
            lwq, lwk, lwv, lwo, lg, lu, ld, lin, lpost, ck, cv = \
                _dq_layer(xs, wdt)
            hn = _rms(h, lin, eps)
            q, k, v = _qkv_bshd(hn, lwq, lwk, lwv, nh, nkv, hd)
            q = _apply_rope_rows(q, sin_p, cos_p)
            k = _apply_rope_rows(k, sin_p, cos_p)
            # ragged scatter: each row appends at its own position
            ck = ck.at[jnp.arange(B), lens].set(k[:, 0])
            cv = cv.at[jnp.arange(B), lens].set(v[:, 0])
            if decode_attn == "pallas":
                attn = decode_attention_pallas(q[:, 0], ck, cv, lens + 1)
            else:
                attn = decode_attention_reference(q[:, 0], ck, cv, lens + 1)
            h = h + jnp.einsum("bsd,dh->bsh",
                               attn.reshape(B, 1, nh * hd), lwo)
            h = h + _swiglu_raw(_rms(h, lpost, eps), lg, lu, ld)
            return h, (ck, cv)

        x, (nck, ncv) = jax.lax.scan(layer, x, stack + (ck_all, cv_all))
        last = _rms(x[:, 0], params["final_norm"], eps)
        logits = jnp.einsum("bh,hv->bv", last, head)
        both = jax.vmap(jax.random.split)(kys)  # [B, 2, 2]
        nxt = sample_rows(logits, both[:, 1], temps, top_ks)
        return (nxt, nck, ncv, lens + 1, both[:, 0]), nxt

    carry0 = (tokens, cache_k, cache_v, lengths, keys)
    (_, ck, cv, _, kf), toks = jax.lax.scan(one_step, carry0, None,
                                            length=n_steps)
    return toks, ck, cv, kf


def build_decode_steps_fn(*, n_steps, nh, nkv, hd, eps, theta, tied,
                          decode_attn, donate=None):
    if donate is None:
        donate = jax.default_backend() != "cpu"
    return jax.jit(
        functools.partial(
            _decode_steps_impl, n_steps=n_steps, nh=nh, nkv=nkv, hd=hd,
            eps=eps, theta=theta, tied=tied, decode_attn=decode_attn),
        donate_argnums=(1, 2) if donate else ())


# ------------------------------------------------------- paged decode step
def _paged_decode_steps_impl(params, pool_k, pool_v, tables, tokens,
                             lengths, keys, temps, top_ks, *, n_steps, nh,
                             nkv, hd, eps, theta, tied, decode_attn):
    """``n_steps`` fused single-token ticks over all slots, KV living in
    the BlockManager pool and addressed through per-slot block tables.

    tables:  [B, max_blocks] int32 — physical block ids per slot
             (sentinel ``num_blocks`` on dead slots / unmapped tails,
             so their appends DROP instead of corrupting a shared pool
             block — the one hazard the dense path never had)
    tokens/lengths/keys/temps/top_ks: as in ``_decode_steps_impl``.

    The engine pre-grows every active slot's table to cover
    ``lengths + n_steps`` rows, so a fused chunk can cross block
    boundaries without host intervention. Shapes depend only on
    (num_slots, max_blocks, pool geometry): one compilation per
    ``n_steps`` serves every request/table mix — the compile-once
    contract is unchanged from the dense engine.

    Returns (toks [n_steps, B], pool_k', pool_v', keys').
    """
    B = tokens.shape[0]
    nb, bs = pool_k.shape[1], pool_k.shape[2]
    mb = tables.shape[1]
    s_tot = mb * bs
    sin, cos = _rope_tables(s_tot, hd, theta)
    stack = tuple(params[k] for k in _STACK_KEYS)
    wdt = params["embed"].dtype
    head = _dq_head(params, tied, wdt)

    def one_step(carry, _):
        tok, pk_all, pv_all, lens, kys = carry
        x = jnp.take(params["embed"], tok[:, None], axis=0)  # [B,1,H]
        sin_p = jnp.take(sin, lens, axis=0, mode="clip")
        cos_p = jnp.take(cos, lens, axis=0, mode="clip")
        # append coordinates: each row writes at its own logical length;
        # rows past the logical capacity (can't happen while budgets are
        # validated — belt-and-braces) and dead slots (sentinel tables)
        # both DROP rather than clamp into someone else's block
        bi = jnp.minimum(lens // bs, mb - 1)
        phys = jnp.take_along_axis(tables, bi[:, None], axis=1)[:, 0]
        phys = jnp.where(lens < s_tot, phys, nb)
        prow = lens % bs

        def layer(h, xs):
            lwq, lwk, lwv, lwo, lg, lu, ld, lin, lpost, pk_l, pv_l = \
                _dq_layer(xs, wdt)
            hn = _rms(h, lin, eps)
            q, k, v = _qkv_bshd(hn, lwq, lwk, lwv, nh, nkv, hd)
            q = _apply_rope_rows(q, sin_p, cos_p)
            k = _apply_rope_rows(k, sin_p, cos_p)
            # ragged append through the table (dead slots drop)
            pk_l = pk_l.at[phys, prow].set(k[:, 0], mode="drop")
            pv_l = pv_l.at[phys, prow].set(v[:, 0], mode="drop")
            if decode_attn == "pallas":
                attn = paged_decode_attention_pallas(
                    q[:, 0], pk_l, pv_l, tables, lens + 1)
            else:
                attn = paged_decode_attention_reference(
                    q[:, 0], pk_l, pv_l, tables, lens + 1)
            h = h + jnp.einsum("bsd,dh->bsh",
                               attn.reshape(B, 1, nh * hd), lwo)
            h = h + _swiglu_raw(_rms(h, lpost, eps), lg, lu, ld)
            return h, (pk_l, pv_l)

        x, (npk, npv) = jax.lax.scan(layer, x, stack + (pk_all, pv_all))
        last = _rms(x[:, 0], params["final_norm"], eps)
        logits = jnp.einsum("bh,hv->bv", last, head)
        both = jax.vmap(jax.random.split)(kys)  # [B, 2, 2]
        nxt = sample_rows(logits, both[:, 1], temps, top_ks)
        return (nxt, npk, npv, lens + 1, both[:, 0]), nxt

    carry0 = (tokens, pool_k, pool_v, lengths, keys)
    (_, pk, pv, _, kf), toks = jax.lax.scan(one_step, carry0, None,
                                            length=n_steps)
    return toks, pk, pv, kf


def build_paged_decode_steps_fn(*, n_steps, nh, nkv, hd, eps, theta, tied,
                                decode_attn, donate=None):
    if donate is None:
        donate = jax.default_backend() != "cpu"
    return jax.jit(
        functools.partial(
            _paged_decode_steps_impl, n_steps=n_steps, nh=nh, nkv=nkv,
            hd=hd, eps=eps, theta=theta, tied=tied, decode_attn=decode_attn),
        donate_argnums=(1, 2) if donate else ())


# ------------------------------------------------------ unified ragged step
def _fused_decode_tick(params, stack, head, tables, sin, cos, tok, pk_all,
                       pv_all, lens, kys, app_mask, temps, top_ks, *, nh,
                       nkv, hd, eps, decode_attn, tp_reduce=None,
                       a8=False, fused=False):
    """ONE fused decode tick over all rows — THE shared tail body of
    the unified ragged step's scan and the multi-tick step's
    while_loop (the two must compute identically or ``decode_ticks>1``
    streams could drift from the single-tick baseline). ``app_mask``
    [R] int32 is 1 where the row's append/length-advance is real (the
    ragged tail's ``dec_mask``; the multi-tick tail's alive mask) —
    masked rows drop their append and attend at their frozen length.
    Returns ``(next_tok, pk', pv', keys')``; the CALLER advances
    ``lens`` by ``app_mask``.

    ``fused=True`` (the engine's ``fused_tick`` knob, README
    "One-kernel decode") dispatches the tick to
    ``kernels.pallas_fused_decode_tick`` — ONE whole-tick
    ``pallas_call`` on the single-chip Pallas geometry (the layer loop
    as the grid dimension, sampling epilogue included), the jnp oracle
    that replays THIS function's op sequence everywhere else — so a
    tick is O(1) device launches instead of O(layers), byte-identical
    either way.
    """
    if fused:
        from ..kernels.pallas_fused_decode_tick import fused_decode_tick
        return fused_decode_tick(
            params, stack, head, tables, sin, cos, tok, pk_all, pv_all,
            lens, kys, app_mask, temps, top_ks, nh=nh, nkv=nkv, hd=hd,
            eps=eps, decode_attn=decode_attn, tp_reduce=tp_reduce, a8=a8)
    R = tok.shape[0]
    nb, bs = _kv_data(pk_all).shape[1], _kv_data(pk_all).shape[2]
    mb = tables.shape[1]
    s_tot = mb * bs
    wdt = params["embed"].dtype
    x = jnp.take(params["embed"], tok[:, None], axis=0)     # [R, 1, H]
    sin_r = jnp.take(sin, lens, axis=0, mode="clip")
    cos_r = jnp.take(cos, lens, axis=0, mode="clip")
    bi = jnp.minimum(lens // bs, mb - 1)
    phys = jnp.take_along_axis(tables, bi[:, None], axis=1)[:, 0]
    # masked rows (idle slots, chunk rows, alive-mask-retired rows)
    # must not append: their next write belongs to a later program
    phys = jnp.where((app_mask > 0) & (lens < s_tot), phys, nb)
    prow = lens % bs

    def layer(h, xs):
        lwq, lwk, lwv, lwo, lg, lu, ld, lin, lpost, pk_l, pv_l = \
            _dq_layer(xs, wdt, a8)
        hn = _rms(h, lin, eps)
        q, k, v = _qkv_proj(hn, lwq, lwk, lwv, nh, nkv, hd)
        q = _apply_rope_rows(q, sin_r, cos_r)
        k = _apply_rope_rows(k, sin_r, cos_r)
        pk_l = _kv_write(pk_l, phys, prow, k[:, 0])
        pv_l = _kv_write(pv_l, phys, prow, v[:, 0])
        kd, vd, ksc, vsc = _kv_attn_args(pk_l, pv_l)
        if decode_attn == "pallas":
            attn = paged_decode_attention_pallas(
                q[:, 0], kd, vd, tables, lens + app_mask,
                k_scale=ksc, v_scale=vsc)
        else:
            attn = paged_decode_attention_reference(
                q[:, 0], kd, vd, tables, lens + app_mask,
                k_scale=ksc, v_scale=vsc)
        o = _o_proj(attn.reshape(R, 1, nh * hd), lwo)
        h = h + (o if tp_reduce is None else tp_reduce(o))
        m = _swiglu_proj(_rms(h, lpost, eps), lg, lu, ld)
        h = h + (m if tp_reduce is None else tp_reduce(m))
        return h, (pk_l, pv_l)

    x, (npk, npv) = jax.lax.scan(layer, x, stack + (pk_all, pv_all))
    lastt = _rms(x[:, 0], params["final_norm"], eps)
    lgt = _head_logits(lastt, head)
    b2 = jax.vmap(jax.random.split)(kys)
    nxt = sample_rows(lgt, b2[:, 1], temps, top_ks)
    return nxt, npk, npv, b2[:, 0]


def _span_last_sample(params, head, x, qstart, qlen, keys, temps, top_ks,
                      eps):
    """Tick 0's per-slot sample — each slot samples from its span's
    LAST packed position (decode rows: the one token; chunk rows: the
    chunk end — live only when the chunk completes the prompt).
    Shared by the unified and multi-tick steps so the sampling rule
    cannot drift. Returns ``(tok0, keys')`` after one split per row.
    """
    T = x.shape[1]
    last_idx = jnp.clip(qstart + qlen - 1, 0, T - 1)
    last = jnp.take(x[0], last_idx, axis=0)                 # [R, H]
    last_h = _rms(last, params["final_norm"], eps)
    logits = _head_logits(last_h, head)
    both = jax.vmap(jax.random.split)(keys)                 # [R, 2, 2]
    tok0 = sample_rows(logits, both[:, 1], temps, top_ks)
    return tok0, both[:, 0]


def _packed_span_forward(params, pool_k, pool_v, tables, ids, seg, pos,
                         qstart, qlen, kvlen, sin, cos, *, nh, nkv, hd,
                         eps, decode_attn, tp_reduce=None, a8=False):
    """ONE forward pass over a packed buffer of variable-length query
    spans through the block tables — the shared tick-0 assembly of the
    unified ragged step AND the speculative verify program (the two
    must write/attend identically or their streams could drift). K/V
    for every live packed token is scattered through its slot's table
    at its logical position (dead rows — ``seg == R`` — and positions
    past the logical capacity DROP), attention runs through the ragged
    paged kernel or its jnp oracle. Returns ``(x [1, T, H], pk, pv)``.
    """
    R = tables.shape[0]
    nb, bs = _kv_data(pool_k).shape[1], _kv_data(pool_k).shape[2]
    mb = tables.shape[1]
    s_tot = mb * bs
    T = ids.shape[0]
    stack = tuple(params[k] for k in _STACK_KEYS)
    wdt = params["embed"].dtype
    sin_p = jnp.take(sin, pos, axis=0, mode="clip")[None]   # [1, T, D]
    cos_p = jnp.take(cos, pos, axis=0, mode="clip")[None]
    # pool write coordinates: token t appends at its logical position
    # through its OWN slot's table; dead packed rows (seg == R) and
    # positions past the logical capacity drop — never clamp into a
    # block another sequence owns
    live_tok = seg < R
    seg_c = jnp.minimum(seg, R - 1)
    bi = jnp.minimum(pos // bs, mb - 1)
    phys0 = jnp.take_along_axis(jnp.take(tables, seg_c, axis=0),
                                bi[:, None], axis=1)[:, 0]
    phys0 = jnp.where(live_tok & (pos < s_tot), phys0, nb)
    prow0 = pos % bs

    def layer0(h, lp):
        (lwq, lwk, lwv, lwo, lg, lu, ld, lin, lpost, pk_l, pv_l) = \
            _dq_layer(lp, wdt, a8)
        hn = _rms(h, lin, eps)
        q, k, v = _qkv_proj(hn, lwq, lwk, lwv, nh, nkv, hd)
        q = _apply_rope_grid(q, sin_p, cos_p)
        k = _apply_rope_grid(k, sin_p, cos_p)
        # write the packed K/V through the tables (quantize-on-write on
        # an int8 pool), then attend over each span causally at its
        # row's kv length — THE one dequant site: the ragged kernel
        # (or its oracle) dequantizes right after the table-indirect
        # fetch, and every consumer of this forward (unified step,
        # multi-tick tick 0, speculative verify) rides it
        pk_l = _kv_write(pk_l, phys0, prow0, k[0])
        pv_l = _kv_write(pv_l, phys0, prow0, v[0])
        kd, vd, ksc, vsc = _kv_attn_args(pk_l, pv_l)
        if decode_attn == "pallas":
            attn = ragged_paged_attention_pallas(
                q[0], kd, vd, tables, qstart, qlen, kvlen,
                k_scale=ksc, v_scale=vsc)
        else:
            attn = ragged_attention_reference(
                q[0], kd, vd, tables, qstart, qlen, kvlen,
                k_scale=ksc, v_scale=vsc)
        o = _o_proj(attn.reshape(1, T, nh * hd), lwo)
        h = h + (o if tp_reduce is None else tp_reduce(o))
        m = _swiglu_proj(_rms(h, lpost, eps), lg, lu, ld)
        h = h + (m if tp_reduce is None else tp_reduce(m))
        return h, (pk_l, pv_l)

    x = jnp.take(params["embed"], ids[None], axis=0)        # [1, T, H]
    x, (pk, pv) = jax.lax.scan(layer0, x, stack + (pool_k, pool_v))
    return x, pk, pv


def _ragged_step_impl(params, pool_k, pool_v, tables, ids, seg, pos,
                      qstart, qlen, kvlen, dec_mask, keys, temps, top_ks,
                      *, n_steps, nh, nkv, hd, eps, theta, tied,
                      decode_attn, tp_reduce=None, a8=False, fused=False):
    """THE unified serving step: one device call that advances every
    slot's span — decode rows (span 1) and prefill chunks (span n) —
    through the same block tables, collapsing the
    ``_paged_suffix_prefill_impl`` + ``_paged_decode_steps_impl`` pair
    the engine used to interleave (README "Unified ragged attention").

    Packed layout (host-built, all runtime arrays — shapes depend only
    on ``(num_slots, token_budget)``):

    ids:     [T] int32 — packed input token ids (decode rows carry the
             slot's last sampled token; chunk rows carry their prompt
             slice; dead packed rows carry 0)
    seg:     [T] int32 — owning slot per packed token (``num_slots`` =
             dead row: every write drops)
    pos:     [T] int32 — logical position per packed token
             (``kvlen[r] - qlen[r] + i`` for span token i)
    qstart/qlen/kvlen: [R] span metadata (``serving/decode`` twin of the
             kernel's row metadata; ``qlen == 0`` = idle slot)
    dec_mask: [R] int32 — 1 where the span is a RUNNING decode row
             (spans that may keep ticking in the fused tail and whose
             appends are real), 0 for chunk rows / idle slots (their
             tail-tick writes are forced to drop)
    keys/temps/top_ks: [R] per-slot sampling state — chunk rows carry
             the sequence's resume key, live (sampling) only on their
             FINAL chunk, exactly like ``_suffix_call`` rows.

    Tick 0 runs the packed buffer through one forward pass — K/V
    scattered through the tables at per-token positions, attention via
    the ragged paged kernel (or its jnp oracle) — then samples one
    token per slot from its span's LAST position. Ticks ``1..n_steps-1``
    are the fused decode scan of ``_paged_decode_steps_impl``, verbatim
    (the engine only fuses when no prefill work is pending, so the tail
    ticks are pure decode; ``dec_mask`` keeps a stray non-decode row's
    appends out of the pool regardless).

    Returns ``(pool_k', pool_v', toks [n_steps, R], keys_t0, keys')``:
    ``toks[0]``/``keys_t0`` are tick 0's per-slot sample + advanced key
    (what a final chunk row adopts as its token 0 — the same split walk
    as a one-shot prefill, so streams stay byte-identical); ``keys'``
    is the post-scan key state the engine adopts for decode rows.
    """
    s_tot = tables.shape[1] * _kv_data(pool_k).shape[2]
    sin, cos = _rope_tables(s_tot, hd, theta)
    stack = tuple(params[k] for k in _STACK_KEYS)
    head = _dq_head(params, tied, params["embed"].dtype, a8)

    # ----------------------------------- tick 0 (shared packed forward)
    x, pk, pv = _packed_span_forward(
        params, pool_k, pool_v, tables, ids, seg, pos, qstart, qlen,
        kvlen, sin, cos, nh=nh, nkv=nkv, hd=hd, eps=eps,
        decode_attn=decode_attn, tp_reduce=tp_reduce, a8=a8)
    tok0, keys_t0 = _span_last_sample(params, head, x, qstart, qlen,
                                      keys, temps, top_ks, eps)

    # ------------------------------------------- fused tail (pure decode)
    lens0 = jnp.where(dec_mask > 0, kvlen, 0)

    def one_step(carry, _):
        tok, pk_all, pv_all, lens, kys = carry
        # non-decode rows (idle slots, a chunk row that just finished)
        # ride dec_mask=0: their appends drop inside the shared tick —
        # their next write belongs to the next step's program
        nxt, npk, npv, nkeys = _fused_decode_tick(
            params, stack, head, tables, sin, cos, tok, pk_all, pv_all,
            lens, kys, dec_mask, temps, top_ks, nh=nh, nkv=nkv, hd=hd,
            eps=eps, decode_attn=decode_attn, tp_reduce=tp_reduce,
            a8=a8, fused=fused)
        return (nxt, npk, npv, lens + dec_mask, nkeys), nxt

    if n_steps > 1:
        carry0 = (tok0, pk, pv, lens0, keys_t0)
        (_, pk, pv, _, keys_fin), toks_rest = jax.lax.scan(
            one_step, carry0, None, length=n_steps - 1)
        toks = jnp.concatenate([tok0[None], toks_rest], axis=0)
    else:
        toks, keys_fin = tok0[None], keys_t0
    return pk, pv, toks, keys_t0, keys_fin


def build_ragged_step_fn(*, n_steps, nh, nkv, hd, eps, theta, tied,
                         decode_attn, donate=None, tp=1,
                         collective_dtype="fp", kv_quant=False,
                         wq8=False, a8=False, fused=False,
                         collective_overlap=False):
    """One jitted unified serving step (``_ragged_step_impl``): shapes
    depend only on ``(num_slots, token_budget)`` plus the fused
    ``n_steps`` — one compilation per step size serves every span mix,
    the same compile-once contract as the decode program it replaces.
    ``tp > 1`` wraps the WHOLE step in shard_map over the heads-sharded
    mesh (README "Tensor-parallel serving"): attention and the QKV/MLP
    projections run fully sharded, the paged pool partitions per shard
    on its head axis, and the only cross-chip traffic is the per-layer
    all-reduce pair (``collective_dtype`` picks fp vs EQuARX-style
    int8). The compile-once contract is unchanged — the TP degree joins
    the engine's jit key, not the trace's shapes."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    if int(tp) > 1:
        tp = int(tp)
        _tp_validate(nh, nkv, tp)
        impl = functools.partial(
            _ragged_step_impl, n_steps=n_steps, nh=nh // tp,
            nkv=nkv // tp, hd=hd, eps=eps, theta=theta, tied=tied,
            decode_attn=decode_attn,
            tp_reduce=_tp_allreduce(collective_dtype, tp,
                                    overlap=collective_overlap),
            a8=a8, fused=fused)
        rep = PartitionSpec()
        pool = _pool_pspec(kv_quant)
        return jax.jit(_tp_shard(
            impl, tp,
            in_specs=(_params_pspec(wq8), pool, pool) + (rep,) * 11,
            out_specs=(pool, pool, rep, rep, rep)),
            donate_argnums=(1, 2) if donate else ())
    return jax.jit(
        functools.partial(
            _ragged_step_impl, n_steps=n_steps, nh=nh, nkv=nkv, hd=hd,
            eps=eps, theta=theta, tied=tied, decode_attn=decode_attn,
            a8=a8, fused=fused),
        donate_argnums=(1, 2) if donate else ())


# ------------------------------------------------------- multi-tick decode
def _multitick_step_impl(params, pool_k, pool_v, tables, ids, seg, pos,
                         qstart, qlen, kvlen, dec_mask, keys, temps,
                         top_ks, eos_ids, budgets, n_ticks, *, max_ticks,
                         nh, nkv, hd, eps, theta, tied, decode_attn,
                         tp_reduce=None, a8=False, fused=False):
    """THE multi-tick serving step (README "Multi-tick decode"): the
    unified ragged step with the host driven out of the per-token loop.
    Tick 0 is ``_ragged_step_impl``'s packed forward verbatim (decode
    rows span 1, prefill chunks span n, K/V written through the block
    tables, one sample per span); the fused tail is the same decode
    scan UPGRADED with

    - a **runtime tick count**: ``n_ticks`` (host-chosen each step,
      1..max_ticks) bounds a ``lax.while_loop`` instead of a static
      ``lax.scan`` length, so ONE compilation serves every tick count —
      mixed-traffic steps pass 1 and pay exactly the unified step's
      work, decode-heavy steps pass ``decode_ticks``;
    - an **on-device alive mask**: per-slot EOS hits (``eos_ids``, -1 =
      no EOS configured) and remaining-budget counters (``budgets`` =
      ``max_new_tokens - len(tokens)`` at step start) retire a row
      inside the loop — a finished row's appends drop exactly like a
      ``dec_mask`` dead row, its length stops advancing, and the loop
      EXITS EARLY once every row is dead (a program can return with
      ticks to spare);

    so the host syncs once per ``n_ticks`` tokens instead of once per
    token, and accepts the whole block in one ``host-accept``.

    The alive update replays the host's ``_maybe_finish`` rule
    exactly — after emitting token index ``t`` a row stays alive iff
    the token is not its EOS and ``t + 1 < budget`` — so the device's
    append cut equals the host's trim cut and the donation invariant
    (the last emitted token's KV is never in the cache) is preserved
    tick-for-tick. Appends per row == tokens the host accepts.

    Per-row sampling walks are positionally identical to sequential
    decode (split once per tick per row, all rows, dead or alive), so
    streams are byte-identical to ``n_ticks = 1`` — greedy AND
    seeded-sampled; the host adopts ``keys_walk[m - 1]`` for a row
    that emitted ``m`` tokens, the same contract as the speculative
    verify's key walk.

    Returns ``(pool_k', pool_v', toks [max_ticks, R],
    keys_walk [max_ticks, R, 2], ticks_run)``: row 0 is tick 0's
    sample + advanced key (what a final chunk row adopts — the same
    split walk as a one-shot prefill); rows past ``ticks_run`` are
    zeros the host never reads.
    """
    R = tables.shape[0]
    s_tot = tables.shape[1] * _kv_data(pool_k).shape[2]
    sin, cos = _rope_tables(s_tot, hd, theta)
    stack = tuple(params[k] for k in _STACK_KEYS)
    head = _dq_head(params, tied, params["embed"].dtype, a8)

    # ----------------------------------- tick 0 (shared packed forward)
    def _packed_tick0(pk_in, pv_in):
        x, pk2, pv2 = _packed_span_forward(
            params, pk_in, pv_in, tables, ids, seg, pos, qstart, qlen,
            kvlen, sin, cos, nh=nh, nkv=nkv, hd=hd, eps=eps,
            decode_attn=decode_attn, tp_reduce=tp_reduce, a8=a8)
        tok0, keys_t0 = _span_last_sample(params, head, x, qstart,
                                          qlen, keys, temps, top_ks,
                                          eps)
        return tok0, keys_t0, pk2, pv2

    if fused:
        # a launch with NO chunk rows — every span a qlen<=1 decode
        # row, the only state the scheduler fuses ticks for — runs
        # tick 0 through the SAME fused whole-tick program as the
        # tail, so the whole sync is one launch per tick; mixed
        # launches (n_ticks == 1 by scheduler policy) keep the packed
        # forward verbatim. Byte-identity of the two tick-0 spellings
        # on pure-decode spans is the standing multi-tick contract
        # (body ticks ≡ single-tick packed steps), applied at tick 0.
        tok_in = ids[jnp.maximum(qstart + qlen - 1, 0)]
        lens_in = jnp.where(dec_mask > 0, kvlen - 1, 0)

        def _fused_tick0(pk_in, pv_in):
            nxt, npk, npv, nkeys = _fused_decode_tick(
                params, stack, head, tables, sin, cos, tok_in, pk_in,
                pv_in, lens_in, keys, dec_mask, temps, top_ks, nh=nh,
                nkv=nkv, hd=hd, eps=eps, decode_attn=decode_attn,
                tp_reduce=tp_reduce, a8=a8, fused=True)
            return nxt, nkeys, npk, npv

        tok0, keys_t0, pk, pv = jax.lax.cond(
            jnp.all(qlen <= 1), _fused_tick0, _packed_tick0,
            pool_k, pool_v)
    else:
        tok0, keys_t0, pk, pv = _packed_tick0(pool_k, pool_v)

    # ------------------------------- fused tail (alive-masked, runtime n)
    lens0 = jnp.where(dec_mask > 0, kvlen, 0)
    # after tick 0 a decode row has emitted 1 token: it keeps ticking
    # iff that token is not its EOS and its budget allows a second
    alive0 = (dec_mask > 0) & (tok0 != eos_ids) & (budgets > 1)
    toks_buf = jnp.zeros((max_ticks, R), jnp.int32).at[0].set(tok0)
    keys_buf = jnp.zeros((max_ticks, R, 2),
                         jnp.uint32).at[0].set(keys_t0)

    def cond(state):
        t, alive = state[0], state[1]
        return jnp.logical_and(t < n_ticks, jnp.any(alive))

    def body(state):
        t, alive, tok, pk_all, pv_all, lens, kys, tb, kb = state
        # dead rows — idle slots, chunk rows, and rows the alive mask
        # retired (EOS hit / budget spent on an earlier tick) — ride
        # app_mask=0 through the shared tick: appends drop, length
        # frozen (a retired row's next write belongs to nobody)
        am = alive.astype(jnp.int32)
        nxt, npk, npv, nkeys = _fused_decode_tick(
            params, stack, head, tables, sin, cos, tok, pk_all, pv_all,
            lens, kys, am, temps, top_ks, nh=nh, nkv=nkv, hd=hd,
            eps=eps, decode_attn=decode_attn, tp_reduce=tp_reduce,
            a8=a8, fused=fused)
        tb = tb.at[t].set(nxt)
        kb = kb.at[t].set(nkeys)
        # the host's _maybe_finish rule, in-program: after emitting
        # token index t a row stays alive iff the token is not its EOS
        # and t + 1 more tokens fit its budget
        alive = alive & (nxt != eos_ids) & (t + 1 < budgets)
        return (t + 1, alive, nxt, npk, npv, lens + am, nkeys, tb, kb)

    state0 = (jnp.int32(1), alive0, tok0, pk, pv, lens0, keys_t0,
              toks_buf, keys_buf)
    (ticks_run, _, _, pk, pv, _, _, toks_buf, keys_buf) = \
        jax.lax.while_loop(cond, body, state0)
    return pk, pv, toks_buf, keys_buf, ticks_run


def build_multitick_step_fn(*, max_ticks, nh, nkv, hd, eps, theta, tied,
                            decode_attn, donate=None, tp=1,
                            collective_dtype="fp", kv_quant=False,
                            wq8=False, a8=False, fused=False,
                            collective_overlap=False):
    """One jitted multi-tick serving step (``_multitick_step_impl``):
    shapes depend only on ``(num_slots, token_budget, max_ticks)`` —
    the tick count actually run is a RUNTIME argument, so one
    compilation serves every span mix AND every adaptive tick count
    from 1 to ``max_ticks``. The compile-once contract covers the
    multi-tick geometry with a single trace. ``tp > 1`` shards it over
    heads exactly like the unified step it extends."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    if int(tp) > 1:
        tp = int(tp)
        _tp_validate(nh, nkv, tp)
        impl = functools.partial(
            _multitick_step_impl, max_ticks=int(max_ticks), nh=nh // tp,
            nkv=nkv // tp, hd=hd, eps=eps, theta=theta, tied=tied,
            decode_attn=decode_attn,
            tp_reduce=_tp_allreduce(collective_dtype, tp,
                                    overlap=collective_overlap),
            a8=a8, fused=fused)
        rep = PartitionSpec()
        pool = _pool_pspec(kv_quant)
        return jax.jit(_tp_shard(
            impl, tp,
            in_specs=(_params_pspec(wq8), pool, pool) + (rep,) * 14,
            out_specs=(pool, pool, rep, rep, rep)),
            donate_argnums=(1, 2) if donate else ())
    return jax.jit(
        functools.partial(
            _multitick_step_impl, max_ticks=int(max_ticks), nh=nh,
            nkv=nkv, hd=hd, eps=eps, theta=theta, tied=tied,
            decode_attn=decode_attn, a8=a8, fused=fused),
        donate_argnums=(1, 2) if donate else ())


# ------------------------------------------------- speculative verify step
def _spec_verify_impl(params, pool_k, pool_v, tables, ids, seg, pos,
                      qstart, qlen, kvlen, sample_start, keys, temps,
                      top_ks, *, spec_len, nh, nkv, hd, eps, theta, tied,
                      decode_attn, tp_reduce=None, a8=False):
    """THE speculative serving step (README "Speculative decoding"):
    one device call that scores every slot's draft-extended span — a
    verify row packs ``[last_token, d_1 .. d_k]`` at positions
    ``len .. len+k`` and a prefill chunk packs its prompt slice, both
    writing K/V through the block tables exactly like
    ``_ragged_step_impl``'s tick 0 (the forward IS that tick's shared
    assembly, ``_packed_span_forward``) — then samples ``spec_len``
    consecutive positions per row under the standard split-per-token
    PRNG walk, so the host can accept the longest draft prefix whose
    tokens the target model reproduces and adopt the key exactly where
    sequential decode would have left it.

    Packed layout (host-built runtime arrays; shapes depend only on
    ``(num_slots, spec token budget, spec_len)``):

    ids/seg/pos:   [T] — as in ``_ragged_step_impl`` (dead rows drop)
    qstart/qlen/kvlen: [R] span metadata (``qlen == 0`` = idle slot;
                   ``kvlen`` counts KV valid AFTER this step's writes)
    sample_start:  [R] — the packed row the sampling walk starts at:
                   a VERIFY row samples from its span START (position
                   ``j`` scores the token after input ``j``), a chunk
                   row from its span END (only its final-position
                   sample — token 0 — is ever adopted); reads clamp
                   inside the span, so short spans repeat their last
                   position and the host ignores the surplus.
    keys/temps/top_ks: [R] per-slot sampling state (chunk rows carry
                   the sequence's resume key, live only on their final
                   chunk — exactly like ``_suffix_call`` rows).

    Walk step ``j``: split every row's key, sample position ``j``'s
    logits with the split — byte-identical to ``spec_len`` sequential
    decode ticks for any prefix the drafts match, which is the whole
    acceptance argument: an accepted token was sampled with the same
    key and the same logits sequential decode would have used, so
    streams with speculation ON equal streams with it OFF, greedy AND
    seeded-sampled. Rejected positions' samples/keys are garbage the
    host never adopts (and their K/V rows are truncated away).

    Returns ``(pool_k', pool_v', toks [spec_len, R],
    keys_walk [spec_len, R, 2])`` — ``keys_walk[j]`` is each row's key
    after ``j + 1`` splits; a row that emits ``m`` tokens adopts
    ``keys_walk[m - 1]``.
    """
    T = ids.shape[0]
    R = tables.shape[0]
    s_tot = tables.shape[1] * _kv_data(pool_k).shape[2]
    sin, cos = _rope_tables(s_tot, hd, theta)
    head = _dq_head(params, tied, params["embed"].dtype, a8)

    x, pk, pv = _packed_span_forward(
        params, pool_k, pool_v, tables, ids, seg, pos, qstart, qlen,
        kvlen, sin, cos, nh=nh, nkv=nkv, hd=hd, eps=eps,
        decode_attn=decode_attn, tp_reduce=tp_reduce, a8=a8)
    # per-row sample positions: spec_len consecutive packed rows from
    # sample_start, clamped inside the row's span (idle rows clamp to
    # row 0 — garbage the host never reads)
    span_end = jnp.clip(qstart + jnp.maximum(qlen, 1) - 1, 0, T - 1)
    j_idx = jnp.arange(spec_len, dtype=jnp.int32)
    idx = jnp.clip(sample_start[:, None] + j_idx[None, :],
                   qstart[:, None], span_end[:, None])       # [R, S]
    hsel = jnp.take(x[0], idx.reshape(-1), axis=0)           # [R*S, H]
    last_h = _rms(hsel, params["final_norm"], eps)
    logits = _head_logits(last_h, head)
    logits = logits.reshape(R, spec_len, -1)

    def walk(kys, lg_j):
        both = jax.vmap(jax.random.split)(kys)               # [R, 2, 2]
        tok = sample_rows(lg_j, both[:, 1], temps, top_ks)
        return both[:, 0], (tok, both[:, 0])

    _, (toks, keys_walk) = jax.lax.scan(
        walk, keys, jnp.moveaxis(logits, 1, 0))
    return pk, pv, toks, keys_walk


def build_spec_verify_fn(*, spec_len, nh, nkv, hd, eps, theta, tied,
                         decode_attn, donate=None, tp=1,
                         collective_dtype="fp", kv_quant=False,
                         wq8=False, a8=False, collective_overlap=False):
    """One jitted speculative verify step (``_spec_verify_impl``):
    shapes depend only on ``(num_slots, spec token budget, spec_len)``
    — one compilation serves every draft/acceptance/chunk mix, the
    same compile-once contract as the programs it replaces. ``tp > 1``
    shards it over heads exactly like the unified step whose tick-0
    assembly it shares."""
    if donate is None:
        donate = jax.default_backend() != "cpu"
    if int(tp) > 1:
        tp = int(tp)
        _tp_validate(nh, nkv, tp)
        impl = functools.partial(
            _spec_verify_impl, spec_len=spec_len, nh=nh // tp,
            nkv=nkv // tp, hd=hd, eps=eps, theta=theta, tied=tied,
            decode_attn=decode_attn,
            tp_reduce=_tp_allreduce(collective_dtype, tp,
                                    overlap=collective_overlap),
            a8=a8)
        rep = PartitionSpec()
        pool = _pool_pspec(kv_quant)
        return jax.jit(_tp_shard(
            impl, tp,
            in_specs=(_params_pspec(wq8), pool, pool) + (rep,) * 11,
            out_specs=(pool, pool, rep, rep)),
            donate_argnums=(1, 2) if donate else ())
    return jax.jit(
        functools.partial(
            _spec_verify_impl, spec_len=spec_len, nh=nh, nkv=nkv, hd=hd,
            eps=eps, theta=theta, tied=tied, decode_attn=decode_attn,
            a8=a8),
        donate_argnums=(1, 2) if donate else ())
