"""Draft-token proposers for speculative decoding (README "Speculative
decoding").

Speculative decode splits each decode advance into a cheap PROPOSE and
one batched VERIFY: a :class:`Drafter` guesses the next ``k`` tokens of
a running sequence from host-visible state, the engine scores all
``k + 1`` positions in one ragged-span forward through the paged block
tables (``decode.build_spec_verify_fn``), accepts the longest matching
prefix, and rolls rejected K/V back by truncating the slot's private
block tail (``PagedKVCache.truncate``). The drafter is therefore pure
host-side policy: it never touches the KV pool, never affects the
compile surface, and a wrong guess costs only the packed-buffer
positions the verify span spent — never a wrong token (acceptance is
exact-match against the target model's own samples, so streams are
byte-identical to speculation off).

Two drafters ship behind the one interface:

- :class:`NgramDrafter` — model-free prompt lookup (PLD, PAPERS.md):
  match the longest recent n-gram of the sequence's history (prompt +
  generated tokens) against an earlier occurrence and propose its
  continuation. Zero extra weights, zero device work; it feeds on the
  repetition that dominates real serving traffic (quotes, code,
  structured output, the model's own loops). The engine's default.
- :class:`ModelDrafter` — a separate (typically much smaller) LLaMA
  draft model proposing greedily. Shares the engine's jit-cache-factory
  idiom: pass one dict to every instance (and every engine rebuild) so
  proposals never re-trace. The reference implementation re-runs the
  draft model's bucketed prefill per proposed token — correct and
  compile-bounded, but O(k) full forwards per call; a production
  drafter would keep its own KV cache (ROADMAP follow-on).
"""
from __future__ import annotations

import numpy as np

_EMPTY = np.zeros(0, np.int32)


def _history(seq):
    """The sequence's full known token history — prompt plus every
    ACCEPTED generated token (draft tokens never enter ``seq.tokens``
    until verification accepts them, which is what makes crash recovery
    safe: ``engine.restore()`` recomputes from exactly this)."""
    if seq.tokens:
        return np.concatenate(
            [seq.prompt, np.asarray(seq.tokens, np.int32)])
    return np.asarray(seq.prompt, np.int32)


class Drafter:
    """Interface: ``propose(seq, k)`` returns up to ``k`` draft token
    ids (1-D int32, possibly empty) guessing the sequence's next
    tokens. Called on the engine-driver thread once per running slot
    per speculative step — keep it cheap; returning fewer than ``k``
    (or none) is always safe and merely shrinks the verify span."""

    def propose(self, seq, k):
        raise NotImplementedError


class NgramDrafter(Drafter):
    """Prompt-lookup n-gram drafter (self-speculative, model-free).

    Finds the longest ``n``-gram (``max_ngram`` down to ``min_ngram``)
    ending the sequence's history that also occurs EARLIER in the
    history, and proposes the continuation after the most recent such
    occurrence. Repetitive continuations — the model re-quoting the
    prompt, structured output, greedy decode settling into a loop —
    verify at near-full acceptance; on non-repetitive text it simply
    finds no match and the verify span degenerates to a plain decode
    row (no wasted device work beyond the packed position).
    """

    def __init__(self, max_ngram=3, min_ngram=1):
        if int(min_ngram) < 1 or int(max_ngram) < int(min_ngram):
            raise ValueError(
                f"need max_ngram >= min_ngram >= 1, got "
                f"max_ngram={max_ngram}, min_ngram={min_ngram}")
        self.max_ngram = int(max_ngram)
        self.min_ngram = int(min_ngram)

    def propose(self, seq, k):
        k = int(k)
        if k <= 0:
            return _EMPTY
        hist = _history(seq)
        L = int(hist.shape[0])
        for n in range(self.max_ngram, self.min_ngram - 1, -1):
            if L < n + 1:
                continue        # history too short for this n + 1 cont.
            tail = hist[L - n:]
            win = np.lib.stride_tricks.sliding_window_view(hist, n)
            hits = np.nonzero((win == tail).all(axis=1))[0]
            hits = hits[hits < L - n]   # exclude the tail itself
            if hits.size:
                i = int(hits[-1])       # most recent earlier occurrence
                return hist[i + n:i + n + k].astype(np.int32, copy=True)
        return _EMPTY


class ModelDrafter(Drafter):
    """Greedy proposals from a separate LLaMA-family draft model.

    ``jit_cache`` follows the engine's shared-factory idiom: pass the
    same dict to every drafter the engine factory builds so crash-
    recovery rebuilds re-trace nothing. Context lengths are padded to
    pow2 buckets, so the compile set is bounded exactly like the
    engine's cold prefill. Drafting with the TARGET model itself is the
    always-accept oracle (the verify argmax is the same function) —
    useful for tests and as the acceptance upper bound, not for speed.
    """

    def __init__(self, model, jit_cache=None):
        from .decode import build_prefill_fn, llama_decode_params
        c = model.config
        self._params, tied = llama_decode_params(model)
        self._consts = dict(
            nh=c.num_attention_heads, nkv=c.num_key_value_heads,
            hd=c.head_dim, eps=float(c.rms_norm_eps),
            theta=float(c.rope_theta), tied=tied)
        self._build = build_prefill_fn
        self._jit = jit_cache if jit_cache is not None else {}
        self._max_len = int(c.max_position_embeddings)

    def _fn(self):
        # "draft" key: the draft model's traces must not count against
        # the serving engine's prefill_compilations() pin when the two
        # share one jit-cache dict
        key = ("draft",)
        if key not in self._jit:
            self._jit[key] = self._build(**self._consts)
        return self._jit[key]

    def propose(self, seq, k):
        import jax.numpy as jnp
        hist = _history(seq)
        out = []
        for _ in range(int(k)):
            L = int(hist.shape[0])
            if L >= self._max_len:
                break
            pad = min(max(8, 1 << (L - 1).bit_length()), self._max_len)
            ids = np.zeros((1, pad), np.int32)
            ids[0, :L] = hist
            _, _, tok0, _ = self._fn()(
                self._params, jnp.asarray(ids),
                np.asarray([L], np.int32), jnp.zeros((1, 2), jnp.uint32),
                np.zeros(1, np.float32), np.zeros(1, np.int32))
            t = int(np.asarray(tok0)[0])
            out.append(t)
            hist = np.append(hist, np.int32(t))
        return np.asarray(out, np.int32)
