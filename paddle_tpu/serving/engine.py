"""Continuous-batching decode engine (the Orca/vLLM serving loop on the
TPU decode path, SURVEY §3.5 / PAPERS.md).

One engine owns ``num_slots`` KV-cache slots and drives a step function:
each :meth:`step` (1) admits queued requests into free slots — one
bucketed prefill each — then (2) runs one fused device call of
``n`` single-token decode ticks over ALL slots, then (3) retires
sequences that hit EOS or their token budget, freeing their slots for
the next admission. Requests join and leave the batch between any two
steps, so short requests never wait for long ones and the batch never
restarts.

Compile discipline (the perf contract): the decode program's shapes
depend only on ``(num_slots, max_seq_len)``; per-request sampling knobs
and per-slot ragged lengths are runtime arrays. One compilation serves
every request mix — :meth:`decode_compilations` counts traces so tests
can pin this. Prefill compiles once per prompt-length bucket.

Offline use::

    engine = ContinuousBatchingEngine(model, num_slots=8)
    outs = engine.generate([GenerationRequest(prompt=ids, ...), ...])

Online use: call :meth:`submit` at arrival time and :meth:`step` in a
loop; finished sequences come back from the step that retired them.
``model.generate()`` is a thin offline wrapper over this engine.
"""
from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from ..profiler.tracing import NULL_SPAN
from .decode import build_decode_steps_fn, build_paged_decode_steps_fn, \
    build_paged_suffix_prefill_fn, build_prefill_fn, build_ragged_step_fn, \
    build_suffix_prefill_fn, llama_decode_params
from .kv_cache import PagedKVCache, PoolExhausted, SlotKVCache
from .policy import ClassTable, PolicyScheduler, select_victims
from .request import GenerationRequest, GenerationResult, Sequence
from .scheduler import FIFOScheduler


class ContinuousBatchingEngine:
    """Slot-based continuous batching over a LLaMA-family model.

    ``prefix_cache=True`` enables automatic prefix caching
    (``serving/prefix_cache.py``): retiring sequences publish their
    prompt's full KV blocks into a ref-counted LRU pool, and a new
    admission whose prompt shares a cached block chain installs it with
    compile-once copy programs and prefills only the uncovered suffix.
    Pass a :class:`~.prefix_cache.PrefixCache` instance to carry one
    pool across successive engines — ONLY when every engine is driven
    from the same single thread (the cache is lock-free by the engine's
    single-driver contract; two concurrently-stepping engines, e.g. two
    gateways, must not share one). Its pool geometry must match this
    engine's layers/heads/dtype. ``prefix_blocks``/``prefix_block_size``
    size the pool the engine builds itself (default: enough blocks to
    cache ``num_slots`` full-length prompts at 32-token granularity).

    ``paged_attn=True`` (the default) serves from true block-table
    paged attention (:class:`~.kv_cache.PagedKVCache`, README "Paged
    attention"): the :class:`~.block_manager.BlockManager` pool IS the
    cache, every live slot addresses it through a per-slot block table
    (a runtime argument — ``decode_compilations()`` stays at 1),
    prefix-cache hits install by *referencing* published block ids
    (zero copy dispatches; N holders physically share one block), decode
    growth appends blocks lazily, and retirement *donates* full prompt
    AND generated blocks to the trie instead of copying them out (so a
    multi-turn resubmission of an assistant turn hits that turn's own
    blocks). Token streams are byte-identical to the dense engine
    (``paged_attn=False``, the legacy :class:`~.kv_cache.SlotKVCache`
    path — still selectable, same test matrix).
    ``prefix_block_size`` doubles as the KV block size; the pool is
    sized ``num_slots * ceil(max_seq_len/block_size)`` live blocks plus
    the ``prefix_blocks`` trie budget (trie-only blocks are reclaimed on
    demand when live growth needs them).

    ``prefill_chunk`` bounds TTFT under mixed traffic (README "Chunked
    prefill"): a cold prompt whose uncovered tail exceeds it is
    prefilled ``prefill_chunk`` tokens per engine step — through the
    paged suffix-prefill program at a host-side resume offset, KV
    landing in the slot's own pool blocks — interleaved with the fused
    decode tick for every live slot, so a long prompt never monopolizes
    a step while decode slots idle. Chunk boundaries are block-aligned
    (the value is rounded up to a block multiple); installed prefix-
    cache hits count toward the resume offset; cancellation or deadline
    expiry mid-chunk frees (or donates) the partial block chain.
    ``prefill_chunk=None``/``0`` disables chunking; the dense engine
    ignores it (one-shot prefill — chunking rides the block tables).

    ``ragged_step=True`` (the default on the paged engine, README
    "Unified ragged attention") runs decode rows AND prefill chunks
    through ONE device program per step — the unified ragged step
    (``decode.build_ragged_step_fn`` over the ragged paged attention
    kernel): each slot contributes one variable-length query span
    (decode = span 1, chunk = span n) to a packed token buffer whose
    shape depends only on ``(num_slots, token_budget)``, so a mixed
    prefill+decode step costs one program launch instead of the
    chunk-call + decode-call pair, and a mid-prefill slot no longer
    burns a discarded full-length decode row. ``ragged_step=False``
    keeps the PR-5 two-program interleave as the A/B baseline (token
    streams are byte-identical either way). With the unified step, the
    per-step chunk grant is adapted at runtime from a measured
    tokens-per-second EWMA (the ``headroom`` stat): the engine grants
    roughly ``headroom_mult`` decode-steps' worth of tokens per step —
    ``prefill_chunk`` remains the hard cap — so chunk work throttles
    itself under decode load instead of stretching every resident
    request's latency. ``headroom_mult=None`` pins the grant at the
    cap (fixed PR-5 pacing, what the deterministic benches use).
    ``step_clock`` injects the timebase the EWMA reads (tests/benches
    pass a virtual clock; default ``time.perf_counter``).

    ``spec_decode=True`` (paged only, default OFF — every banked
    baseline is an A/B away) turns on speculative multi-token decode
    (README "Speculative decoding"): a :class:`~.drafter.Drafter`
    (default: model-free prompt-lookup n-grams,
    :class:`~.drafter.NgramDrafter`; or a tiny draft model via
    :class:`~.drafter.ModelDrafter`) proposes up to ``spec_k`` tokens
    per running slot, one batched forward scores all ``k + 1``
    positions per slot as a ragged span through the same paged
    attention kernel (draft K/V appended through the block tables
    exactly like a prefill chunk), the longest matching prefix is
    accepted — plus the model's own token at the first mismatch, so a
    launch always advances every slot — and rejected draft K/V rolls
    back via ``PagedKVCache.truncate`` (exact block accounting,
    donated/shared blocks untouched). Acceptance is exact-match
    against the target model's own sampling walk, so token streams are
    BYTE-IDENTICAL to ``spec_decode=False`` — greedy and seeded-
    sampled alike; speculation only reorders work. Prefill chunks ride
    the same one-launch-per-step program; drafts share the packed
    buffer's headroom with the chunk grant
    (``FIFOScheduler.spec_grants``). ``decode_compilations()`` counts
    the verify geometry and stays 1. On the CPU/jnp substrate the
    verify walk prices the packed buffer densely (same caveat as the
    unified step below); the modeled win is launches-per-token
    (``scripts/bench_spec.py``, SPEC_BENCH.json).

    ``decode_ticks > 1`` (unified ragged engine only, default 1 — every
    banked baseline is an A/B away) turns on multi-tick decode (README
    "Multi-tick decode"): EVERY step runs ONE multi-tick program
    (``decode.build_multitick_step_fn``) whose packed tick 0 is the
    unified step verbatim and whose fused tail runs a RUNTIME number of
    decode ticks — up to ``decode_ticks`` — with on-device EOS/budget
    retirement (a finished row's appends drop inside the program
    exactly where the host's trim cuts) and early exit when every row
    retires. The host syncs once per block instead of once per token
    (``plan``/``launch``/``host-accept`` cover n tokens), trimming each
    slot at its first EOS/budget cut so streams stay byte-identical to
    ``decode_ticks=1``. The scheduler adapts the tick count per step
    (``FIFOScheduler.choose_decode_ticks``: 1 under mixed traffic,
    shrunk to the nearest guaranteed retirement while the queue waits);
    since the count is a runtime argument, ``decode_compilations()``
    stays at 1 — the multi-tick geometry keys its own jit-cache entry
    (``("mtick", num_slots, token_budget, decode_ticks, attn)``).
    Incompatible with ``spec_decode`` (a speculative step has no
    pure-decode tail to fuse). ``decode_chunk`` fusion is superseded on
    this path — the multi-tick program subsumes it with masking.

    Substrate note: the unified program's packed buffer is a fixed
    ``num_slots + prefill_chunk`` tokens, which the TPU Pallas kernel
    prices at the LIVE spans only (span-block gating + ragged DMA
    skip) but the CPU ``decode_attention="jnp"`` oracle computes
    densely — on that correctness substrate a decode-only step pays
    the padding, so CPU deployments that never chunk should pass
    ``ragged_step=False`` (or ``prefill_chunk=None``, which sizes the
    buffer back to ``num_slots``). The serving benches pin the
    two-program baseline for exactly this reason
    (``RAGGED_BENCH.json``'s ``cpu_oracle_wall_ms`` records the gap).
    """

    def __init__(self, model, num_slots=8, max_seq_len=None, decode_chunk=8,
                 prefill_bucketing="pow2", jit_cache=None,
                 prefix_cache=False, prefix_blocks=None,
                 prefix_block_size=32, paged_attn=True,
                 prefill_chunk=512, ragged_step=True, headroom_mult=2.0,
                 step_clock=None, spec_decode=False, spec_k=4,
                 drafter=None, decode_ticks=1, kv_dtype=None,
                 quantize_weights=False, quantize_activations=False,
                 tp=1, collective_dtype="fp",
                 host_tier_bytes=0, priority_classes=None,
                 fused_tick=False, collective_overlap=False):
        c = model.config
        # multi-tenant SLO policy (README "Multi-tenant SLO serving"):
        # like host_tier_bytes, policy not geometry — classes change
        # admission order and preemption choices, never a traced shape
        # or a jit key. The default None is the single neutral class:
        # the plain FIFO scheduler is kept and every banked baseline
        # stays byte-identical.
        self.classes = ClassTable.coerce(priority_classes)
        self._policy = self.classes.active
        # host-RAM spill tier behind the prefix trie (README "Tiered KV
        # prefix cache"): policy, not geometry — it changes no traced
        # shape and adds no jit key, so it never joins a jit-cache or
        # fleet geometry tuple. 0 (default) = off, byte-identical to
        # every banked baseline.
        self._host_tier_bytes = int(host_tier_bytes)
        if self._host_tier_bytes < 0:
            raise ValueError(
                f"host_tier_bytes must be >= 0, got {host_tier_bytes}")
        if c.decode_attention not in ("pallas", "jnp"):
            raise ValueError(
                f"decode_attention must be 'pallas' or 'jnp', got "
                f"{c.decode_attention!r}")
        if prefill_bucketing not in ("pow2", "exact"):
            raise ValueError(
                f"prefill_bucketing must be 'pow2' or 'exact', got "
                f"{prefill_bucketing!r}")
        # multi-chip tensor parallelism (README "Tensor-parallel
        # serving"): tp=N shards every serving program over an N-device
        # heads-sharded mesh with the paged pool partitioned per shard.
        if int(tp) < 1:
            raise ValueError(f"tp must be >= 1, got {int(tp)}")
        if collective_dtype not in ("fp", "int8"):
            raise ValueError(
                f"collective_dtype must be 'fp' or 'int8', got "
                f"{collective_dtype!r}")
        self._tp = int(tp)
        # tp=1 has no mesh and no wire: normalize the collective dtype
        # so banners/geometry tuples report the effective value
        self._coll_dtype = collective_dtype if self._tp > 1 else "fp"
        if self._tp > 1:
            if not (bool(paged_attn) and bool(ragged_step)):
                raise ValueError(
                    "tp > 1 requires the unified ragged paged engine "
                    "(paged_attn=True, ragged_step=True): tensor "
                    "parallelism shards the packed-span programs and "
                    "the block pool; the dense / two-program paths "
                    "never grew mesh plumbing")
            if c.num_attention_heads % self._tp \
                    or c.num_key_value_heads % self._tp:
                raise ValueError(
                    f"tp={self._tp} must divide num_attention_heads "
                    f"({c.num_attention_heads}) and num_key_value_heads "
                    f"({c.num_key_value_heads}): the mesh shards over "
                    f"heads")
            if self._coll_dtype == "int8" and c.hidden_size % self._tp:
                raise ValueError(
                    f"collective_dtype='int8' needs hidden_size "
                    f"({c.hidden_size}) divisible by tp={self._tp}: the "
                    f"quantized all-reduce chunks the activation per "
                    f"shard")
            from .decode import _tp_mesh
            # raises with the XLA_FLAGS hint when the mesh can't exist;
            # bound here so the pool construction below reuses THE mesh
            self._tp_mesh = _tp_mesh(self._tp)
        else:
            self._tp_mesh = None
        if kv_dtype not in (None, "int8", "fp8"):
            raise ValueError(
                f"kv_dtype must be None (store KV at the pool dtype), "
                f"'int8' or 'fp8', got {kv_dtype!r}")
        if kv_dtype is not None and not (paged_attn and ragged_step):
            raise ValueError(
                f"kv_dtype={kv_dtype!r} requires the unified ragged "
                f"paged engine (paged_attn=True, ragged_step=True): the "
                f"quantized pool's one upcast site is the ragged "
                f"attention kernel, and the dense / two-program paths "
                f"never grew scale-plane plumbing")
        if quantize_activations and not quantize_weights:
            raise ValueError(
                "quantize_activations=True requires "
                "quantize_weights=True: the int8xint8 projection path "
                "contracts runtime-quantized activations against the "
                "int8 weight pytree, so there is no activation-only "
                "variant")
        if quantize_activations and not (paged_attn and ragged_step):
            raise ValueError(
                "quantize_activations=True requires the unified ragged "
                "paged engine (paged_attn=True, ragged_step=True): only "
                "the packed-span programs grew the int8xint8 projection "
                "path, and a dense-path decode would silently fall back "
                "to weight-dequant matmuls")
        self.model = model
        self.config = c
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len or c.max_position_embeddings)
        self._bucketing = prefill_bucketing
        self._params, self._tied = llama_decode_params(model)
        self._paged = bool(paged_attn)
        # quantized KV pool (README "Quantized serving"): "int8" stores
        # int8 with per-row-per-head fp32 scale planes, "fp8" stores
        # float8_e4m3fn with per-BLOCK planes (constant 1.0 — e4m3's
        # exponent is the per-value scale; see BlockManager). Either
        # way the append paths quantize on write and the attention
        # kernels upcast in-register after the table-indirect DMA.
        # Default None keeps the pool at the model dtype — every banked
        # baseline is byte-identical to before the knob existed.
        # _kv_quant carries the MODE (falsy None / "int8" / "fp8"): the
        # builders and _pool_pspec dispatch on the string.
        self._kv_quant = kv_dtype
        self._kv_dtype = kv_dtype
        # int8 weight-only decode matmuls: convert ONCE per model (the
        # converted pytree is model-resident, so the factory's rebuilds
        # and every fleet replica share both the quantized arrays and
        # the jit cache — decode_compilations()==1 across rebuilds)
        self._wq8 = bool(quantize_weights)
        # int8xint8 decode projections (README "Quantized serving"):
        # activations quantize per-row at runtime and contract against
        # the int8 weights with int32 accumulate — the per-layer weight
        # DEQUANT disappears from the scanned layer body (the AST pin
        # in tests/test_cost_observatory.py holds it there). Default
        # False keeps the weight-only path byte-identical.
        self._a8 = bool(quantize_activations)
        if self._wq8:
            from .decode import quantize_decode_params
            qp = model.__dict__.get("_decode_qparams")
            if qp is None:
                qp = quantize_decode_params(self._params, self._tied)
                model.__dict__["_decode_qparams"] = qp
            self._params = qp
        # jit-key variant tags: quantized pools/params are a DIFFERENT
        # TRACE of the same impl (dtype / pytree structure), so engines
        # differing only in kv_dtype or quantize_weights sharing one
        # jit_cache dict must key apart or both compile pins break.
        # Appended at the END of each key; () on default engines keeps
        # every pre-existing key byte-identical. The TP degree (and its
        # collective dtype) is a variant the same way: a sharded
        # program is a different trace of the same impl, so tp=2 and
        # tp=1 engines sharing one jit_cache must key apart.
        self._kvtag = (("kv8f",) if self._kv_dtype == "fp8"
                       else ("kv8",) if self._kv_quant else ())
        self._wtag = ("w8",) if self._wq8 else ()
        self._atag = ("a8",) if self._a8 else ()
        self._tptag = ((f"tp{self._tp}", self._coll_dtype)
                       if self._tp > 1 else ())
        if self._tp > 1:
            # commit the params onto the mesh ONCE per (model, tp, w8):
            # rebuilds and fleet replicas share the placed arrays (and
            # the jit cache never pays a per-call reshard)
            from .decode import place_tp_params
            placed = model.__dict__.setdefault("_tp_params", {})
            pkey = (self._tp, self._wq8)
            if pkey not in placed:
                placed[pkey] = place_tp_params(self._params, self._tp,
                                               self._wq8)
            self._params = placed[pkey]
        dtype = self._params["embed"].dtype
        from .block_manager import BlockManager
        from .prefix_cache import PrefixCache
        self.prefix_cache = None
        if self._paged:
            bs = int(prefix_block_size)
            if bs < 1:
                raise ValueError(
                    f"prefix_block_size must be >= 1, got {bs}")
            max_blocks = -(-self.max_seq_len // bs)
            live = self.num_slots * max_blocks
            # the pool's STORAGE dtype follows kv_dtype (int8 data +
            # scale planes), not the model dtype — a shared pool must
            # match the engine's quantization mode exactly
            store = (jnp.float8_e4m3fn if self._kv_dtype == "fp8"
                     else jnp.int8 if self._kv_quant else dtype)
            # TP partitions the pool's HEAD axis across the mesh: the
            # BlockManager commits its arrays with that sharding once,
            # so every sharded program adopts them zero-copy
            tp_mesh = self._tp_mesh
            if isinstance(prefix_cache, PrefixCache):
                pool = prefix_cache.pool
                want = (c.num_hidden_layers, c.num_key_value_heads,
                        c.head_dim)
                have = (pool.k.shape[0],) + pool.k.shape[3:]
                if have != want or pool.k.dtype != store \
                        or pool.block_size != bs \
                        or getattr(pool, "kv_dtype",
                                   None) != self._kv_dtype:
                    raise ValueError(
                        f"shared PrefixCache pool geometry "
                        f"{have}/bs={pool.block_size}/{pool.k.dtype} does "
                        f"not match this paged engine "
                        f"{want}/bs={bs}/{store} "
                        f"(kv_dtype={self._kv_dtype!r})")
                if getattr(pool, "tp", 1) != self._tp:
                    raise ValueError(
                        f"shared PrefixCache pool is partitioned for "
                        f"tp={getattr(pool, 'tp', 1)} but this engine "
                        f"runs tp={self._tp}: a pool's head-axis "
                        f"sharding must match every engine serving "
                        f"from it")
                if pool.num_blocks <= live:
                    raise ValueError(
                        f"shared pool of {pool.num_blocks} blocks cannot "
                        f"back {live} live blocks plus a prefix trie on "
                        f"the paged engine")
                if prefix_cache.max_blocks is None:
                    # a dense-idiom cache (pool IS the budget) adopted by
                    # a paged engine: bound trie residency to the pool's
                    # headroom over the live grid, else donations grow
                    # until every decode-growth alloc pays an eviction
                    prefix_cache.max_blocks = pool.num_blocks - live
                self.prefix_cache = prefix_cache
            elif prefix_cache:
                if prefix_blocks is None:
                    budget = self.num_slots * max(self.max_seq_len // bs, 1)
                else:
                    budget = int(prefix_blocks)
                    if budget < 1:
                        raise ValueError(
                            f"prefix_blocks must be >= 1, got {budget}")
                pool = BlockManager(
                    c.num_hidden_layers, live + budget, bs,
                    c.num_key_value_heads, c.head_dim, dtype=dtype,
                    kv_dtype=self._kv_dtype, mesh=tp_mesh)
                self.prefix_cache = PrefixCache(
                    pool, max_blocks=budget,
                    host_tier_bytes=self._host_tier_bytes)
            else:
                pool = BlockManager(
                    c.num_hidden_layers, live, bs, c.num_key_value_heads,
                    c.head_dim, dtype=dtype, kv_dtype=self._kv_dtype,
                    mesh=tp_mesh)
            self.cache = PagedKVCache(
                c.num_hidden_layers, self.num_slots, self.max_seq_len,
                c.num_key_value_heads, c.head_dim, dtype=dtype,
                block_size=bs, pool=pool, prefix_cache=self.prefix_cache,
                kv_dtype=self._kv_dtype)
        else:
            self.cache = SlotKVCache(
                c.num_hidden_layers, self.num_slots, self.max_seq_len,
                c.num_key_value_heads, c.head_dim, dtype=dtype)
            if prefix_cache:
                if isinstance(prefix_cache, PrefixCache):
                    # fail fast on a geometry mismatch: copies between the
                    # pool and this cache would otherwise die mid-serving
                    # with an opaque XLA shape/dtype error on the first hit
                    pool = prefix_cache.pool
                    want = (self.cache.k.shape[0],) + self.cache.k.shape[3:]
                    have = (pool.k.shape[0],) + pool.k.shape[3:]
                    if have != want or pool.k.dtype != self.cache.k.dtype:
                        raise ValueError(
                            f"shared PrefixCache pool geometry "
                            f"{have}/{pool.k.dtype} does not match this "
                            f"engine's cache {want}/{self.cache.k.dtype}")
                    self.prefix_cache = prefix_cache
                else:
                    bs = int(prefix_block_size)
                    if bs < 1:
                        raise ValueError(
                            f"prefix_block_size must be >= 1, got {bs}")
                    if prefix_blocks is None:
                        nb = self.num_slots * max(self.max_seq_len // bs, 1)
                    else:
                        nb = int(prefix_blocks)  # 0/negative: BlockManager
                        # raises rather than silently falling back to default
                    self.prefix_cache = PrefixCache(BlockManager(
                        c.num_hidden_layers, nb, bs, c.num_key_value_heads,
                        c.head_dim, dtype=dtype),
                        host_tier_bytes=self._host_tier_bytes)
        # chunked prefill (paged only — the dense per-slot cache has no
        # block tables to resume through; its prefill stays one-shot).
        # The chunk is rounded UP to a block multiple so every non-final
        # chunk boundary is block-aligned: a partially prefilled prompt
        # is exactly a prefix of whole pool blocks + a host resume
        # offset, which keeps mid-prefill cancellation/donation trivial.
        self._chunk = None
        if prefill_chunk and int(prefill_chunk) < 1:
            # validated on BOTH engines: an A/B toggle of paged_attn
            # must not turn a hard error into a silent no-op
            raise ValueError(
                f"prefill_chunk must be >= 1 (or None/0 to disable), "
                f"got {int(prefill_chunk)}")
        if self._paged and prefill_chunk:
            bs = self.cache.block_size
            self._chunk = -(-int(prefill_chunk) // bs) * bs
        # unified ragged step (paged only): size the packed token buffer
        # once — num_slots decode rows plus the chunk cap, but only when
        # a prompt long enough to chunk can exist at all (a chunk cap >=
        # max_seq_len can never trigger, so the buffer stays num_slots
        # and a decode-only engine pays nothing for the unification)
        self._ragged = self._paged and bool(ragged_step)
        chunkable = self._chunk is not None and self._chunk < self.max_seq_len
        self._token_budget = self.num_slots + (self._chunk if chunkable
                                               else 0)
        # speculative decode (paged only — rollback truncates the block
        # tail; README "Speculative decoding"): every step becomes ONE
        # draft-extended verify launch whose packed buffer shares its
        # headroom between prefill-chunk tokens and verify spans (a
        # verify span spends 1 + k positions of it). The buffer is
        # sized for the LARGER of the two demands, not their sum —
        # chunk-heavy steps throttle drafts, decode-heavy steps have
        # the chunk headroom to speculate into.
        self._spec = bool(spec_decode)
        if self._spec and not self._paged:
            raise ValueError(
                "spec_decode requires the paged engine (paged_attn="
                "True): draft rollback truncates the slot's private "
                "block tail, which the dense per-slot cache does not "
                "have")
        if self._spec and int(spec_k) < 1:
            raise ValueError(f"spec_k must be >= 1, got {int(spec_k)}")
        self._spec_k = int(spec_k)
        self._spec_len = self._spec_k + 1       # the sampling-walk depth
        self._spec_budget = self.num_slots + max(
            self._chunk if chunkable else 0,
            self.num_slots * self._spec_k)
        self.drafter = None
        if self._spec:
            if drafter is None:
                from .drafter import NgramDrafter
                drafter = NgramDrafter()
            self.drafter = drafter
        # multi-tick decode (README "Multi-tick decode"): when > 1, the
        # engine runs EVERY step through ONE multi-tick program —
        # chunk rows ride tick 0 exactly like the unified step, and
        # pure-decode steps fuse up to decode_ticks on-device ticks
        # behind a single host sync, with EOS/budget retirement masked
        # inside the program (decode.build_multitick_step_fn). The tick
        # count actually run is a RUNTIME argument chosen per step by
        # the scheduler (FIFOScheduler.choose_decode_ticks: clamped to
        # 1 under mixed traffic, shrunk to the nearest guaranteed
        # retirement when the queue has waiting work), so one
        # compilation serves every tick count.
        if int(decode_ticks) < 1:
            raise ValueError(
                f"decode_ticks must be >= 1, got {int(decode_ticks)}")
        self._decode_ticks = int(decode_ticks)
        self._mtick = self._decode_ticks > 1
        if self._mtick and not self._ragged:
            raise ValueError(
                "decode_ticks > 1 requires the unified ragged engine "
                "(paged_attn=True, ragged_step=True): multi-tick decode "
                "is the unified step's fused tail driven past the host "
                "sync")
        if self._mtick and self._spec:
            raise ValueError(
                "decode_ticks > 1 is incompatible with spec_decode: a "
                "speculative step is a verify launch every step, so "
                "there is no pure-decode tail to multi-tick. spec_decode "
                "composes with: paged_attn, ragged_step, prefix_cache, "
                "prefill_chunk, kv_dtype, quantize_weights, "
                "quantize_activations, tp, collective_overlap, "
                "host_tier_bytes, priority_classes. decode_ticks > 1 "
                "composes with those plus fused_tick — pick one of the "
                "two step shapes")
        # one-kernel decode (README "One-kernel decode"): fused_tick
        # swaps the scanned per-layer tick body for ONE Pallas program
        # whose grid dimension IS the layer loop — a tick becomes O(1)
        # device launches instead of O(layers). Same op sequence, same
        # bits: the kernel replays _fused_decode_tick exactly, and the
        # jnp oracle (kernels.pallas_fused_decode_tick) covers the
        # geometries the single-device mega-kernel can't express
        # (in-kernel collectives, int8 activations). Default False keeps
        # every banked baseline byte-identical.
        self._fused_tick = bool(fused_tick)
        if self._fused_tick and not self._ragged:
            raise ValueError(
                "fused_tick=True requires the unified ragged paged "
                "engine (paged_attn=True, ragged_step=True): the fused "
                "program is the packed-span tick body, and the dense / "
                "two-program paths never grew its dispatch site")
        if self._fused_tick and self._spec:
            raise ValueError(
                "fused_tick=True is incompatible with spec_decode: the "
                "fused program is the one-token tick body, and a verify "
                "launch is a spec_len-token span. fused_tick composes "
                "with: prefix_cache, prefill_chunk, decode_ticks, "
                "kv_dtype, quantize_weights, quantize_activations, tp, "
                "collective_overlap, host_tier_bytes, priority_classes")
        # TP compute/collective overlap (README "One-kernel decode"):
        # the per-layer all-reduce pair (post o-proj + post down-proj
        # tp_reduce sites) switches to a chunked reduce-scatter /
        # all-gather schedule so chunk k's wire time hides behind chunk
        # k+1's compute. Same bits on the wire format (EQuARX int8
        # preserved) and same ledger bytes — but a DIFFERENT trace
        # (ppermute chains instead of one psum), so the tp tag grows an
        # "ov" marker to key overlap engines apart in a shared cache.
        self._coll_overlap = bool(collective_overlap)
        if self._coll_overlap and self._tp <= 1:
            raise ValueError(
                "collective_overlap=True requires tp > 1: the overlap "
                "schedule rewrites the per-layer tensor-parallel "
                "all-reduce pair, and a tp=1 engine has no collectives "
                "to overlap")
        if self._coll_overlap:
            self._tptag = self._tptag + ("ov",)
        # jit-key tag for the fused-tick variant: appended LAST (after
        # kv8f/a8/tpN) so every pre-existing key stays byte-identical
        # on default engines
        self._fktag = ("fk",) if self._fused_tick else ()
        if headroom_mult is not None and float(headroom_mult) <= 0:
            raise ValueError(
                f"headroom_mult must be > 0 (or None for fixed-cap chunk "
                f"pacing), got {headroom_mult}")
        self._headroom_mult = (None if headroom_mult is None
                               else float(headroom_mult))
        self._clock = step_clock if step_clock is not None \
            else time.perf_counter
        # the current step's start reading of step_clock: SLO stamps
        # (t_admitted/t_first_token/t_finish) quantize to it instead of
        # reading the clock again — step() must read its clock exactly
        # twice per step (start + end), a contract the deterministic
        # benches and the injected-tick-clock tests rely on. Step
        # granularity is exactly the resolution those latencies have.
        self._stamp_t = None
        # headroom EWMAs (the adaptive chunk budget's inputs): measured
        # unified-step tokens/second, and the duration of decode-only
        # steps (the latency baseline chunk work must not stretch past
        # ~headroom_mult x)
        self._tps_ewma = None
        self._dt_decode_ewma = None
        if self._policy:
            # clock + slot ledger bound late: the closures read the
            # live attributes at decision time, so the injected
            # step_clock and rebuilt slot arrays are always current
            self.scheduler = PolicyScheduler(
                decode_chunk, table=self.classes,
                clock=lambda: self._clock(),
                slot_usage=self._class_slot_usage)
        else:
            self.scheduler = FIFOScheduler(decode_chunk)
        self._slots = [None] * self.num_slots
        self._last_tok = np.zeros(self.num_slots, np.int32)
        self._temps = np.zeros(self.num_slots, np.float32)
        self._topks = np.zeros(self.num_slots, np.int32)
        self._keys = jnp.zeros((self.num_slots, 2), jnp.uint32)
        # jitted programs, shareable across engines of the same model so
        # a fresh engine never re-traces (model.generate passes the
        # model-level dict)
        self._jit = jit_cache if jit_cache is not None else {}
        self.stats = {"steps": 0, "decode_calls": 0, "decode_steps": 0,
                      "slot_steps": 0, "active_slot_steps": 0,
                      "prefills": 0, "prefill_tokens": 0,
                      "prefill_tokens_saved": 0,
                      "prefill_copy_dispatches": 0,
                      "prefill_chunks": 0, "chunk_tokens": 0,
                      "unified_steps": 0,
                      "mtick_syncs": 0, "mtick_ticks": 0,
                      "mtick_pure_syncs": 0,
                      "last_decode_ticks": 0,
                      "spec_steps": 0, "spec_proposed": 0,
                      "spec_accepted": 0, "spec_tokens": 0,
                      "spec_last_accept": [],
                      "headroom": self._chunk or 0, "headroom_tps": 0.0,
                      "last_step_duration_s": 0.0, "last_step_tokens": 0,
                      "tokens_generated": 0, "cancelled": 0, "timeouts": 0,
                      "preemptions": 0, "restores": 0,
                      "policy_preemptions": 0}
        # fault-injection hook (serving/faults.py): called with the
        # engine at the top of every step attempt; None in production.
        # Whatever it raises propagates to the driver — except
        # PoolExhausted, which the step loop repairs by preemption.
        self.fault_hook = None
        # request-lifecycle tracer (profiler/tracing.py, README
        # "Tracing & debugging"): None in production; the gateway
        # installs one (and re-installs it on every rebuilt engine).
        # Every instrumentation site guards on _tr() — one attribute
        # check when tracing is off, so the hot path pays nothing.
        self.tracer = None
        # device-boundary cost observatory (profiler/cost.py, README
        # "Cost attribution & /debug/profile"): None in production
        # engines built bare; the gateway installs ONE observatory
        # across every engine incarnation, so its dispatch/transfer/
        # compile counts stay monotonic across rebuilds. Every touch
        # guards on _co() — the tracer's one-attribute discipline.
        self.cost = None
        # streaming hooks (the gateway's wire into the step loop):
        # on_token(seq, token_id) fires for EVERY generated token the
        # moment the host sees it; on_finish(seq) fires exactly once per
        # sequence, for every finish_reason — including cancel(), whose
        # retirements never appear in a step() return. Both run on the
        # thread driving step() — keep them cheap and non-reentrant.
        self.on_token = None
        self.on_finish = None
        # policy-preemption hook: on_policy_preempt(victim_seq) fires
        # just before an SLO-driven displacement (the gateway's per-
        # victim-class counter). Same thread/cheapness contract as
        # on_token/on_finish. None on a policy-off engine — the step
        # loop never consults policy there.
        self.on_policy_preempt = None

    # ------------------------------------------------------------- tracing
    def _tr(self):
        """The active tracer, or None — THE guard every trace site
        uses, so a disabled tracer costs one attribute check and no
        event-arg construction."""
        t = self.tracer
        return t if (t is not None and t.enabled) else None

    def _co(self):
        """The active cost observatory, or None — THE guard every cost
        site uses (``_tr()``'s twin), so a disabled/absent observatory
        costs one attribute check and no accounting work. Also the
        chokepoint that keeps the prefix cache's tier ledger pointed at
        the live observatory: the gateway installs ``engine.cost``
        AFTER construction (and swaps it on rebuild), and the trie's
        spill/readmit paths record through ``prefix_cache.cost`` — one
        identity check per step keeps the two in sync."""
        c = self.cost
        co = c if (c is not None and c.enabled) else None
        pc = self.prefix_cache
        if pc is not None and pc.cost is not co:
            pc.cost = co
        return co

    def _wrap_prog(self, key, fn, host_out):
        """The jit-cache hand-out chokepoint: every program accessor
        routes through here, so with the observatory on, EVERY device
        program the engine can launch is counted — exactly once per
        call, no site-by-site bookkeeping to drift. ``host_out`` names
        the result indices the engine fetches to host (the program's
        true device→host surface)."""
        co = self._co()
        if co is None:
            return fn
        return co.wrap(key, fn, host_out=host_out)

    def _stamp_now(self):
        """Timestamp for the Sequence SLO stamps: the current step's
        start reading while inside a step (no extra clock reads — see
        ``_stamp_t``), a fresh reading outside one (submit/cancel)."""
        return self._stamp_t if self._stamp_t is not None \
            else self._clock()

    def _trace_phase_end(self, tr, seq, args=None):
        """Close the sequence's current lifecycle span (named by its
        ``trace_phase``: queued|prefill|decode|preempted|recovered)
        on the request's trace lane and restart the mark."""
        tr.complete(seq.trace_phase, seq.trace_mark,
                    tid=tr.req_tid(seq.request_id), args=args)
        seq.trace_mark = tr.now()

    def _tspan(self, name, args=None):
        """Engine-lane span context manager, or a shared no-op when
        tracing is off. Convenience for the prefill paths; the
        per-step hot sites use explicit ``_tr()`` guards so the
        disabled path never builds an args dict."""
        tr = self._tr()
        if tr is None:
            return NULL_SPAN
        return tr.span(name, args=args)

    # ------------------------------------------------------------ programs
    def _fn_consts(self):
        c = self.config
        return dict(nh=c.num_attention_heads, nkv=c.num_key_value_heads,
                    hd=c.head_dim, eps=float(c.rms_norm_eps),
                    theta=float(c.rope_theta), tied=self._tied)

    def _tp_consts(self):
        """Builder kwargs of the TP variant ({} on tp=1, so default
        engines call the builders exactly as before)."""
        if self._tp <= 1:
            return {}
        return dict(tp=self._tp, collective_dtype=self._coll_dtype,
                    kv_quant=self._kv_quant, wq8=self._wq8)

    def _q_consts(self):
        """Builder kwargs of the activation-quantized variant ({} when
        off, so default engines call the builders exactly as before).
        Only the builders that grew the int8xint8 path take ``a8`` —
        the validation above keeps a8 engines off the dense/two-program
        builders."""
        return dict(a8=True) if self._a8 else {}

    def _prefill_fn(self):
        # the weight tag (not the kv tag): the cold prefill touches the
        # params but never the pool, so two engines differing only in
        # kv_dtype SHARE this trace while a quantized-weights engine
        # (different param pytree = different trace) keys apart. The
        # TP tag joins: a sharded prefill is a different program.
        key = ("prefill",) + self._wtag + self._atag + self._tptag
        if key not in self._jit:
            tpk = self._tp_consts()
            tpk.pop("kv_quant", None)   # prefill never touches the pool
            self._jit[key] = build_prefill_fn(**self._fn_consts(), **tpk,
                                              **self._q_consts())
        # host_out: the engine fetches tok0 (result 2); pk/pv feed the
        # cache writer device-side and keys stay device state
        return self._wrap_prog(key, self._jit[key], host_out=(2,))

    def _suffix_fn(self):
        # paged and dense suffix programs are distinct (table-indirect
        # vs slot-indexed) and may share one jit_cache dict, so they key
        # apart; the cold prefill is IDENTICAL either way and is shared.
        # The suffix program touches params AND pool — all three tags.
        key = (("psuffix",) if self._paged else ("suffix",)) \
            + self._kvtag + self._wtag + self._atag + self._tptag
        if key not in self._jit:
            build = (build_paged_suffix_prefill_fn if self._paged
                     else build_suffix_prefill_fn)
            self._jit[key] = build(**self._fn_consts(),
                                   **self._tp_consts(),
                                   **self._q_consts())
        return self._wrap_prog(key, self._jit[key], host_out=(2,))

    def _decode_fn(self, n_steps):
        kind = "pdecode" if self._paged else "decode"
        key = (kind, int(n_steps), self.config.decode_attention) \
            + self._kvtag + self._wtag
        if key not in self._jit:
            build = (build_paged_decode_steps_fn if self._paged
                     else build_decode_steps_fn)
            self._jit[key] = build(
                n_steps=int(n_steps),
                decode_attn=self.config.decode_attention,
                **self._fn_consts())
        return self._wrap_prog(key, self._jit[key], host_out=(0,))

    def _ragged_fn(self, n_steps):
        # the full packed-buffer geometry — num_slots AND token budget,
        # not their sum alone — is part of the key: engines with
        # different geometry sharing one jit_cache must not pool their
        # shape-keyed traces under one fn (decode_compilations counts
        # only THIS engine's geometry, and e.g. slots=8/chunk=64 vs
        # slots=16/chunk=56 share a token budget of 72)
        key = ("ragged", self.num_slots, self._token_budget,
               int(n_steps), self.config.decode_attention) \
            + self._kvtag + self._wtag + self._atag + self._tptag \
            + self._fktag
        if key not in self._jit:
            self._jit[key] = build_ragged_step_fn(
                n_steps=int(n_steps),
                decode_attn=self.config.decode_attention,
                fused=self._fused_tick,
                collective_overlap=self._coll_overlap,
                **self._fn_consts(), **self._tp_consts(),
                **self._q_consts())
        # host reads the sampled tokens and the tick-0 keys (chunk
        # installs); keys_fin is adopted device-side via jnp.where
        return self._wrap_prog(key, self._jit[key], host_out=(2, 3))

    def _mtick_fn(self):
        # like the ragged key: the full packed geometry (num_slots AND
        # token budget) plus max_ticks — CONFIG, like the spec key's
        # spec_len — key the trace apart from other engines sharing
        # one jit_cache. The tick count actually run is a runtime
        # argument, so this is the engine's ONE decode program.
        key = ("mtick", self.num_slots, self._token_budget,
               self._decode_ticks, self.config.decode_attention) \
            + self._kvtag + self._wtag + self._atag + self._tptag \
            + self._fktag
        if key not in self._jit:
            from .decode import build_multitick_step_fn
            self._jit[key] = build_multitick_step_fn(
                max_ticks=self._decode_ticks,
                decode_attn=self.config.decode_attention,
                fused=self._fused_tick,
                collective_overlap=self._coll_overlap,
                **self._fn_consts(), **self._tp_consts(),
                **self._q_consts())
        # host reads the sampled token block, the key walk (per-slot
        # adoption at each slot's trim cut) and the ticks-run scalar
        return self._wrap_prog(key, self._jit[key], host_out=(2, 3, 4))

    def _spec_fn(self):
        # like the ragged key: the full packed geometry (num_slots AND
        # the spec token budget) plus the sampling-walk depth key the
        # trace apart from other engines sharing one jit_cache
        key = ("spec", self.num_slots, self._spec_budget,
               self._spec_len, self.config.decode_attention) \
            + self._kvtag + self._wtag + self._atag + self._tptag
        if key not in self._jit:
            from .decode import build_spec_verify_fn
            self._jit[key] = build_spec_verify_fn(
                spec_len=self._spec_len,
                decode_attn=self.config.decode_attention,
                collective_overlap=self._coll_overlap,
                **self._fn_consts(), **self._tp_consts(),
                **self._q_consts())
        # host reads the sampled walk tokens AND the key walk (both are
        # np.asarray'd for acceptance)
        return self._wrap_prog(key, self._jit[key], host_out=(2, 3))

    @property
    def spec_decode(self) -> bool:
        """Whether this engine runs speculative multi-token decode
        (draft → ragged-span verify → block-tail rollback) — the public
        surface for banners/metrics."""
        return self._spec

    @property
    def spec_k(self) -> int:
        """Max draft tokens per verify span (0 when speculation is
        off)."""
        return self._spec_k if self._spec else 0

    @property
    def decode_ticks(self) -> int:
        """Max on-device decode ticks per host sync (1 = the unified
        single-sync-per-token step) — the public surface for
        banners/metrics. README "Multi-tick decode"."""
        return self._decode_ticks

    @property
    def tp(self) -> int:
        """Tensor-parallel degree: the number of mesh devices every
        serving program shards over (1 = single-chip, no mesh) — the
        public surface for banners/metrics (README "Tensor-parallel
        serving")."""
        return self._tp

    @property
    def collective_dtype(self) -> str:
        """The EFFECTIVE wire dtype of the per-layer TP all-reduce:
        ``"int8"`` runs it EQuARX-style block-quantized, ``"fp"`` is a
        plain psum (and the reported value on tp=1, where no collective
        ever runs) — the public surface for banners/metrics."""
        return self._coll_dtype

    @property
    def fused_tick(self) -> bool:
        """Whether the decode tick body runs as ONE fused Pallas
        program (grid-over-layers mega-kernel; O(1) device launches per
        tick) instead of the scanned per-layer stack — the public
        surface for banners/metrics (README "One-kernel decode")."""
        return self._fused_tick

    @property
    def collective_overlap(self) -> bool:
        """Whether the per-layer TP all-reduce pair runs the chunked
        reduce-scatter/all-gather overlap schedule instead of one psum
        (False on tp=1, where no collective ever runs) — the public
        surface for banners/metrics (README "One-kernel decode")."""
        return self._coll_overlap

    def _record_collectives(self, co, spans):
        """EXACT collective-byte accounting for one sharded launch —
        called at every launch site behind the ``_co()`` guard.
        ``spans`` is ``[(rows, repeats)]``: each entry covers
        ``repeats`` passes over the layer stack, each pass paying the
        per-layer all-reduce PAIR (post o-proj + post down-proj) on a
        ``[rows, hidden]`` activation. Bytes follow the shared wire
        model (``quantization.collective_wire_bytes``), so the
        fp-vs-int8 counter ratio is shape-derived and deterministic —
        the TP bench's >=3x gate reads these counters, not a network
        probe."""
        if self._tp <= 1:
            return
        from ..quantization import collective_wire_bytes
        L = self.config.num_hidden_layers
        hidden = self.config.hidden_size
        fp_b = np.dtype(self._params["embed"].dtype).itemsize
        ops, nbytes = 0, 0
        for rows, reps in spans:
            if rows <= 0 or reps <= 0:
                continue
            ops += 2 * L * reps
            nbytes += 2 * L * reps * collective_wire_bytes(
                rows, hidden, self._tp, self._coll_dtype,
                fp_itemsize=fp_b)
        co.record_collective(self._coll_dtype, ops, nbytes)

    @property
    def kv_dtype(self) -> str:
        """The EFFECTIVE KV storage dtype this engine serves from:
        ``"int8"`` / ``"fp8"`` on a quantized pool, else the pool's
        array dtype name — the public surface for banners/metrics
        (README "Quantized serving")."""
        if self._kv_quant:
            return self._kv_dtype
        arr = self.cache.pool.k if self._paged else self.cache.k
        return str(arr.dtype)

    @property
    def quantize_weights(self) -> bool:
        """Whether the decode-path projection matmuls run int8
        weight-only (converted once at engine build) — the public
        surface for banners/metrics."""
        return self._wq8

    @property
    def quantize_activations(self) -> bool:
        """Whether the decode-path projections run int8xint8 — per-row
        runtime activation quant contracted against the int8 weights
        with int32 accumulate, no per-layer weight dequant — the public
        surface for banners/metrics (README "Quantized serving")."""
        return self._a8

    @property
    def ragged_step(self) -> bool:
        """Whether this engine runs the unified ragged step (one device
        program per step for decode rows + prefill chunks) — the public
        surface for banners/metrics."""
        return self._ragged

    @property
    def prefill_chunk(self) -> int:
        """The EFFECTIVE chunked-prefill budget this engine runs: the
        configured value rounded up to a KV-block multiple, or 0 when
        chunking is disabled (or ignored — the dense engine has no
        block tables to resume through). The public surface for
        banners/metrics; ``_chunk`` stays the internal None-able
        form."""
        return self._chunk or 0

    def decode_compilations(self) -> int:
        """Total decode-program traces OF THIS ENGINE'S KIND (the
        compiles-once assertion hook): stays at one per ``(num_slots,
        max_seq_len, n_steps)`` — on the unified engine, one per
        ``(num_slots, token_budget, n_steps)`` — no matter how request
        sampling params / token budgets / block tables / span mixes
        vary. Dense, paged-two-program and unified engines sharing one
        jit_cache count only their own programs. On the speculative
        engine the verify program IS the decode program — every step,
        chunk-carrying or not, is one spec-geometry launch — so the
        count covers the verify geometry too. Tag-aware INCLUSIVE of
        the sharded geometry: a tp=N engine counts only its own
        ``("tpN", dtype)``-tagged traces, so the pin covers the
        shard_map program and a tp=1 sibling sharing the jit cache
        never pollutes it (README "Tensor-parallel serving"). The
        fused-tick tag joins the tail the same way: a fused engine
        counts only its own ``fk``-tagged traces, and the pin stays ==1
        inclusive of the ``fk`` (and ``fk`` x ``tpN`` x ``kv8f``/``a8``)
        variant geometry (README "One-kernel decode")."""
        tags = self._kvtag + self._wtag + self._atag + self._tptag \
            + self._fktag
        if self._spec:
            # spec_len is CONFIG (spec_k + 1), not a runtime variant
            # like the ragged key's n_steps — two engines differing
            # only in spec_k can share a budget (the chunk term of the
            # max dominates), so it must be part of the identity.
            # key[5:] is the quantization-variant tail: a quantized
            # engine sharing this jit_cache is a different program.
            return sum(fn._cache_size() for key, fn in self._jit.items()
                       if key[0] == "spec"
                       and key[1] == self.num_slots
                       and key[2] == self._spec_budget
                       and key[3] == self._spec_len
                       and key[5:] == tags)
        if self._mtick:
            # the multi-tick program IS the decode program — every
            # step, chunk-carrying or not, is one mtick-geometry launch
            # whose tick count is a runtime argument, so the count
            # covers the multi-tick geometry with a single trace.
            # decode_ticks is CONFIG (part of the identity, like the
            # spec key's spec_len): two engines differing only in
            # decode_ticks share a packed budget but not a program.
            return sum(fn._cache_size() for key, fn in self._jit.items()
                       if key[0] == "mtick"
                       and key[1] == self.num_slots
                       and key[2] == self._token_budget
                       and key[3] == self._decode_ticks
                       and key[5:] == tags)
        if self._ragged:
            return sum(fn._cache_size() for key, fn in self._jit.items()
                       if key[0] == "ragged"
                       and key[1] == self.num_slots
                       and key[2] == self._token_budget
                       and key[5:] == tags)
        kind = "pdecode" if self._paged else "decode"
        return sum(fn._cache_size() for key, fn in self._jit.items()
                   if key[0] == kind and key[3:] == tags)

    def prefill_compilations(self) -> int:
        """Prefill-side traces, cold + suffix: bounded by the pow2
        (group, bucket) grid — independent of the hit/miss/eviction mix
        (the bounded-compile half of the prefix-cache contract). Tag-
        aware like :meth:`decode_compilations`: only THIS engine's
        quantization variant counts."""
        sfx = "psuffix" if self._paged else "suffix"
        return sum(fn._cache_size() for key, fn in self._jit.items()
                   if (key[0] == "prefill"
                       and key[1:] == self._wtag + self._atag
                       + self._tptag)
                   or (key[0] == sfx
                       and key[1:] == self._kvtag + self._wtag
                       + self._atag + self._tptag))

    # ------------------------------------------------------------- intake
    def _key_for(self, request):
        if request.prng_key is not None:
            return jnp.asarray(request.prng_key)
        if request.seed is not None:
            return jax.random.PRNGKey(int(request.seed))
        from ..core import random as random_mod
        return random_mod.next_key()

    def validate(self, request):
        """Raise the submit-time errors without mutating engine state —
        callable from any thread (the HTTP front door pre-validates here
        so a bad request 400s on the handler thread instead of poisoning
        the driver loop)."""
        if not isinstance(request, GenerationRequest):
            raise TypeError(
                f"submit() takes a GenerationRequest, got "
                f"{type(request).__name__}")
        prompt_len = int(np.asarray(request.prompt).reshape(-1).shape[0])
        if prompt_len < 1:
            raise ValueError("empty prompt")
        if int(request.max_new_tokens) < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {request.max_new_tokens}")
        if prompt_len + int(request.max_new_tokens) > self.max_seq_len:
            raise ValueError(
                f"prompt ({prompt_len}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds the KV cache length "
                f"({self.max_seq_len}); raise max_seq_len or generate "
                f"fewer tokens")
        if request.timeout_s is not None and float(request.timeout_s) <= 0:
            raise ValueError(
                f"timeout_s must be > 0, got {request.timeout_s}")
        # unknown priority_class raises here — on the caller's thread,
        # so the HTTP front door 400s instead of poisoning the driver
        self.classes.resolve(request.priority_class)

    def submit(self, request) -> Sequence:
        """Queue a request; returns its live Sequence handle."""
        self.validate(request)
        deadline = (time.monotonic() + float(request.timeout_s)
                    if request.timeout_s is not None else None)
        seq = Sequence(request, key=self._key_for(request),
                       submit_step=self.stats["steps"], deadline=deadline)
        seq.pclass = self.classes.resolve(request.priority_class)
        seq.t_submit = self._clock()
        tr = self._tr()
        if tr is not None:
            seq.trace_mark = tr.now()
        self.scheduler.submit(seq)
        return seq

    def cancel(self, seq: Sequence) -> bool:
        """Retire a sequence with ``finish_reason="cancelled"`` — queued
        (dropped before ever touching a slot), mid-chunked-prefill (the
        partial block chain is freed, or donated when a trie is on), or
        running (KV slot freed mid-decode; the ragged kernel skips the
        dead slot from the next step on). Must be called from the
        thread driving :meth:`step`. Returns False if the sequence
        already finished."""
        if seq.done:
            return False
        if seq.status == "queued":
            if not self.scheduler.remove(seq):
                return False
        self.stats["cancelled"] += 1
        self._finish(seq, "cancelled", [])
        return True

    # ------------------------------------------------------------ stepping
    def _admission_hit_len(self, seq):
        """THE prefix lookup for one admitted sequence (the scheduler
        calls this once per pop): records hit/miss stats, pins the
        matched chain immediately — before any admission this step can
        publish-and-evict — and stores it on the sequence for
        _admit_group to install. Returns the covered token count."""
        matched = self.prefix_cache.lookup(seq.work)
        if matched:
            self.prefix_cache.acquire(matched)
            seq.prefix_nodes = matched
        return self.prefix_cache.block_size * len(matched)

    def _bucket(self, plen):
        if self._bucketing == "exact":
            return plen
        return min(max(8, 1 << (plen - 1).bit_length()), self.max_seq_len)

    def _admit_group(self, seqs, finished):
        """Admit a batch of sequences. With the prefix cache enabled the
        batch splits on cached-chain lookup: misses take the cold path
        (ONE full-prompt prefill device call per prompt-length bucket),
        hits install their cached blocks and take the suffix path (ONE
        suffix prefill per suffix-length bucket). Both pad the group dim
        to a power of two, so compile count stays bounded at
        O(log(num_slots) × buckets) regardless of the hit mix.

        With chunked prefill on, a sequence whose UNCOVERED prompt
        exceeds ``prefill_chunk`` skips both one-shot paths: it claims
        its slot (and zero-copy-installs any matched chain) now, enters
        the PREFILLING state, and the step loop feeds it to the suffix
        program one budgeted chunk at a time."""
        tr = self._tr()
        for seq in seqs:
            if tr is not None:
                # close each admitted sequence's waiting span (named by
                # the phase that just ended: queued, or preempted/
                # recovered for a readmission) on its request lane
                self._trace_phase_end(
                    tr, seq,
                    args={"prefix_hit_tokens": seq.prefix_hit_tokens})
            # the phase NAME tracks state even with tracing off (one
            # attr store): a capture window opened mid-flight must
            # close this request's next span under the right name
            seq.trace_phase = "prefill"
        cold, hits = [], []
        for seq in seqs:
            # the lookup already ran (and pinned) in _admission_hit_len
            # at scheduler pop time — before ANY admission this step: a
            # cold sequence retiring instantly (max_new_tokens=1 /
            # immediate EOS) publishes inside _admit_cold, and under
            # pool pressure that publish evicts; an unpinned matched
            # chain could be reaped and its block re-used before
            # _admit_hits copies from it
            covered = seq.prefix_hit_tokens   # set at scheduler pop time
            if self._chunk and seq.work_len - covered > self._chunk:
                self._enter_chunked_prefill(seq, covered)
            elif seq.prefix_nodes:
                hits.append((seq, seq.prefix_nodes))
            else:
                cold.append(seq)
        if cold:
            self._admit_cold(cold, finished)
        if hits:
            self._admit_hits(hits, finished)

    def _enter_chunked_prefill(self, seq, covered):
        """Claim a slot for a long prompt without prefilling it yet: an
        installed prefix-cache hit counts toward the resume offset
        (zero-copy table references, exactly as on the one-shot hit
        path); everything past it arrives chunk by chunk."""
        slot = self.cache.alloc()
        seq.slot = slot
        if seq.prefix_nodes:
            self.cache.install_prefix(
                slot, [node.block_id for node in seq.prefix_nodes])
        seq.prefilled = covered
        self.cache.lengths[slot] = covered
        seq.status = "prefilling"
        if seq.t_admitted is None:      # first claim only: queue wait
            seq.t_admitted = self._stamp_now()  # kept across restore
        self._slots[slot] = seq
        self.scheduler.enter_prefill(seq)

    def _admit_cold(self, seqs, finished):
        by_bucket = {}
        for seq in seqs:
            by_bucket.setdefault(self._bucket(seq.work_len), []).append(seq)
        for s_pad, group in sorted(by_bucket.items()):
            G = len(group)
            Gp = 1 << (G - 1).bit_length()
            ids = np.zeros((Gp, s_pad), np.int32)
            lens = np.ones(Gp, np.int32)  # pad rows: 1 valid token
            temps = np.zeros(Gp, np.float32)
            topks = np.zeros(Gp, np.int32)
            keys = np.zeros((Gp, 2), np.uint32)
            for i, seq in enumerate(group):
                ids[i, :seq.work_len] = seq.work
                lens[i] = seq.work_len
                temps[i] = float(seq.request.temperature)
                topks[i] = int(seq.request.top_k)
                keys[i] = np.asarray(seq.key)
            with self._tspan("prefill_launch",
                             args={"bucket": s_pad, "group": G}):
                # host arrays pass uncoerced: jit device_puts them
                # identically, and the cost facade then counts the
                # REAL host→device upload bytes of the call
                pk, pv, tok0s, keys2 = self._prefill_fn()(
                    self._params, ids, lens, keys, temps, topks)
                tok0s = np.asarray(tok0s)
            co = self._co()
            if co is not None:
                # sharded cold prefill: one pass over the padded group
                self._record_collectives(co, [(Gp * s_pad, 1)])
            for i, seq in enumerate(group):
                seq.launches += 1       # rode this bucket's prefill
                slot = self.cache.alloc()
                seq.slot = slot   # before the write: a PoolExhausted
                # raised inside write_prefill's block growth must leave
                # the claimed slot findable for _abort_admission
                self.cache.write_prefill(slot, pk[:, i], pv[:, i],
                                         seq.work_len)
                self._install_seq(seq, slot, tok0s[i], keys2[i],
                                  seq.work_len, finished)

    def _admit_hits(self, hits, finished):
        """Admit prefix-cache hits, then ONE suffix-prefill device call
        per suffix-length bucket covering only the uncovered prompt
        tails.

        Dense: install each sequence's matched chain into its slot with
        compile-once block copies (one ``copy_block_in`` dispatch per
        block — counted in ``prefill_copy_dispatches``). Group padding
        rows carry slot index ``num_slots`` and prefix ``max_seq_len``
        so every one of their cache writes drops inside the program.

        Paged: ZERO-COPY install — the slot's block table simply
        references the matched chain's block ids (no device dispatch;
        N concurrent holders share the physical blocks), private tail
        blocks are appended to cover the prompt, and the suffix prefill
        writes through the table. Padding rows carry all-sentinel
        tables so their writes drop."""
        pc = self.prefix_cache
        bs = pc.block_size
        by_bucket = {}
        for seq, matched in hits:
            suffix_len = seq.work_len - len(matched) * bs
            by_bucket.setdefault(self._bucket(suffix_len),
                                 []).append((seq, matched))
        for s_pad, group in sorted(by_bucket.items()):
            rows = []
            for seq, matched in group:
                # chain already pinned + prefix_hit_tokens already set
                # by _admission_hit_len at scheduler pop time
                covered = len(matched) * bs
                slot = self.cache.alloc()
                seq.slot = slot
                if self._paged:
                    self.cache.install_prefix(
                        slot, [node.block_id for node in matched])
                    self.cache.ensure_capacity(slot, seq.work_len)
                else:
                    for j, node in enumerate(matched):
                        self.cache.copy_block_in(slot, j * bs, pc.pool,
                                                 node.block_id)
                        self.stats["prefill_copy_dispatches"] += 1
                rows.append((seq, covered, seq.work_len - covered, True))
            tok0s, keys2 = self._suffix_call(s_pad, rows)
            for i, (seq, matched) in enumerate(group):
                seq.launches += 1       # rode this bucket's suffix call
                slot = seq.slot
                self.cache.lengths[slot] = seq.work_len
                self.stats["prefill_tokens_saved"] += seq.prefix_hit_tokens
                self._install_seq(seq, slot, tok0s[i], keys2[i],
                                  seq.work_len - seq.prefix_hit_tokens,
                                  finished)

    def _suffix_call(self, s_pad, rows):
        """ONE suffix-prefill device call for an ``s_pad``-bucket group
        — THE shared assembly behind the one-shot hit path (dense and
        paged) and the chunked-prefill path, so their calling
        conventions can never drift apart. ``rows`` is
        ``[(seq, offset, n, live)]``: prefill ``prompt[offset:offset+n]``
        into the sequence's already-claimed slot, whose storage must
        already cover the span (paged: table blocks installed/appended;
        dense: matched blocks copied in). Sampling runs only where
        ``live`` — non-final chunk rows run greedy-off and their output
        is discarded untouched. Group padding rows carry sentinel
        tables (paged) / slot ``num_slots`` (dense) and an all-covered
        prefix, so every one of their writes drops in-program. Returns
        host ``tok0s`` + device ``keys2``; only live rows' entries are
        meaningful."""
        Gp = 1 << (len(rows) - 1).bit_length()
        if self._paged:
            mb = self.cache.max_blocks
            addr = np.full((Gp, mb), self.cache.sentinel, np.int32)
            prefix_lens = np.full(Gp, mb * self.cache.block_size, np.int32)
        else:
            addr = np.full(Gp, self.num_slots, np.int32)   # writes drop
            prefix_lens = np.full(Gp, self.max_seq_len, np.int32)
        ids = np.zeros((Gp, s_pad), np.int32)
        suf_lens = np.ones(Gp, np.int32)
        temps = np.zeros(Gp, np.float32)
        topks = np.zeros(Gp, np.int32)
        keys = np.zeros((Gp, 2), np.uint32)
        for i, (seq, off, n, live) in enumerate(rows):
            addr[i] = self.cache.tables[seq.slot] if self._paged \
                else seq.slot
            ids[i, :n] = seq.work[off:off + n]
            suf_lens[i] = n
            prefix_lens[i] = off
            keys[i] = np.asarray(seq.key)
            if live:
                temps[i] = float(seq.request.temperature)
                topks[i] = int(seq.request.top_k)
        # pool arrays in program-argument form: (data, scale) pairs on
        # an int8 pool, plain arrays otherwise (PagedKVCache.kv_args)
        kv = self.cache.kv_args()
        with self._tspan("prefill_launch",
                         args={"bucket": s_pad, "group": len(rows)}):
            # host arrays pass uncoerced (see _admit_cold): the cost
            # facade counts the call's real host→device upload bytes
            nk, nv, tok0s, keys2 = self._suffix_fn()(
                self._params, *kv, addr, prefix_lens, ids, suf_lens,
                keys, temps, topks)
            self.cache.update(nk, nv)
            tok0s = np.asarray(tok0s)
        co = self._co()
        if co is not None:
            # sharded suffix/chunk prefill: one pass, padded group
            self._record_collectives(co, [(Gp * s_pad, 1)])
        return tok0s, keys2

    def _run_prefill_chunks(self, plan, finished):
        """Run this step's budgeted slice of the chunked-prefill
        backlog: ONE paged suffix-prefill device call per chunk-length
        bucket (normally exactly one — full chunks share the
        ``prefill_chunk`` bucket, so the compile set stays closed over
        the pow2 (group, bucket) grid no matter how prompt lengths
        vary). Each chunk writes K/V through the sequence's block table
        at its host resume offset — the same program, offset machinery,
        and zero-copy discipline as the prefix-hit suffix path.

        Only a FINAL chunk (one that completes the prompt) samples:
        its logits produce token 0 and its split key is adopted, so the
        PRNG walk — and therefore the token stream — is byte-identical
        to a one-shot prefill. Non-final chunks run greedy-off rows and
        their sampled output is discarded untouched."""
        by_bucket = {}
        for seq, n in plan:
            by_bucket.setdefault(self._bucket(n), []).append((seq, n))
        for s_pad, group in sorted(by_bucket.items()):
            rows = []
            for seq, n in group:
                off = seq.prefilled
                self.cache.ensure_capacity(seq.slot, off + n)
                # final chunk (completes the work content): sampling live
                rows.append((seq, off, n, off + n == seq.work_len))
            tok0s, keys2 = self._suffix_call(s_pad, rows)
            for i, (seq, n) in enumerate(group):
                self._advance_chunk(seq, n, tok0s[i], keys2[i], finished)

    def _advance_chunk(self, seq, n, tok0, key0, finished):
        """Per-chunk completion bookkeeping shared by the two-program
        chunk call and the unified ragged step — the ONE place chunk
        accounting and the final-chunk install live, so the two step
        paths cannot silently diverge. ``tok0``/``key0`` are the chunk
        row's sampled token + advanced key, consumed only when this
        chunk completes the prompt."""
        slot, end = seq.slot, seq.prefilled + n
        seq.launches += 1               # rode this chunk's device call
        self.stats["prefill_chunks"] += 1
        self.stats["chunk_tokens"] += n
        tr = self._tr()
        if tr is not None:
            # one lifecycle span per chunk on the request's lane:
            # prefill_chunk[i] from the previous mark (admission or the
            # prior chunk) to this chunk's host completion
            tr.complete(f"prefill_chunk[{seq.trace_chunk_i}]",
                        seq.trace_mark, tid=tr.req_tid(seq.request_id),
                        args={"tokens": n, "offset": seq.prefilled})
            seq.trace_mark = tr.now()
            seq.trace_chunk_i += 1
        self.cache.lengths[slot] = end
        seq.prefilled = end
        if end == seq.work_len:             # work content complete
            self.scheduler.leave_prefill(seq)
            self.stats["prefill_tokens_saved"] += seq.prefix_hit_tokens
            self._install_seq(seq, slot, tok0, key0,
                              seq.work_len - seq.prefix_hit_tokens,
                              finished)

    def _install_seq(self, seq, slot, tok0, key2, prefilled_tokens,
                     finished):
        """Post-prefill slot bookkeeping shared by the cold and hit
        admission paths — the ONE place a future per-slot knob gets
        wired, so the two paths cannot silently diverge.
        ``prefilled_tokens`` is the device prefill work actually done
        (full prompt cold, uncovered suffix on a hit).

        A RESTORED sequence (``restore_point > 0``, recovery-by-
        recompute after a crash or preemption) takes the same slot
        bookkeeping but adopts no sampled output: its next decode input
        is the last token it already streamed (for a greedy request the
        prefill's argmax reproduces it anyway — the logits at the end of
        ``work`` are the logits that sampled it originally) and its PRNG
        walk resumes from the key snapshot taken when it was displaced,
        so the continuation is byte-identical and no consumer ever sees
        a replayed token."""
        req = seq.request
        seq.slot = slot
        seq.status = "running"
        if seq.t_admitted is None:      # first claim only: queue wait
            seq.t_admitted = self._stamp_now()  # kept across restore
        tr = self._tr()
        if tr is not None:
            self._trace_phase_end(
                tr, seq, args={"prefix_hit_tokens": seq.prefix_hit_tokens,
                               "restored": bool(seq.restore_point)})
        seq.trace_phase = "decode"      # tracked even with tracing off
        self._slots[slot] = seq
        self._temps[slot] = float(req.temperature)
        self._topks[slot] = int(req.top_k)
        self.stats["prefills"] += 1
        self.stats["prefill_tokens"] += int(prefilled_tokens)
        if seq.restore_point:
            self._last_tok[slot] = int(seq.tokens[-1])
            self._keys = self._keys.at[slot].set(jnp.asarray(seq.key))
            return
        seq.tokens = [int(tok0)]
        self._last_tok[slot] = seq.tokens[0]
        self._keys = self._keys.at[slot].set(key2)
        self.stats["tokens_generated"] += 1
        self._emit(seq, seq.tokens[0])
        self._maybe_finish(seq, finished)

    def _maybe_finish(self, seq, finished):
        req = seq.request
        t = seq.tokens[-1]
        if req.eos_token_id is not None and t == int(req.eos_token_id):
            self._finish(seq, "stop", finished)
        elif len(seq.tokens) >= int(req.max_new_tokens):
            self._finish(seq, "length", finished)

    def _finish(self, seq, reason, finished):
        if seq.status == "prefilling":
            # cancellation / deadline expiry mid-chunk: out of the
            # chunk pipeline before the slot teardown below frees (or
            # donates) the partially installed block chain
            self.scheduler.leave_prefill(seq)
        seq.status = "finished"
        seq.finish_reason = reason
        seq.t_finish = self._stamp_now()
        tr = self._tr()
        if tr is not None:
            args = {"finish_reason": reason, "tokens": len(seq.tokens)}
            if seq.trace_accepts:
                args["accept_lens"] = list(seq.trace_accepts)
            self._trace_phase_end(tr, seq, args=args)
            tr.instant("finished", tid=tr.req_tid(seq.request_id),
                       args={"finish_reason": reason})
        slot = seq.slot
        if slot is not None and self._slots[slot] is seq:
            self._slots[slot] = None
            # reset the slot's knobs: a stale temperature would keep the
            # sampler's all-greedy fast path (decode.sample_rows)
            # disabled for every later greedy-only batch
            self._temps[slot] = 0.0
            self._topks[slot] = 0
            self._last_tok[slot] = 0
            self._donate_and_free(seq, slot)
        if self.prefix_cache is not None and seq.prefix_nodes:
            self.prefix_cache.release(seq.prefix_nodes)
            seq.prefix_nodes = []
        finished.append(seq)
        if self.on_finish is not None:
            self.on_finish(seq)

    def _donate_and_free(self, seq, slot):
        """Slot teardown shared by retirement (:meth:`_finish`) and
        preemption (:meth:`_preempt`) — the ONE place the
        donate-vs-free ownership handoff lives, so the two paths cannot
        silently diverge. Publish BEFORE freeing: the slot's prompt
        rows/blocks are intact (decode only ever appended past them)
        and the sequence's own pins still shield its matched chain from
        eviction during the publish walk.

        Paged + trie: DONATE the slot's full blocks (ownership handoff,
        zero copies); ``free`` then drops only the undonated private
        tail. The donation range is every row actually written — prompt
        AND generated tokens (a multi-turn resubmission of this
        sequence's assistant text hits these blocks), capped at the
        written row count: the last sampled token's KV is never in the
        cache (it would be appended by the decode tick that never ran),
        and a mid-prefill teardown has only ``prefilled`` valid rows."""
        if self.prefix_cache is not None and self._paged:
            with self._tspan("donate", args={"slot": slot}):
                written = int(self.cache.lengths[slot])
                content = seq.prompt if not seq.tokens else np.concatenate(
                    [seq.prompt, np.asarray(seq.tokens, np.int32)])
                donated = self.prefix_cache.publish_donate(
                    content[:written], self.cache.slot_block_ids(slot))
                self.cache.free(slot, keep=donated)
        elif self.prefix_cache is not None:
            with self._tspan("donate", args={"slot": slot}):
                self.prefix_cache.publish(seq.prompt, slot, self.cache)
                self.cache.free(slot)
        else:
            self.cache.free(slot)

    def _expire_deadlines(self, seqs, finished):
        """Retire every sequence whose deadline has passed. Runs once at
        the top of each step, over the queue (an expired request must
        not claim a slot) and the active slots (a running sequence stops
        paying for decode at the first step boundary past its
        deadline)."""
        now = time.monotonic()
        for seq in seqs:
            if seq.done or seq.deadline is None or now < seq.deadline:
                continue
            if seq.status == "queued" and not self.scheduler.remove(seq):
                continue
            self.stats["timeouts"] += 1
            self._finish(seq, "timeout", finished)

    def _emit(self, seq, token):
        if seq.t_first_token is None:
            seq.t_first_token = self._stamp_now()
        seq.t_last_token = self._stamp_now()
        if self.on_token is not None:
            self.on_token(seq, token)

    def step(self):
        """Admit + this step's budgeted prefill-chunk grant + decode +
        retire. On the unified engine (``ragged_step=True``) the grant
        and the decode tick are ONE device program; on the two-program
        baseline they are the PR-5 chunk-call + fused-decode-call pair.
        Returns every sequence this step finished (possibly empty),
        deadline expiries included — queue-side timeouts come back with
        ``slot=None`` and no tokens. Only :meth:`cancel` retires
        outside a step; those surface through ``on_finish`` / the
        Sequence handle alone.

        Fault repair: a :class:`~.kv_cache.PoolExhausted` raised
        anywhere in the step body (block growth on a mis-sized shared
        pool, or injected by a fault plan) is caught HERE — any
        admission left half-done is unwound back to the queue, the
        YOUNGEST slot-holding sequence is preempted by recompute
        (:meth:`_preempt`: its chain donates to the prefix trie, so the
        re-queued prefill is usually a zero-copy trie hit), and the
        step retries without re-admitting. Exhaustion that no
        preemption can repair re-raises. Anything the injected
        ``fault_hook`` raises other than PoolExhausted propagates to
        the driver (the gateway's supervisor)."""
        t0 = self._clock()
        self._stamp_t = t0
        tr = self._tr()
        ts0 = tr.now() if tr is not None else None
        co = self._co()
        cost0 = co.snapshot() if co is not None else None
        finished = []
        # deadline sweep BEFORE admission: an expired queued request
        # must never claim a slot (and a running one stops paying for
        # decode at the first step boundary past its deadline)
        self._expire_deadlines(
            list(self.scheduler.queue)
            + [s for s in self._slots if s is not None], finished)
        step_tokens, had_chunks = 0, False
        admitted = []
        for attempt in range(self.num_slots + 2):
            try:
                if self.fault_hook is not None:
                    self.fault_hook(self)
                if attempt == 0:
                    if self._policy:
                        # policy decisions record through the step's
                        # already-guarded tracer; displace best-effort
                        # work BEFORE admission so the freed slots are
                        # in num_free for this very step's admission
                        self.scheduler.tracer = tr
                        self._policy_preempt()
                    admitted = self.scheduler.admissions(
                        self.cache.num_free,
                        hit_len_fn=self._admission_hit_len
                        if self.prefix_cache is not None else None)
                    if admitted:
                        if co is not None:
                            co.set_phase("admit")
                        with self._tspan("admit",
                                         args={"n": len(admitted)}):
                            self._admit_group(admitted, finished)
                if self._spec:
                    step_tokens, had_chunks = self._spec_step(finished)
                elif self._mtick:
                    step_tokens, had_chunks = self._multitick_step(
                        finished)
                elif self._ragged:
                    step_tokens, had_chunks = self._unified_step(finished)
                else:
                    step_tokens, had_chunks = self._two_program_step(
                        finished)
                break
            except PoolExhausted:
                # unwind, preempt, retry — no device work was committed
                # for the failed attempt (every raise site runs before
                # its device call), so host bookkeeping is consistent
                self._abort_admission(admitted)
                admitted = []
                if not self._preempt_youngest():
                    self._stamp_t = None    # leaving the step: stamps
                    raise                   # must read a fresh clock
            except BaseException:
                # ANY other failure escaping mid-admission (a real
                # device/runtime error — the crash class the supervisor
                # rebuilds for) must not strand popped-but-uninstalled
                # sequences in limbo: back to the queue they go, where
                # crash recovery's snapshot can see them
                self._abort_admission(admitted)
                self._stamp_t = None
                raise
        self.stats["steps"] += 1
        self._record_step(self._clock() - t0, step_tokens, had_chunks)
        self._stamp_t = None
        if co is not None:
            co.set_phase(None)
        if tr is not None:
            tr.complete("step", ts0,
                        args={"step": self.stats["steps"] - 1,
                              "tokens": step_tokens,
                              "chunks": bool(had_chunks)})
            # counter tracks (ph:"C") on the same timeline as the step
            # spans, so Perfetto graphs cost alongside the phases:
            # KV-pool occupancy + table pressure, and (with the cost
            # observatory on) this step's dispatch/transfer deltas
            if self._paged:
                tr.counter("kv_blocks", self.cache.occupancy())
                tr.counter("block_table_fill",
                           {"fill": round(self.cache.table_fill(), 6)})
            if co is not None:
                d = co.delta(cost0)
                tr.counter("dispatches",
                           {"per_step": d["dispatches"],
                            "compiles": d["compiles"]})
                tr.counter("transfer_bytes",
                           {"h2d": d["h2d_bytes"],
                            "d2h": d["d2h_bytes"]})
        return finished

    # ----------------------------------------------------- fault recovery
    def _abort_admission(self, seqs):
        """Unwind a half-done admission after a step-body failure:
        every popped sequence not yet installed goes back to the queue
        HEAD in its original FIFO order (by ``queue_tick`` — the batch
        itself arrives suffix-sorted, so arrival order must come from
        the stamp), its claimed slot freed (partial block growth
        included — ``free`` drops exactly the owned tail) and its
        prefix pins released, so ``num_free`` and the pool refcounts
        are exactly what they were before the attempt."""
        tr = self._tr()
        for seq in sorted(seqs, key=lambda s: -s.queue_tick):
            if seq.status != "queued":
                continue      # installed (running/prefilling) — keep
            if self.prefix_cache is not None and seq.prefix_nodes:
                self.prefix_cache.release(seq.prefix_nodes)
                seq.prefix_nodes = []
            seq.prefix_hit_tokens = 0
            if seq.slot is not None:
                if self._slots[seq.slot] is None:
                    self.cache.free(seq.slot)
                seq.slot = None
            if seq.trace_phase == "prefill":
                # the admission this step ran was unwound: back to a
                # fresh queued span (the aborted attempt stays visible
                # as the closed span that preceded it)
                if tr is not None:
                    tr.instant("admission_aborted",
                               tid=tr.req_tid(seq.request_id))
                seq.trace_phase = "queued"
                seq.trace_mark = tr.now() if tr is not None else None
            self.scheduler.requeue_front(seq)

    def _class_slot_usage(self):
        """Running-count-per-class-name ledger for the policy
        scheduler's headroom math: a walk of the slot array (prefilling
        sequences hold slots and count — a reservation is about slot
        occupancy, not decode state)."""
        used = {}
        for seq in self._slots:
            if seq is None or seq.done:
                continue
            pclass = getattr(seq, "pclass", None)
            if pclass is not None:
                used[pclass.name] = used.get(pclass.name, 0) + 1
        return used

    def _policy_preempt(self):
        """SLO-driven preemption (README "Multi-tenant SLO serving"):
        when queued requests have burned past the urgency fraction of
        their TTFT budget and free slots cannot cover them, displace
        one strictly-lower-rank running sequence per uncovered urgent
        request through the ordinary preemption-by-recompute path
        (:meth:`_preempt` — chain donated to the trie, PRNG walk
        snapshotted, stream byte-identical after restore). Runs before
        admission at the top of the step, inside the step's stamp
        window, so urgency and victim choice replay deterministically
        under an injected clock. With nothing below the urgent rank in
        the slots, the request keeps waiting — equals never displace
        equals."""
        urgent = self.scheduler.urgent(self._stamp_t)
        if not urgent:
            return
        free = self.cache.num_free
        tr = self._tr()
        for seq in urgent[free:]:
            pclass = getattr(seq, "pclass", None)
            rank = pclass.rank if pclass is not None else 0
            victims = select_victims(self._slots, 1, rank)
            if not victims:
                continue
            victim = victims[0]
            self.stats["policy_preemptions"] += 1
            if tr is not None:
                tr.instant(
                    "policy_preempt",
                    args={"urgent": seq.request_id,
                          "victim": victim.request_id,
                          "victim_class": getattr(
                              victim.pclass, "name", None)})
            if self.on_policy_preempt is not None:
                self.on_policy_preempt(victim)
            self._preempt(victim)

    def _preempt_youngest(self) -> bool:
        """PoolExhausted repair: displace the YOUNGEST slot-holding
        sequence (latest arrival — the one with the least sunk work and
        the least head-of-line seniority). Returns False when no slot
        holds a preemptible sequence."""
        victims = [s for s in self._slots if s is not None and not s.done]
        if not victims:
            return False
        self._preempt(max(victims, key=lambda s: s.request_id))
        return True

    def _displace(self, seq, reason):
        """Slot teardown shared by preemption (:meth:`_preempt`) and
        cross-engine eviction (:meth:`evict`) — free the sequence's
        slot NOW, donating its written chain (prompt + generated
        blocks) to the prefix trie when one is on, exactly like
        retirement, and snapshot the slot's CURRENT PRNG key — what the
        next decode tick would have sampled with — so the recomputed
        continuation resumes the identical walk. A mid-recompute
        (prefilling, ``restore_point > 0``) sequence keeps the snapshot
        it already carries: its key was never installed into the slot
        array. Leaves the sequence slotless and un-queued; the caller
        decides which engine's :meth:`restore` re-admits it."""
        slot = seq.slot
        tr = self._tr()
        if tr is not None:
            self._trace_phase_end(
                tr, seq, args={reason: True,
                               "tokens": len(seq.tokens)})
            tr.instant(reason, tid=tr.req_tid(seq.request_id),
                       args={"slot": slot})
        if seq.status == "prefilling":
            self.scheduler.leave_prefill(seq)
        if seq.tokens and seq.status == "running":
            seq.key = np.asarray(self._keys, np.uint32)[slot].copy()
        self._slots[slot] = None
        self._temps[slot] = 0.0
        self._topks[slot] = 0
        self._last_tok[slot] = 0
        self._donate_and_free(seq, slot)
        if self.prefix_cache is not None and seq.prefix_nodes:
            self.prefix_cache.release(seq.prefix_nodes)
            seq.prefix_nodes = []
        seq.slot = None

    def _preempt(self, seq):
        """Preemption-by-recompute: displace the sequence
        (:meth:`_displace` — chain donated, PRNG snapshotted) and
        re-queue it HERE via :meth:`restore`. Because the chain was
        just donated, the recompute prefill is typically a zero-copy
        trie hit; the PRNG walk snapshot keeps the continuation
        byte-identical. Nothing is emitted and the sequence does not
        finish — consumers just see a pause."""
        self.stats["preemptions"] += 1
        self._displace(seq, "preempted")
        self.restore(seq)
        seq.trace_phase = "preempted"   # restore() named it "recovered"

    def evict(self, seq: Sequence) -> bool:
        """Remove a LIVE sequence from this engine for cross-engine
        migration (the fleet's live request migration / drain path):
        same displacement as preemption — chain donated to THIS
        engine's trie, PRNG walk snapshotted — but ownership leaves
        the engine: the caller re-admits via a SIBLING engine's
        :meth:`restore`, which rebuilds KV by recompute so the
        continuation is byte-identical on the new engine. A
        still-queued sequence is simply removed from the scheduler
        (nothing to displace). Must be called from the thread driving
        :meth:`step`. Returns False for a finished sequence or one
        this engine does not hold."""
        if seq.done:
            return False
        if seq.status == "queued":
            return self.scheduler.remove(seq)
        if seq.slot is None or self._slots[seq.slot] is not seq:
            return False
        self._displace(seq, "evicted")
        seq.status = "queued"   # slotless, awaiting the target restore
        return True

    def restore(self, seq: Sequence) -> bool:
        """Re-enqueue a LIVE sequence for recovery-by-recompute (crash
        recovery and preemption both land here): its prompt and
        generated-so-far tokens are known host-side, so its KV is
        rebuilt by prefilling ``prompt + tokens[:-1]`` — chunked when
        long, and often a zero-copy prefix-trie hit on a donated chain
        — after which decode resumes from the last generated token with
        the saved PRNG walk. Greedy streams continue byte-identically
        (the recompute reproduces the exact logits), consumers never
        see a replayed token, and a pre-token sequence simply requeues.
        The caller must have torn down any slot state first (crash
        recovery starts from a fresh engine; :meth:`_preempt` frees the
        slot). Returns False for an already-finished sequence."""
        if seq.done:
            return False
        seq.status = "queued"
        seq.slot = None
        seq.prefix_nodes = []
        seq.prefix_hit_tokens = 0
        seq.prefilled = 0
        seq.restore_point = len(seq.tokens)
        tr = self._tr()
        # the wait-until-readmission span: "recovered" (the gateway
        # restoring onto a rebuilt engine lands here directly);
        # _preempt renames its own restores to "preempted" right after
        # this call. The name tracks state even with tracing off.
        seq.trace_phase = "recovered"
        seq.trace_mark = tr.now() if tr is not None else None
        if seq.tokens:
            seq.work = np.concatenate(
                [seq.prompt, np.asarray(seq.tokens[:-1], np.int32)])
        else:
            seq.work = seq.prompt
        self.stats["restores"] += 1
        self.scheduler.submit(seq)
        return True

    def _record_step(self, dt, tokens, had_chunks):
        """Feed the step's measured duration + processed tokens into
        the stats surface (``serving_step_duration_seconds`` /
        ``serving_step_tokens`` on /metrics read exactly these) and,
        on the unified engine, into the headroom EWMAs the adaptive
        chunk budget derives from."""
        self.stats["last_step_duration_s"] = float(dt)
        self.stats["last_step_tokens"] = int(tokens)
        if not (self._ragged or self._spec) or tokens <= 0 or dt <= 0:
            return
        a = 0.2
        if had_chunks:
            # packed-step throughput: what a chunk-carrying unified
            # step actually moves per second. Decode-only steps must
            # NOT feed this — their tokens/s is an autoregressive
            # rate, ~budget-fold below what the packed buffer absorbs
            tps = tokens / dt
            self._tps_ewma = tps if self._tps_ewma is None \
                else (1 - a) * self._tps_ewma + a * tps
            self.stats["headroom_tps"] = self._tps_ewma
        else:
            self._dt_decode_ewma = dt if self._dt_decode_ewma is None \
                else (1 - a) * self._dt_decode_ewma + a * dt

    def _prefill_budget(self):
        """This step's chunk-token grant: the measured-headroom budget
        (``headroom_tps x headroom_mult x decode-only step time``,
        minus the decode rows sharing the step), clamped to
        ``[1, prefill_chunk]`` — i.e. spend at most ~``headroom_mult``
        decode-steps' worth of measured time on the packed buffer, so
        chunk work throttles itself exactly when chunk-carrying steps
        run slower than the decode baseline. Before both EWMAs have a
        measurement — or with ``headroom_mult=None`` — the grant is
        the fixed cap, i.e. PR-5 pacing; under a SUSTAINED all-chunk
        regime the decode baseline is the last chunk-free step
        measured (decode-only steps are its only feed), so a backlog
        that never leaves the engine a chunk-free step keeps PR-5
        pacing rather than inventing a baseline. Sub-block grants are
        not wasted: the scheduler carries them to the next plan
        (``FIFOScheduler.prefill_plan``)."""
        cap = self._chunk
        if self._headroom_mult is None or self._tps_ewma is None \
                or self._dt_decode_ewma is None:
            self.stats["headroom"] = cap
            return cap
        n_dec = sum(1 for s in self._slots
                    if s is not None and s.status == "running")
        afford = int(self._tps_ewma * self._headroom_mult
                     * self._dt_decode_ewma) - n_dec
        budget = max(1, min(cap, afford))
        self.stats["headroom"] = budget
        return budget

    def _unified_step(self, finished):
        """ONE device call for everything this step advances: every
        running slot contributes a span-1 decode row and every planned
        prefill chunk a span-n row to the packed token buffer of the
        unified ragged program (``decode.build_ragged_step_fn``). This
        is the whole point of the unification — a mixed step launches
        one program where the two-program engine launched a chunk call
        plus a decode call, and a mid-prefill slot costs its chunk span
        instead of a discarded full-length decode row. Pure-decode
        steps still fuse ``choose_num_steps`` ticks (the scan tail of
        the same program). Returns ``(tokens_processed, had_chunks)``
        for the headroom EWMAs."""
        tr = self._tr()
        tp0 = tr.now() if tr is not None else None
        co = self._co()
        if co is not None:
            co.set_phase("plan")
        plan = []
        if self._chunk and self.scheduler.num_prefilling:
            plan = self.scheduler.prefill_plan(self._prefill_budget(),
                                               self.cache.block_size,
                                               cap=self._chunk)
        active = [s for s in self._slots
                  if s is not None and s.status == "running"]
        if not active and not plan:
            return 0, False
        n = self.scheduler.choose_num_steps(active) if active else 1
        R, T = self.num_slots, self._token_budget
        ids = np.zeros(T, np.int32)
        seg = np.full(T, R, np.int32)       # sentinel: dead packed rows
        pos = np.zeros(T, np.int32)
        qstart = np.zeros(R, np.int32)
        qlen = np.zeros(R, np.int32)
        kvlen = np.zeros(R, np.int32)
        dec_mask = np.zeros(R, np.int32)
        temps = np.zeros(R, np.float32)
        topks = np.zeros(R, np.int32)
        keys = np.asarray(self._keys, np.uint32).copy()
        cursor = self._pack_decode_rows(n, ids, seg, pos, qstart, qlen,
                                        kvlen, dec_mask, temps, topks)
        chunk_rows, cursor = self._pack_chunk_rows(
            plan, cursor, ids, seg, pos, qstart, qlen, kvlen, keys,
            temps, topks)
        if tr is not None:
            # plan: admission already ran in step(); this is the chunk
            # grant + span packing. launch: the one device program +
            # the host transfer that fences it. host-accept: token/
            # chunk bookkeeping (donate spans nest inside it).
            tr.complete("plan", tp0,
                        args={"rows": len(active), "chunks": len(plan),
                              "fused_steps": n})
            tl0 = tr.now()
        if co is not None:
            co.set_phase("launch")
        npk, npv, toks, keys_t0, keys_fin = self._ragged_fn(n)(
            self._params, *self.cache.kv_args(),
            self.cache.tables, ids, seg, pos, qstart, qlen, kvlen,
            dec_mask, keys, temps, topks)
        self.cache.update(npk, npv)
        toks_np = np.asarray(toks)          # [n, R]
        keys_t0_np = np.asarray(keys_t0)
        self.stats["unified_steps"] += 1
        if co is not None:
            # sharded launch: tick 0 all-reduces the PADDED packed
            # buffer (the device computes full shapes), each fused tail
            # tick the per-slot row block — exact, shape-derived
            self._record_collectives(
                co, [(self._token_budget, 1), (self.num_slots, n - 1)])
            co.set_phase("host-accept")
        if tr is not None:
            tr.complete("launch", tl0,
                        args={"packed_tokens": cursor, "fused_steps": n})
            th0 = tr.now()
        if active:
            # decode rows adopt the post-scan key walk; chunk/idle rows
            # keep their host-side key state (a final chunk adopts its
            # tick-0 key inside _install_seq below)
            self._keys = jnp.where(
                jnp.asarray(dec_mask[:, None].astype(bool)),
                keys_fin, self._keys)
        # chunk bookkeeping first — mirrors the two-program order where
        # the chunk call ran before the decode ticks surfaced tokens
        for slot, seq, ntok, final in chunk_rows:
            self._advance_chunk(seq, ntok, toks_np[0, slot],
                                keys_t0_np[slot], finished)
        if active:
            self.stats["decode_calls"] += 1
            self.stats["decode_steps"] += n
            self.stats["slot_steps"] += n * self.num_slots
            for slot in range(self.num_slots):
                s = self._slots[slot]
                if s is not None and dec_mask[slot]:
                    s.launches += 1     # rode this step's one program
            self._accept_decode_rows(toks_np, n, dec_mask, finished)
        if tr is not None:
            tr.complete("host-accept", th0,
                        args={"emitted": (n * len(active) if active
                                          else 0)})
        return cursor + (n - 1) * len(active), bool(chunk_rows)

    def _pack_decode_rows(self, n, ids, seg, pos, qstart, qlen, kvlen,
                          dec_mask, temps, topks, eos_ids=None,
                          budgets=None):
        """Pack every RUNNING slot's span-1 decode row into the packed
        token buffer — the ONE decode-row assembly shared by the
        unified and multi-tick steps (``_pack_chunk_rows``' twin), so
        the packing and table-pre-growth rules cannot silently
        diverge. Pre-grows each slot's table for the fused block:
        ``n`` rows on the unified scan (it appends unconditionally);
        ``min(n, remaining)`` when the alive-mask metadata
        (``eos_ids``/``budgets``) is being packed, because the device
        stops a row's appends exactly at its EOS/budget cut. Returns
        the cursor past the packed decode rows."""
        lens = self.cache.lengths
        cursor = 0
        for slot, s in enumerate(self._slots):
            if s is None or s.status != "running":
                continue
            grow = n if budgets is None else min(n, s.remaining)
            self.cache.ensure_capacity(slot, int(lens[slot]) + grow)
            qstart[slot] = cursor
            qlen[slot] = 1
            kvlen[slot] = int(lens[slot]) + 1
            dec_mask[slot] = 1
            ids[cursor] = self._last_tok[slot]
            seg[cursor] = slot
            pos[cursor] = int(lens[slot])
            temps[slot] = self._temps[slot]
            topks[slot] = self._topks[slot]
            if eos_ids is not None:
                eos = s.request.eos_token_id
                eos_ids[slot] = -1 if eos is None else int(eos)
                budgets[slot] = s.remaining
            cursor += 1
        return cursor

    def _accept_decode_rows(self, toks_np, n, dec_mask, finished,
                            counts=None):
        """Host-accept of the fused ticks' ``[n, R]`` token block —
        the ONE trim loop shared by the unified and multi-tick steps,
        so the accept/trim rules (EOS and budget cuts via
        ``_maybe_finish``, per-token bookkeeping) cannot silently
        diverge. Tick-major like the device computed it; a slot whose
        sequence finished at an earlier tick is skipped from then on
        (on the multi-tick path the device's alive cut equals this
        trim, so the skipped entries are masked garbage that never
        surfaces). ``counts`` (optional [R] array) receives each
        slot's accepted-token count — the multi-tick key-walk
        adoption index. Returns tokens emitted."""
        emitted = 0
        for i in range(n):
            for slot in range(self.num_slots):
                seq = self._slots[slot]
                if seq is None or seq.status != "running" \
                        or not dec_mask[slot]:
                    continue  # freed/mid-prefill slot, finished at an
                    # earlier tick, or a span this call did not decode
                    # (a chunk row installed above starts decoding
                    # NEXT step); its sampled garbage never surfaces
                t = int(toks_np[i, slot])
                seq.tokens.append(t)
                if counts is not None:
                    counts[slot] += 1
                self.cache.lengths[slot] += 1
                self._last_tok[slot] = t
                self.stats["active_slot_steps"] += 1
                self.stats["tokens_generated"] += 1
                emitted += 1
                self._emit(seq, t)
                self._maybe_finish(seq, finished)
        return emitted

    def _multitick_step(self, finished):
        """ONE device call that advances every slot by up to
        ``decode_ticks`` tokens (README "Multi-tick decode"): the
        unified ragged step with the per-token host round-trip
        amortized to one sync per ``n`` ticks. Every running slot
        contributes a span-1 decode row and every planned prefill
        chunk its span to the packed tick-0 buffer, exactly like
        :meth:`_unified_step`; the fused tail then runs ``n``
        (scheduler-chosen, runtime — one compilation serves them all)
        decode ticks with ON-DEVICE EOS/budget retirement: a finished
        row's appends drop inside the program precisely where the
        host's trim will cut, and the program returns early once
        every row is dead. The host accepts the whole ``[n, R]``
        token block in one ``host-accept``, trimming each slot at its
        first EOS/budget cut — byte-identical to tick-at-a-time —
        and adopts each surviving row's PRNG key at its trim cut from
        the returned key walk. Returns ``(tokens_processed,
        had_chunks)`` for the headroom EWMAs."""
        tr = self._tr()
        tp0 = tr.now() if tr is not None else None
        co = self._co()
        if co is not None:
            co.set_phase("plan")
        plan = []
        if self._chunk and self.scheduler.num_prefilling:
            plan = self.scheduler.prefill_plan(self._prefill_budget(),
                                               self.cache.block_size,
                                               cap=self._chunk)
        active = [s for s in self._slots
                  if s is not None and s.status == "running"]
        if not active and not plan:
            return 0, False
        n = self.scheduler.choose_decode_ticks(active,
                                               self._decode_ticks)
        R, T = self.num_slots, self._token_budget
        ids = np.zeros(T, np.int32)
        seg = np.full(T, R, np.int32)       # sentinel: dead packed rows
        pos = np.zeros(T, np.int32)
        qstart = np.zeros(R, np.int32)
        qlen = np.zeros(R, np.int32)
        kvlen = np.zeros(R, np.int32)
        dec_mask = np.zeros(R, np.int32)
        temps = np.zeros(R, np.float32)
        topks = np.zeros(R, np.int32)
        eos_ids = np.full(R, -1, np.int32)  # -1: no EOS configured
        budgets = np.zeros(R, np.int32)
        keys = np.asarray(self._keys, np.uint32).copy()
        # packing eos_ids/budgets switches _pack_decode_rows to the
        # alive-mask pre-growth: the WHOLE block's capacity up front
        # (min(n, remaining) rows — the device stops at the cut), so
        # no mid-block host intervention, no fallback at block
        # boundaries
        cursor = self._pack_decode_rows(n, ids, seg, pos, qstart, qlen,
                                        kvlen, dec_mask, temps, topks,
                                        eos_ids=eos_ids,
                                        budgets=budgets)
        chunk_rows, cursor = self._pack_chunk_rows(
            plan, cursor, ids, seg, pos, qstart, qlen, kvlen, keys,
            temps, topks)
        if tr is not None:
            tr.complete("plan", tp0,
                        args={"rows": len(active), "chunks": len(plan),
                              "ticks": n})
            tl0 = tr.now()
        if co is not None:
            co.set_phase("launch")
        npk, npv, toks, kwalk, ticks_run = self._mtick_fn()(
            self._params, *self.cache.kv_args(),
            self.cache.tables, ids, seg, pos, qstart, qlen, kvlen,
            dec_mask, keys, temps, topks, eos_ids, budgets,
            np.int32(n))
        self.cache.update(npk, npv)
        toks_np = np.asarray(toks)          # [max_ticks, R]
        kwalk_np = np.asarray(kwalk)        # [max_ticks, R, 2]
        ticks = int(ticks_run)              # <= n: early exit when all
        self.stats["unified_steps"] += 1    # rows retire on device
        if co is not None:
            # multi-tick sharded launch: tick 0 on the padded packed
            # buffer + the ticks the while_loop ACTUALLY ran (early
            # exit spends no wire) on the per-slot row block
            self._record_collectives(
                co, [(self._token_budget, 1),
                     (self.num_slots, ticks - 1)])
            co.set_phase("host-accept")
        if tr is not None:
            tr.complete("launch", tl0,
                        args={"packed_tokens": cursor, "ticks": n,
                              "ticks_run": ticks})
            th0 = tr.now()
        # chunk bookkeeping first — mirrors the unified-step order (a
        # final chunk adopts tick 0's token/key, the same one split as
        # a one-shot prefill)
        for slot, seq, ntok, final in chunk_rows:
            self._advance_chunk(seq, ntok, toks_np[0, slot],
                                kwalk_np[0, slot], finished)
        emitted_total = 0
        if active:
            self.stats["decode_calls"] += 1
            self.stats["decode_steps"] += ticks
            self.stats["slot_steps"] += ticks * self.num_slots
            self.stats["mtick_syncs"] += 1
            self.stats["mtick_ticks"] += ticks
            if not chunk_rows:
                # every span was a qlen<=1 decode row: the program's
                # pure-decode predicate held, so a fused engine ran
                # tick 0 through the whole-tick kernel (the bench's
                # exact device-launch accounting reads this count)
                self.stats["mtick_pure_syncs"] += 1
            self.stats["last_decode_ticks"] = ticks
            counts = np.zeros(R, np.int32)  # accepted tokens per slot
            for slot in range(self.num_slots):
                s = self._slots[slot]
                if s is not None and dec_mask[slot]:
                    s.launches += 1     # rode this step's one program
            emitted_total = self._accept_decode_rows(
                toks_np, ticks, dec_mask, finished, counts=counts)
            # adopt each SURVIVING decode row's key at its trim cut:
            # keys_walk[m - 1] for a row that accepted m tokens (a
            # still-running row accepted every tick, so this is the
            # post-block key — same walk position as m sequential
            # ticks). Finished slots are freed; idle/chunk rows keep
            # their host key state. Snapshot AFTER chunk bookkeeping:
            # a final chunk's _install_seq key write must survive.
            knp = np.asarray(self._keys, np.uint32).copy()
            adopted = False
            for slot in range(self.num_slots):
                seq = self._slots[slot]
                if seq is None or not dec_mask[slot] \
                        or seq.status != "running" \
                        or counts[slot] == 0:
                    continue
                knp[slot] = kwalk_np[counts[slot] - 1, slot]
                adopted = True
            if adopted:
                self._keys = jnp.asarray(knp)
        if tr is not None:
            tr.complete("host-accept", th0,
                        args={"emitted": emitted_total,
                              "ticks_run": ticks})
        return sum(c for _, c in plan) + emitted_total, bool(chunk_rows)

    def _pack_chunk_rows(self, plan, cursor, ids, seg, pos, qstart, qlen,
                         kvlen, keys, temps, topks, sample_start=None):
        """Pack this step's planned prefill chunks into the packed
        token buffer — the ONE chunk-row assembly shared by the
        unified and speculative steps, so their packing rules (block
        growth, span metadata, the final-chunk-only sampling rule)
        cannot silently diverge. ``sample_start`` is the speculative
        program's extra metadata: a chunk row samples at its span END
        (token 0); the unified program derives that position in-program
        and passes None. Returns ``(chunk_rows, cursor)``."""
        chunk_rows = []                     # (slot, seq, n_tokens, final)
        for seq, ntok in plan:
            slot, off = seq.slot, seq.prefilled
            self.cache.ensure_capacity(slot, off + ntok)
            final = off + ntok == seq.work_len
            qstart[slot] = cursor
            qlen[slot] = ntok
            kvlen[slot] = off + ntok
            if sample_start is not None:
                sample_start[slot] = cursor + ntok - 1
            ids[cursor:cursor + ntok] = seq.work[off:off + ntok]
            seg[cursor:cursor + ntok] = slot
            pos[cursor:cursor + ntok] = np.arange(off, off + ntok,
                                                  dtype=np.int32)
            # chunk rows sample (and advance the PRNG) only on their
            # FINAL chunk — the same rule as the two-program path, so
            # streams stay byte-identical to a one-shot prefill
            keys[slot] = np.asarray(seq.key)
            if final:
                temps[slot] = float(seq.request.temperature)
                topks[slot] = int(seq.request.top_k)
            chunk_rows.append((slot, seq, ntok, final))
            cursor += ntok
        return chunk_rows, cursor

    def _spec_step(self, finished):
        """ONE device call for everything a speculative step advances
        (README "Speculative decoding"): every running slot contributes
        a DRAFT-EXTENDED verify span — ``[last_token, d_1 .. d_k]``,
        the drafter's guesses appended through the block tables exactly
        like a prefill chunk — and every planned prefill chunk its
        span, to the packed buffer of the verify program
        (``decode.build_spec_verify_fn``). The program samples
        ``spec_k + 1`` consecutive positions per row under the standard
        split-per-token PRNG walk; the host accepts the longest draft
        prefix the target model reproduced, emits those tokens plus the
        model's own correction at the first mismatch (so every launch
        yields >= 1 token and acceptance only reorders work — streams
        are byte-identical to speculation off, greedy AND sampled), and
        rolls rejected draft K/V back by truncating the slot's private
        block tail (``PagedKVCache.truncate`` — exact num_free/refcount
        restoration, donated trie blocks untouched).

        Budget discipline: drafts share the packed buffer's headroom
        with the chunk grant (``FIFOScheduler.spec_grants`` — a verify
        span spends ``1 + k`` positions), so chunk-heavy steps throttle
        speculation instead of overflowing the compile geometry.
        Returns ``(tokens_processed, had_chunks)`` for the headroom
        EWMAs."""
        tr = self._tr()
        tp0 = tr.now() if tr is not None else None
        co = self._co()
        if co is not None:
            co.set_phase("plan")
        plan = []
        if self._chunk and self.scheduler.num_prefilling:
            plan = self.scheduler.prefill_plan(self._prefill_budget(),
                                               self.cache.block_size,
                                               cap=self._chunk)
        active = [(slot, s) for slot, s in enumerate(self._slots)
                  if s is not None and s.status == "running"]
        if not active and not plan:
            return 0, False
        R, T = self.num_slots, self._spec_budget
        lens = self.cache.lengths
        chunk_spend = sum(n for _, n in plan)
        # drafter proposals, clipped per row to the verify depth, the
        # token budget (a verify emits at most k+1 tokens — proposing
        # past remaining-1 is wasted span), and the KV capacity
        drafts = []
        for slot, s in active:
            cap = min(self._spec_k, s.remaining - 1,
                      self.max_seq_len - int(lens[slot]) - 1)
            d = self.drafter.propose(s, cap) if cap > 0 else ()
            drafts.append(np.asarray(d, np.int32).reshape(-1)[:max(cap, 0)])
        grants = self.scheduler.spec_grants(
            [len(d) for d in drafts], T - R - chunk_spend)
        ids = np.zeros(T, np.int32)
        seg = np.full(T, R, np.int32)       # sentinel: dead packed rows
        pos = np.zeros(T, np.int32)
        qstart = np.zeros(R, np.int32)
        qlen = np.zeros(R, np.int32)
        kvlen = np.zeros(R, np.int32)
        sample_start = np.zeros(R, np.int32)
        temps = np.zeros(R, np.float32)
        topks = np.zeros(R, np.int32)
        keys = np.asarray(self._keys, np.uint32).copy()
        cursor = 0
        verify_rows = []                    # (slot, seq, draft, len0)
        for (slot, s), d, g in zip(active, drafts, grants):
            d = d[:g]
            L0 = int(lens[slot])
            q = 1 + len(d)
            # the verify span appends draft K/V rows [L0, L0+q) — the
            # table must cover them pre-call (rejected rows hand their
            # blocks back through truncate below)
            self.cache.ensure_capacity(slot, L0 + q)
            qstart[slot] = cursor
            qlen[slot] = q
            kvlen[slot] = L0 + q
            sample_start[slot] = cursor     # sample EVERY span position
            ids[cursor] = self._last_tok[slot]
            if len(d):
                ids[cursor + 1:cursor + q] = d
            seg[cursor:cursor + q] = slot
            pos[cursor:cursor + q] = np.arange(L0, L0 + q, dtype=np.int32)
            temps[slot] = self._temps[slot]
            topks[slot] = self._topks[slot]
            verify_rows.append((slot, s, d, L0))
            cursor += q
        chunk_rows, cursor = self._pack_chunk_rows(
            plan, cursor, ids, seg, pos, qstart, qlen, kvlen, keys,
            temps, topks, sample_start=sample_start)
        if tr is not None:
            tr.complete("plan", tp0,
                        args={"rows": len(active), "chunks": len(plan),
                              "draft_tokens": int(sum(grants))})
            tl0 = tr.now()
        if co is not None:
            co.set_phase("launch")
        npk, npv, toks, kwalk = self._spec_fn()(
            self._params, *self.cache.kv_args(),
            self.cache.tables, ids, seg, pos, qstart, qlen, kvlen,
            sample_start, keys, temps, topks)
        self.cache.update(npk, npv)
        toks_np = np.asarray(toks)          # [spec_len, R]
        kwalk_np = np.asarray(kwalk)        # [spec_len, R, 2]
        self.stats["spec_steps"] += 1
        if co is not None:
            # one packed verify forward per spec step (no decode tail)
            self._record_collectives(co, [(self._spec_budget, 1)])
            co.set_phase("host-accept")
        if tr is not None:
            tr.complete("launch", tl0,
                        args={"packed_tokens": cursor})
            th0 = tr.now()
        # chunk bookkeeping first — mirrors the unified-step order (a
        # final chunk adopts its walk-step-0 token/key, the same one
        # split as a one-shot prefill)
        for slot, seq, ntok, final in chunk_rows:
            self._advance_chunk(seq, ntok, toks_np[0, slot],
                                kwalk_np[0, slot], finished)
        emitted_total = 0
        accept_lens = []
        if verify_rows:
            self.stats["decode_calls"] += 1
            self.stats["decode_steps"] += 1
            self.stats["slot_steps"] += self.num_slots
            # snapshot AFTER chunk bookkeeping: a final chunk's
            # _install_seq key write must survive the batched update
            knp = np.asarray(self._keys, np.uint32).copy()
            for slot, seq, d, L0 in verify_rows:
                seq.launches += 1       # rode this step's one verify
                a = 0
                while a < len(d) and int(toks_np[a, slot]) == int(d[a]):
                    a += 1
                req = seq.request
                emit = []
                for j in range(a + 1):
                    t = int(toks_np[j, slot])
                    emit.append(t)
                    if req.eos_token_id is not None \
                            and t == int(req.eos_token_id):
                        break       # sequential decode would stop here
                    if len(seq.tokens) + len(emit) \
                            >= int(req.max_new_tokens):
                        break
                m = len(emit)
                # rollback: rows [L0, L0 + 1 + len(d)) were written;
                # only [L0, L0 + m) are confirmed — the last emitted
                # token's own KV is at L0 + m, NOT in the cache, which
                # preserves the donation invariant
                self.cache.truncate(slot, L0 + m)
                self.cache.lengths[slot] = L0 + m
                self._last_tok[slot] = emit[-1]
                knp[slot] = kwalk_np[m - 1, slot]
                self.stats["spec_proposed"] += len(d)
                self.stats["spec_accepted"] += m - 1
                self.stats["spec_tokens"] += m
                accept_lens.append(m)
                if tr is not None and len(seq.trace_accepts) < 512:
                    # per-request acceptance history, surfaced as the
                    # decode span's args at retirement (bounded so a
                    # very long decode cannot grow an unbounded list)
                    seq.trace_accepts.append(m)
                emitted_total += m
                for t in emit:
                    seq.tokens.append(t)
                    self.stats["active_slot_steps"] += 1
                    self.stats["tokens_generated"] += 1
                    self._emit(seq, t)
                self._maybe_finish(seq, finished)
            self._keys = jnp.asarray(knp)
        self.stats["spec_last_accept"] = accept_lens
        if tr is not None:
            if verify_rows:
                tr.instant("spec_accept",
                           args={"accept_lens": list(accept_lens),
                                 "proposed": [len(d) for _, _, d, _
                                              in verify_rows]})
            tr.complete("host-accept", th0,
                        args={"emitted": emitted_total})
        return chunk_spend + emitted_total, bool(chunk_rows)

    def _two_program_step(self, finished):
        """The PR-5 two-program interleave (``ragged_step=False`` and
        the dense engine): at most one budgeted chunk call, then one
        fused decode call. Kept intact as the A/B baseline the unified
        step is pinned byte-identical against."""
        tr = self._tr()
        tp0 = tr.now() if tr is not None else None
        co = self._co()
        if co is not None:
            # the chunk device calls below are this engine's prefill
            # plan — they attribute to the plan phase, same as the span
            co.set_phase("plan")
        plan = []
        if self._chunk and self.scheduler.num_prefilling:
            plan = self.scheduler.prefill_plan(self._chunk,
                                               self.cache.block_size,
                                               cap=self._chunk)
            if plan:
                self._run_prefill_chunks(plan, finished)
        chunk_tokens = sum(c for _, c in plan)
        n = 0
        active = [s for s in self._slots
                  if s is not None and s.status == "running"]
        if active:
            n = self.scheduler.choose_num_steps(active)
        if tr is not None:
            # emitted whether or not a decode call follows: a
            # chunks-only step must still show its plan phase (the
            # unified/spec paths emit plan unconditionally too). On
            # this two-program path the span covers the chunk device
            # calls as well — they ARE this engine's prefill plan.
            tr.complete("plan", tp0,
                        args={"rows": len(active), "chunks": len(plan),
                              "fused_steps": n})
            tl0 = tr.now()
        if active:
            if co is not None:
                co.set_phase("launch")
            if self._paged:
                # append-block on decode growth: a fused chunk of n
                # ticks writes rows [len, len+n) per slot, so the table
                # must cover them BEFORE the device call (block ids are
                # runtime data — growing them costs no retrace)
                lens = self.cache.lengths
                for slot, s in enumerate(self._slots):
                    if s is not None and s.status == "running":
                        self.cache.ensure_capacity(
                            slot, int(lens[slot]) + n)
                    elif s is not None:
                        # mid-prefill slot: its table is REAL (prefix +
                        # installed chunks), so the decode program's
                        # append must DROP, not land in the block the
                        # next chunk will write — feed it a length past
                        # the logical capacity (the program's dead-slot
                        # clamp) instead of its resume offset. Known
                        # cost: the slot's discarded attention row runs
                        # at that full length for the duration of the
                        # prefill (one array drives both the append
                        # clamp and the compute gate; skipping the
                        # compute needs a per-slot active mask in the
                        # program signature — ROADMAP, rides the
                        # decode-batch-aware chunk sizing follow-on)
                        if lens is self.cache.lengths:
                            lens = lens.copy()
                        lens[slot] = self.cache.max_blocks * \
                            self.cache.block_size
                toks, nk, nv, keys = self._decode_fn(n)(
                    self._params, self.cache.pool.k, self.cache.pool.v,
                    self.cache.tables, self._last_tok, lens, self._keys,
                    self._temps, self._topks)
            else:
                toks, nk, nv, keys = self._decode_fn(n)(
                    self._params, self.cache.k, self.cache.v,
                    self._last_tok, self.cache.lengths, self._keys,
                    self._temps, self._topks)
            self.cache.update(nk, nv)
            self._keys = keys
            toks_np = np.asarray(toks)  # [n, num_slots]
            if co is not None:
                co.set_phase("host-accept")
            if tr is not None:
                tr.complete("launch", tl0, args={"fused_steps": n})
                th0 = tr.now()
            self.stats["decode_calls"] += 1
            self.stats["decode_steps"] += n
            self.stats["slot_steps"] += n * self.num_slots
            for s in active:
                s.launches += 1         # rode this one decode call
            for i in range(n):
                for slot in range(self.num_slots):
                    seq = self._slots[slot]
                    if seq is None or seq.status != "running":
                        continue  # freed/mid-prefill slot (or finished
                        # mid-chunk); its sampled garbage never surfaces
                    t = int(toks_np[i, slot])
                    seq.tokens.append(t)
                    self.cache.lengths[slot] += 1
                    self._last_tok[slot] = t
                    self.stats["active_slot_steps"] += 1
                    self.stats["tokens_generated"] += 1
                    self._emit(seq, t)
                    self._maybe_finish(seq, finished)
            if tr is not None:
                tr.complete("host-accept", th0,
                            args={"emitted": n * len(active)})
        return chunk_tokens + n * len(active), bool(plan)

    def has_work(self) -> bool:
        return bool(self.scheduler.num_queued
                    or any(s is not None for s in self._slots))

    @property
    def num_active(self) -> int:
        """Slots currently decoding (the /metrics active-slots gauge)."""
        return self.num_slots - self.cache.num_free

    # ------------------------------------------------------------- offline
    def generate(self, requests):
        """Submit all, run to completion, return each request's
        :class:`GenerationResult` (array-like generated ids, np.int32,
        EOS included when hit, plus ``finish_reason``) in submission
        order."""
        seqs = [self.submit(r) for r in requests]
        while self.has_work():
            self.step()
        return [GenerationResult(s.output_ids(), s.finish_reason,
                                 s.request_id) for s in seqs]
