"""Deterministic fault injection for the serving stack.

The fault-tolerance layer (supervised gateway driver + engine
preemption, README "Fault tolerance & chaos testing") is only
trustworthy if every failure class it claims to survive can be
reproduced on demand, at an exact step, with an exact blast radius.
This module is that reproducer: a :class:`FaultPlan` is a deterministic
schedule of injected faults, threaded through the engine and gateway as
injectable hooks —

- the engine calls its ``fault_hook`` at the top of every ``step()``
  attempt (a step boundary, so an injected raise always leaves host
  bookkeeping consistent — exactly the contract recovery recomputes
  from);
- the gateway re-installs the same hook on every engine it builds, so a
  plan keeps firing across crash-recovery rebuilds (its step counter is
  plan-global, not per-engine-incarnation);
- simulated *hangs* never sleep: the plan advances a
  :class:`VirtualClock` past the supervisor's watchdog deadline and
  returns, so the hung-step classification is tested in microseconds.

Fault classes (``kind``):

- ``"transient"`` — raises :class:`TransientFault`; the supervisor
  retries the same engine with bounded backoff.
- ``"fatal"`` — raises :class:`FatalFault`; the supervisor rebuilds the
  engine and recovers every live request by recompute.
- ``"nan"`` — REALLY corrupts the engine's KV storage with NaNs, then
  raises :class:`FatalFault`. Recovery must recompute from host-side
  token state; a bystander stream that stays byte-identical proves the
  corrupted device state was discarded, not reused.
- ``"hung"`` — advances the plan's :class:`VirtualClock` by
  ``stall_s`` and returns; the step "completes" but overran the
  watchdog, so the supervisor classifies it hung and rebuilds.
- ``"pool"`` — raises :class:`~.kv_cache.PoolExhausted`; the ENGINE
  catches this one itself and preempts the youngest sequence
  (recompute, not crash) — the gateway never sees it.

Poison faults (:meth:`FaultPlan.poison`) fire whenever a matching
sequence holds a KV slot, every time it is readmitted — the
repeated-crash-pinned-to-one-request case the gateway's bisection
quarantine exists to isolate.

Everything here is host-side and dependency-free; production builds
simply never install a plan (``fault_hook=None`` costs one attribute
check per step).
"""
from __future__ import annotations


class FaultError(RuntimeError):
    """Base class of injected faults (so tests/benches can catch the
    whole family without matching real errors)."""


class TransientFault(FaultError):
    """Injected fault the supervisor should classify retryable."""


class FatalFault(FaultError):
    """Injected fault the supervisor should classify fatal (engine
    rebuild + recovery-by-recompute)."""


class VirtualClock:
    """Injectable monotonic clock: ``clock()`` reads, ``advance()``
    moves time forward. Drives the gateway watchdog (and the engine's
    ``step_clock``) in tests/benches so hung-step classification and
    EWMA pacing are deterministic and instant."""

    def __init__(self, start=0.0):
        self.t = float(start)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt):
        if dt < 0:
            raise ValueError(f"clock cannot go backwards (dt={dt})")
        self.t += float(dt)
        return self.t


class _Entry:
    __slots__ = ("kind", "message", "stall_s", "predicate", "remaining")

    def __init__(self, kind, message, stall_s, predicate, repeat):
        if kind not in ("transient", "fatal", "nan", "hung", "pool"):
            raise ValueError(f"unknown fault kind {kind!r}")
        self.kind = kind
        self.message = message
        self.stall_s = stall_s
        self.predicate = predicate
        self.remaining = None if repeat is None else int(repeat)


class FaultPlan:
    """Deterministic fault schedule; install as an engine's
    ``fault_hook`` (or pass as the gateway's ``fault_hook`` so rebuilt
    engines inherit it). ``clock`` is required only for ``"hung"``
    entries.

    Step indices are PLAN-global: the plan counts every hook firing —
    one per ``step()`` attempt, across engine rebuilds and
    pool-pressure retries — so a schedule replays identically no matter
    how recovery reshapes the engine underneath it. ``log`` records
    every fired fault as ``(plan_step, kind)`` for assertions.
    """

    def __init__(self, clock=None):
        self.clock = clock
        self._at = {}        # plan step -> [_Entry]
        self._poison = []    # [_Entry] with predicates
        self.step = 0        # hook firings so far (the plan-global index)
        self.log = []

    # ---------------------------------------------------------- authoring
    def at_step(self, step, kind="fatal", message=None, stall_s=None):
        """Fire one ``kind`` fault at plan step ``step`` (0-based)."""
        self._at.setdefault(int(step), []).append(
            _Entry(kind, message, stall_s, None, 1))
        return self

    def poison(self, predicate, kind="fatal", message=None, repeat=None):
        """Fire whenever ``predicate(seq)`` matches a slot-holding live
        sequence — every step it is resident, every readmission
        (``repeat=None`` = unbounded: the poisoned-request model)."""
        self._poison.append(_Entry(kind, message, None, predicate, repeat))
        return self

    # ---------------------------------------------------------- injection
    def install(self, engine):
        engine.fault_hook = self
        return self

    def _fire(self, engine, entry):
        self.log.append((self.step - 1, entry.kind))
        if entry.kind == "hung":
            if self.clock is None:
                raise ValueError(
                    "a 'hung' fault needs the plan's VirtualClock")
            self.clock.advance(entry.stall_s if entry.stall_s is not None
                               else 3600.0)
            return
        if entry.kind == "pool":
            from .kv_cache import PoolExhausted
            pool = getattr(engine.cache, "pool", None)
            # same occupancy snapshot the real raise site reports, so
            # the injected exception is representative of the condition
            # it simulates
            raise PoolExhausted(
                live_blocks=pool.num_used if pool is not None else 0,
                pinned_blocks=int((pool._ref > 0).sum())
                if pool is not None else 0,
                free_blocks=pool.num_free if pool is not None else 0,
                message=entry.message or "injected pool exhaustion")
        if entry.kind == "nan":
            self._corrupt(engine)
            raise FatalFault(entry.message
                             or "injected NaN corruption in KV storage")
        cls = TransientFault if entry.kind == "transient" else FatalFault
        raise cls(entry.message or f"injected {entry.kind} fault")

    @staticmethod
    def _corrupt(engine):
        """Overwrite the engine's KV device storage with NaNs — real
        corruption, so recovery provably recomputes instead of reusing
        the poisoned cache."""
        import jax.numpy as jnp
        store = getattr(engine.cache, "pool", engine.cache)
        if jnp.issubdtype(store.k.dtype, jnp.floating):
            store.k = jnp.full_like(store.k, jnp.nan)
            store.v = jnp.full_like(store.v, jnp.nan)
        elif getattr(store, "quantized", False):
            # int8 pool: the data arrays are integral (no NaN exists),
            # but poisoning the fp32 scale planes is just as
            # destructive — every dequantized read turns NaN — so the
            # recovery-really-recomputes proof holds on the quantized
            # engine too (README "Quantized serving")
            store.k_scale = jnp.full_like(store.k_scale, jnp.nan)
            store.v_scale = jnp.full_like(store.v_scale, jnp.nan)

    def __call__(self, engine):
        """The hook the engine invokes at the top of each step
        attempt."""
        step = self.step
        self.step += 1
        for entry in self._poison:
            if entry.remaining is not None and entry.remaining <= 0:
                continue
            if any(s is not None and not s.done and entry.predicate(s)
                   for s in engine._slots):
                if entry.remaining is not None:
                    entry.remaining -= 1
                self._fire(engine, entry)
        for entry in self._at.get(step, ()):
            if entry.remaining is not None:
                if entry.remaining <= 0:
                    continue
                entry.remaining -= 1
            self._fire(engine, entry)

    @property
    def exhausted(self) -> bool:
        """True when every scheduled (non-poison) fault has fired."""
        return self.step > max(self._at) if self._at else True
