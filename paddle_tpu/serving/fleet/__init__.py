"""Engine fleet: replicated serving with prefix-affinity routing,
failover-to-sibling, and live request migration (README "Engine
fleet"; the ROADMAP multi-tenant scale-out item, step a).

Public surface:

- :class:`EngineFleet` — N supervised engine replicas (each a PR-7
  gateway: own paged pool, prefix trie, scheduler, supervisor) behind
  one routing front door, with compiled programs shared per pool
  geometry, one ``replica``-labeled metrics registry, failover of a
  dead replica's live requests to siblings, and live migration /
  drain / rebalance built on ``engine.evict()`` + ``restore()``;
- :class:`FleetReplica` — one replica's fleet-side handle (router
  signals + the ``/debug/fleet`` row);
- :class:`Router` / :class:`RoundRobinRouter` /
  :class:`LeastLoadedRouter` / :class:`PrefixAffinityRouter` /
  :class:`ClassHeadroomRouter` / :func:`make_router` — the pluggable
  routing policies (``class-headroom`` routes by per-replica
  non-displaceable class pressure — README "Multi-tenant SLO
  serving").

The HTTP surface (``--replicas N`` / ``serve_fleet()``: routed
``/v1/completions``, ``GET /debug/fleet``, ``POST /fleet/drain`` and
``POST /fleet/rebalance``) lives in
:mod:`paddle_tpu.serving.server.httpd`.
"""
from .fleet import EngineFleet
from .replica import FleetReplica
from .router import (ClassHeadroomRouter, LeastLoadedRouter,
                     PrefixAffinityRouter, RoundRobinRouter, Router,
                     make_router)

__all__ = [
    "EngineFleet", "FleetReplica", "Router", "RoundRobinRouter",
    "LeastLoadedRouter", "PrefixAffinityRouter", "ClassHeadroomRouter",
    "make_router",
]
