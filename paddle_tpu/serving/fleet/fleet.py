"""Engine fleet: N replicated serving engines behind one front door
(README "Engine fleet"; the ROADMAP multi-tenant scale-out item,
step a).

An :class:`EngineFleet` owns N :class:`~.replica.FleetReplica`\\ s —
each a PR-7 supervised gateway with its own paged pool, prefix trie,
scheduler and driver thread, shared-nothing at runtime — and routes
every submission through a pluggable policy (``fleet/router.py``:
round-robin, least-loaded, prefix-affinity-within-a-load-band). Three
properties carry over from the single-engine stack unchanged, by
construction:

- **Compile-once across the fleet**: replicas with the SAME pool
  geometry share one jit-cache dict (so N replicas trace each program
  once, total), replicas with DIFFERENT geometry get isolated dicts
  (two geometries pooling shape-keyed traces under one fn would break
  each engine's ``decode_compilations() == 1`` pin) — the same
  shared-jit factory discipline ``serve()`` uses for crash-recovery
  rebuilds, extended one axis.
- **Monotonic fleet metrics**: every replica registers its series
  through a ``registry.labeled(replica=i)`` view of ONE shared
  registry, and each gateway keeps its own carried
  ``(base, engine)`` counter snapshot — so a scrape covers the whole
  fleet, every series carries a ``replica`` label, and any single
  replica rebuilding re-bases only its own series.
- **Zero requests lost on replica death**: a replica whose supervisor
  exhausts its restart budget hands its live requests — snapshotted
  exactly like a rebuild's recovery, PRNG walks included — to the
  fleet's ``on_fatal`` hook, which re-admits each on a sibling via
  ``engine.restore()`` recompute. Streams continue byte-identically
  (restore is the same primitive intra-engine recovery already proves);
  consumers see a pause, never an error.

Live migration rides the same primitive in the healthy direction:
:meth:`EngineFleet.migrate` evicts a running sequence from its replica
between steps (chain donated to the source trie, PRNG snapshotted —
``engine.evict``) and re-admits it on a sibling, which is what
:meth:`drain_replica` (empty a replica for maintenance) and
:meth:`rebalance` (shed load from the hottest replica) are built from.

Routing is deterministic: policies read only replica load/trie state,
never a clock — a fixed submission order over fixed replica state
routes identically on every replay (the fleet chaos matrix pins this
under a :class:`~paddle_tpu.serving.faults.VirtualClock`).
"""
from __future__ import annotations

import collections
import threading

import numpy as np

from ...profiler.metrics import MetricsRegistry
from ...profiler.tracing import SpanTracer
from ..prefix_cache import HostTier
from ..server.gateway import GatewayClosedError, QueueFullError, \
    ServingGateway
from .replica import FleetReplica
from .router import make_router

#: the fleet's own trace lane in the merged /debug/trace document
TID_FLEET = 1


def _per_replica(value, n, name):
    """Broadcast a scalar engine knob to ``n`` replicas, or validate a
    per-replica sequence of length ``n`` (the ``--num-slots 8,4``
    CLI form)."""
    if isinstance(value, (list, tuple)):
        if len(value) != n:
            raise ValueError(
                f"{name} has {len(value)} per-replica values for "
                f"{n} replicas")
        return list(value)
    return [value] * n


class EngineFleet:
    """N supervised engine replicas + a routing front door.

    ``model`` is shared by every replica (weights live once; each
    replica's KV pool and trie are its own). ``num_slots``,
    ``max_seq_len``, ``prefill_chunk``, ``max_queue`` and
    ``prefix_blocks`` accept either a scalar (same on every replica) or
    a per-replica sequence — mixed pool geometries get isolated
    jit-cache dicts automatically. ``router`` is a policy name
    (``round-robin`` | ``least-loaded`` | ``affinity``) or a
    :class:`~.router.Router` instance. ``fault_hooks`` threads one
    fault plan per replica (the chaos harness; ``None`` entries leave a
    replica un-instrumented). ``start=False`` leaves every driver
    stopped so tests/benches can submit a whole workload first —
    routing decisions then depend only on submission order, making
    chaos replays deterministic.
    """

    def __init__(self, model, replicas=2, router="affinity",
                 num_slots=8, max_seq_len=None, decode_chunk=1,
                 max_queue=64, prefix_cache=True, prefix_blocks=None,
                 prefix_block_size=32, paged_attn=True,
                 prefill_chunk=512, ragged_step=True, headroom_mult=2.0,
                 spec_decode=False, spec_k=4, drafter=None,
                 decode_ticks=1, kv_dtype=None, quantize_weights=False,
                 quantize_activations=False,
                 tp=1, collective_dtype="fp", host_tier_bytes=0,
                 priority_classes=None,
                 fused_tick=False, collective_overlap=False,
                 registry=None, clock=None, watchdog_deadline_s=None,
                 max_transient_retries=3, retry_backoff_s=0.02,
                 max_restarts=8, fault_hooks=None, trace=False,
                 trace_buffer=65536, cost=True, idle_wait_s=0.02,
                 start=True):
        n = int(replicas)
        if n < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.model = model
        self.router = make_router(router)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._lock = threading.Lock()
        self._clock = clock
        #: the fleet's own trace lane (router decisions, failovers,
        #: migrations); per-replica engine/request lanes live on each
        #: gateway's tracer and merge into one document in trace_doc()
        self.tracer = SpanTracer(capacity=trace_buffer, clock=clock)
        if trace:
            self.tracer.enable()
        #: routing decision log — (stream_id, replica_index), the chaos
        #: matrix's determinism pin. Bounded: a long-running fleet
        #: appends one entry per admission, and an unbounded list would
        #: be a slow leak on the production submit path (the tracer
        #: ring next to it is bounded for the same reason).
        self.decisions = collections.deque(maxlen=4096)
        slots = _per_replica(num_slots, n, "num_slots")
        smax = _per_replica(max_seq_len, n, "max_seq_len")
        chunk = _per_replica(prefill_chunk, n, "prefill_chunk")
        queues = _per_replica(max_queue, n, "max_queue")
        pblocks = _per_replica(prefix_blocks, n, "prefix_blocks")
        # host_tier_bytes is POLICY, not geometry: it changes no traced
        # shape and adds no jit key, so it never joins the geom tuple
        # below — replicas with different tier budgets still share one
        # jit-cache dict. With any replica tiered, submit() runs the
        # fleet cache plane: spilled chains move host-to-host from the
        # replica that evicted them to the replica about to need them.
        tiers = _per_replica(host_tier_bytes, n, "host_tier_bytes")
        # the class table is POLICY too (the host_tier_bytes rule): one
        # parsed table shared fleet-wide — admission/preemption policy
        # must agree across replicas or a migrated request would change
        # tier — and it never joins the geom tuple
        from ..policy import ClassTable
        self.classes = ClassTable.coerce(priority_classes)
        hooks = _per_replica(None, n, "fault_hooks") \
            if fault_hooks is None else list(fault_hooks)
        if len(hooks) != n:
            raise ValueError(
                f"fault_hooks has {len(hooks)} entries for {n} replicas")
        # one jit-cache dict PER POOL GEOMETRY, model-resident so a
        # second fleet over the same model stays warm: same-geometry
        # replicas (and their crash-recovery rebuilds) share every
        # compiled program; a differing geometry isolates — its
        # shape-keyed traces must not pool under another engine's fn
        # or both engines' decode_compilations() pins break.
        jits = model.__dict__.setdefault("_serving_jit_fleet", {})
        self.replicas = []
        for i in range(n):
            # EVERY knob that reaches a traced program's arg shapes
            # belongs here — the pool arrays included: num_blocks =
            # live + prefix budget sizes pool_k/pool_v, so
            # prefix_blocks (and the trie toggle that defaults it) are
            # geometry, not just policy
            # kv_dtype and quantize_weights are geometry too: an int8
            # pool is a different arg DTYPE and quantized params a
            # different pytree — per-geometry jit caches must not
            # collide or both engines' compile pins break (the
            # pool-geometry-keyed-cache rule)
            # tp and collective_dtype are geometry the same way: a
            # sharded program is a different trace (different mesh,
            # different collectives), so replicas with different TP
            # degrees get isolated jit-cache dicts — the same
            # discipline as the kv8/w8 tags
            # fused_tick and collective_overlap are geometry the same
            # way: the fused mega-kernel and the ppermute-chain overlap
            # schedule are different traces of the same step, so
            # replicas differing in either get isolated jit-cache dicts
            geom = (slots[i], smax[i], chunk[i], bool(paged_attn),
                    bool(ragged_step), bool(spec_decode), int(spec_k),
                    int(decode_chunk), int(prefix_block_size),
                    bool(prefix_cache), pblocks[i], int(decode_ticks),
                    kv_dtype, bool(quantize_weights),
                    bool(quantize_activations),
                    int(tp), str(collective_dtype),
                    bool(fused_tick), bool(collective_overlap))
            jit = jits.setdefault(geom, {})

            def factory(i=i, jit=jit):
                from ..engine import ContinuousBatchingEngine
                return ContinuousBatchingEngine(
                    model, num_slots=slots[i], max_seq_len=smax[i],
                    decode_chunk=decode_chunk,
                    prefix_cache=prefix_cache,
                    prefix_blocks=pblocks[i],
                    prefix_block_size=prefix_block_size,
                    paged_attn=paged_attn, prefill_chunk=chunk[i],
                    ragged_step=ragged_step,
                    headroom_mult=headroom_mult,
                    spec_decode=spec_decode, spec_k=spec_k,
                    drafter=drafter, decode_ticks=decode_ticks,
                    kv_dtype=kv_dtype,
                    quantize_weights=quantize_weights,
                    quantize_activations=quantize_activations,
                    tp=tp, collective_dtype=collective_dtype,
                    host_tier_bytes=tiers[i],
                    priority_classes=self.classes,
                    fused_tick=fused_tick,
                    collective_overlap=collective_overlap,
                    jit_cache=jit)

            gw = ServingGateway(
                factory(), max_queue=queues[i], idle_wait_s=idle_wait_s,
                registry=self.registry.labeled(replica=str(i)),
                start=False, engine_factory=factory,
                watchdog_deadline_s=watchdog_deadline_s,
                max_transient_retries=max_transient_retries,
                retry_backoff_s=retry_backoff_s,
                max_restarts=max_restarts, clock=clock,
                fault_hook=hooks[i], trace=trace,
                trace_buffer=trace_buffer, cost=cost,
                on_fatal=self._on_replica_fatal,
                stream_id_prefix=f"cmpl-r{i}")
            self.replicas.append(FleetReplica(i, gw))
        self._init_metrics()
        if start:
            self.start()

    # ------------------------------------------------------------- helpers
    def _tr(self):
        """The fleet tracer when recording, else None — the engine's
        ``_tr()`` one-attribute guard discipline, fleet lane."""
        t = self.tracer
        return t if t.enabled else None

    def _routable(self, exclude=None):
        return [r for r in self.replicas
                if r.routable and r is not exclude]

    def _alive(self, exclude=None):
        return [r for r in self.replicas
                if r.alive and r is not exclude]

    def _by_gateway(self, gateway):
        for r in self.replicas:
            if r.gateway is gateway:
                return r
        return None

    # ------------------------------------------------------------- metrics
    def _init_metrics(self):
        r = self.registry
        r.gauge("serving_fleet_replicas",
                "Engine replicas behind the fleet front door.").set(
            len(self.replicas))
        r.gauge("serving_fleet_alive_replicas",
                "Replicas currently routable (alive and accepting)."
                ).set_fn(lambda: len(self._routable()))
        self._m_routed = r.counter(
            "serving_fleet_router_decisions_total",
            "Admissions routed, by policy and chosen replica.")
        self._m_failovers = r.counter(
            "serving_fleet_failovers_total",
            "Replica deaths whose live requests were re-admitted on "
            "siblings (failover-to-sibling events).")
        self._m_migrated = r.counter(
            "serving_fleet_migrated_requests_total",
            "Requests moved between replicas, by cause "
            "(cause = failover|migration).")
        self._m_tier_transfers = r.counter(
            "serving_fleet_tier_transfers_total",
            "Spilled prefix blocks moved host-to-host between replica "
            "tiers by the fleet cache plane (a routed request about to "
            "miss on its replica pulled the chain from the sibling "
            "that spilled it).")
        self._m_tier_transfer_bytes = r.counter(
            "serving_fleet_tier_transfer_bytes_total",
            "Host bytes the fleet cache plane moved between replica "
            "tiers.")
        # plain carried ints for /fleet/cacheplane (scrape-style reads
        # under the submit lock, like the decisions log)
        self._tier_transfers = 0
        self._tier_transfer_bytes = 0

    # ---------------------------------------------------------- front door
    def submit(self, request):
        """Route and enqueue one request; returns its
        :class:`~..server.gateway.TokenStream`. Walks the router's
        preference order so a full waiting room sheds sideways to the
        next-best replica; :class:`QueueFullError` means EVERY routable
        replica is full (the HTTP 429), :class:`GatewayClosedError`
        that none is routable (503)."""
        reps = self._routable()
        # heterogeneous max_seq_len: prefer replicas that can hold the
        # request to completion; when NONE can, keep the full order so
        # the first replica's validate() raises the true 400 (a request
        # too long for every replica must not surface as a 503)
        fitting = [r for r in reps if r.can_hold(request)]
        order = self.router.rank(request, fitting or reps)
        if not order:
            raise GatewayClosedError("no routable replicas")
        last = None
        for k, rep in enumerate(order):
            try:
                self._tier_warm(rep, request)
                stream = rep.gateway.submit(request)
            except (QueueFullError, GatewayClosedError) as e:
                last = e
                continue
            with self._lock:
                self.decisions.append((stream.id, rep.index))
            self._m_routed.inc(policy=self.router.name,
                               replica=str(rep.index))
            tr = self._tr()
            if tr is not None:
                tr.instant(
                    "route", tid=TID_FLEET,
                    args={"stream": stream.id, "replica": rep.index,
                          "policy": self.router.name, "rank": k,
                          "load": rep.load()})
            return stream
        raise last

    # -------------------------------------------------- fleet cache plane
    def _tier_warm(self, rep, request):
        """The fleet cache plane (README "Tiered KV prefix cache"):
        before a routed request submits to ``rep``, pull any spilled
        prefix chain it will need from a sibling replica's host tier
        into ``rep``'s — host-to-host, by reference (tier buffers are
        immutable by convention), addressed by content digests
        (:meth:`HostTier.chain_digests`), so a miss on replica A that
        hits replica B's tier becomes a local tier hit at admission:
        prefix affinity upgraded from a routing heuristic to a
        distributed prefix cache. Transfers extend the target's
        coverage contiguously from its resident+tier frontier and stop
        at the first block no sibling holds. Returns blocks moved;
        never raises (racing a driver-side trie mutation degrades to a
        cold route, exactly like the affinity probe)."""
        pc = getattr(rep.gateway.engine, "prefix_cache", None)
        if pc is None or pc.tier is None \
                or getattr(request, "prompt", None) is None:
            return 0
        try:
            prompt = np.asarray(request.prompt).reshape(-1)
            keys = pc._blocks_of(prompt, len(prompt) - 1)
            if not keys:
                return 0
            digests = HostTier.chain_digests(keys)
            covered = len(pc.lookup(prompt, record=False))
        except Exception:
            return 0                # malformed prompt / racing rebuild
        moved = moved_bytes = 0
        path = tuple(keys[:covered])
        for depth in range(covered, len(keys)):
            path = path + (keys[depth],)
            if pc.tier.has(path):
                continue            # already local
            entry = None
            for donor in self.replicas:
                if donor is rep or not donor.alive:
                    continue
                dpc = getattr(donor.gateway.engine, "prefix_cache", None)
                if dpc is None or dpc.tier is None:
                    continue
                entry = dpc.tier.export_digest(digests[depth])
                if entry is not None:
                    break
            if entry is None:
                break               # chain must stay contiguous
            _, bufs, nbytes = entry
            # shared=True: these are the donor tier's buffers by
            # reference (the pointer-move transfer) — neither tier may
            # recycle them into its staging pool
            pc.tier.put(path, bufs, shared=True)
            pc.stats["tier_transfers"] += 1
            moved += 1
            moved_bytes += nbytes
            co = rep.gateway.cost
            if co is not None:
                co.record_tier("peer", 1, nbytes)
        if moved:
            with self._lock:
                self._tier_transfers += moved
                self._tier_transfer_bytes += moved_bytes
            self._m_tier_transfers.inc(moved)
            self._m_tier_transfer_bytes.inc(moved_bytes)
            tr = self._tr()
            if tr is not None:
                tr.instant(
                    "tier_transfer", tid=TID_FLEET,
                    args={"to": rep.index, "blocks": moved,
                          "bytes": moved_bytes})
        return moved

    def cache_plane_doc(self) -> dict:
        """The ``GET /fleet/cacheplane`` body: per-replica tier
        occupancy + published digest counts, and the fleet's transfer
        totals — the distributed-prefix-cache debug surface."""
        rows = []
        for r in self.replicas:
            pc = getattr(r.gateway.engine, "prefix_cache", None)
            tier = pc.tier if pc is not None else None
            row = {"replica": r.index, "enabled": tier is not None}
            if tier is not None:
                row.update(
                    tier_blocks=tier.num_blocks,
                    tier_bytes=tier.bytes_used,
                    capacity_bytes=pc.host_tier_bytes,
                    digests=len(tier.digest_table()),
                    spilled_blocks=int(
                        r.gateway._pc_stat("spilled_blocks")),
                    tier_hits=int(r.gateway._pc_stat("tier_hits")),
                    readmitted_blocks=int(
                        r.gateway._pc_stat("readmitted_blocks")),
                    tier_transfers_in=int(
                        r.gateway._pc_stat("tier_transfers")))
            rows.append(row)
        with self._lock:
            transfers = self._tier_transfers
            transfer_bytes = self._tier_transfer_bytes
        return {"replicas": rows,
                "transfers_total": transfers,
                "transfer_bytes_total": transfer_bytes}

    # ------------------------------------------------------------ failover
    def _on_replica_fatal(self, gateway, pairs):
        """Failover-to-sibling (the gateway's ``on_fatal`` hook, called
        on the dying replica's driver thread): mark the replica dead,
        then re-admit each surviving (stream, sequence) pair on the
        least-loaded alive sibling — ``adopt`` + ``restore()``
        recompute, streams byte-identical. Returns the streams actually
        adopted; any the siblings refuse fall back to the gateway's
        stranding path (an error event, never a hang)."""
        rep = self._by_gateway(gateway)
        if rep is None:
            return False
        rep.dead = True
        adopted = []
        targets = self._alive()
        if not targets:
            return False            # last replica down: strand as before
        tr = self._tr()
        if tr is not None:
            tr.instant("replica_dead", tid=TID_FLEET,
                       args={"replica": rep.index,
                             "survivors": len(pairs)})
        for stream, seq in pairs:
            placed = False
            for tgt in sorted(
                    (r for r in self._alive()
                     if r.can_hold(stream.request)),
                    key=lambda r: (r.load(), r.index)):
                try:
                    tgt.gateway.adopt(stream, seq)
                except GatewayClosedError:
                    continue
                adopted.append(stream)
                self._m_migrated.inc(cause="failover")
                if tr is not None:
                    tr.instant(
                        "failover", tid=TID_FLEET,
                        args={"stream": stream.id, "from": rep.index,
                              "to": tgt.index,
                              "tokens": (len(seq.tokens)
                                         if seq is not None else 0)})
                placed = True
                break
            if not placed and not self._alive():
                break               # no target left at all: strand rest
            # else: THIS request is unplaceable (every alive sibling
            # refused or is too small for it) — it strands with an
            # error, but later survivors still get their chance
        if adopted:
            self._m_failovers.inc()
        return adopted

    # ----------------------------------------------------- live migration
    def migrate(self, stream, target=None):
        """Move one in-flight request to another replica while both are
        healthy: the source driver evicts its sequence between steps
        (chain donated to the source trie, PRNG walk snapshotted) and
        the pair is adopted by ``target`` (a replica or index; default:
        the least-loaded other replica, chosen at handoff time). The
        stream continues byte-identically on the target — consumers
        see a pause, never a replayed or lost token. Asynchronous: the
        handoff happens on the source driver's next loop pass."""
        if isinstance(target, int):
            target = self.replicas[target]
        source = self._by_gateway(stream.gateway)

        def handoff(st, seq):
            tgt = target
            if tgt is not None and not tgt.can_hold(st.request):
                tgt = None      # explicit target too small: re-select
            if tgt is None or not tgt.alive:
                cands = sorted(
                    (r for r in self._routable(exclude=source)
                     if r.can_hold(st.request)),
                    key=lambda r: (r.load(), r.index))
                if not cands:
                    raise GatewayClosedError(
                        "no routable sibling can hold this request")
                tgt = cands[0]
            tgt.gateway.adopt(st, seq)
            self._m_migrated.inc(cause="migration")
            tr = self._tr()
            if tr is not None:
                tr.instant(
                    "migrate", tid=TID_FLEET,
                    args={"stream": st.id,
                          "from": source.index if source else None,
                          "to": tgt.index,
                          "tokens": (len(seq.tokens)
                                     if seq is not None else 0)})

        stream.gateway.request_migration(stream, handoff)

    def _live_streams(self, rep):
        """Snapshot a replica's in-flight streams (driver mutates the
        dict concurrently; retry the rare mid-resize read)."""
        gw = rep.gateway
        for _ in range(8):
            try:
                return list(gw._live.values()) + list(gw._intake)
            except RuntimeError:
                continue
        return []

    def drain_replica(self, index) -> int:
        """Take a replica out of rotation (maintenance): new work
        routes around it and every in-flight request migrates to a
        sibling by eviction + ``restore()`` recompute. Returns the
        number of migrations requested; the replica's driver performs
        them on its next loop passes. The replica stays alive and can
        be returned to rotation with :meth:`undrain_replica`."""
        rep = self.replicas[int(index)]
        rep.accepting = False
        if not self._routable(exclude=rep):
            return 0                # nowhere to move work; just cordon
        streams = [st for st in self._live_streams(rep)
                   if st.finish_reason is None]
        for st in streams:
            self.migrate(st)
        return len(streams)

    def undrain_replica(self, index):
        """Return a drained (alive) replica to rotation."""
        rep = self.replicas[int(index)]
        if rep.dead:
            raise ValueError(f"replica {rep.index} is dead")
        rep.accepting = True

    def rebalance(self, max_moves=8) -> int:
        """One load-shedding pass: migrate up to ``max_moves`` of the
        MOST-loaded replica's youngest in-flight requests (least sunk
        recompute work — the preemption policy's victim order) to the
        LEAST-loaded replica, until their in-flight counts would be
        within one of each other. Returns migrations requested."""
        reps = self._routable()
        if len(reps) < 2:
            return 0
        src = max(reps, key=lambda r: (r.load(), -r.index))
        dst = min(reps, key=lambda r: (r.load(), r.index))
        if src is dst:
            return 0
        src_live = [st for st in self._live_streams(src)
                    if st.finish_reason is None and st.seq is not None]
        dst_live = sum(1 for st in self._live_streams(dst)
                       if st.finish_reason is None)
        gap = len(src_live) - dst_live
        if gap <= 1:
            return 0
        src_live.sort(key=lambda st: -st.seq.request_id)  # youngest first
        moves = min(int(max_moves), gap // 2)
        for st in src_live[:moves]:
            self.migrate(st, target=dst)
        return moves

    # ------------------------------------------------------ health / debug
    @property
    def health_state(self) -> str:
        """Fleet-level ``/healthz`` status: ``ok`` when every routable
        replica is ok; ``degraded`` when any replica is degraded, dead
        or draining (capacity is reduced but the fleet serves);
        ``recovering`` while any replica recovers; ``draining`` when
        nothing is routable."""
        routable = self._routable()
        if not routable:
            return "draining"
        states = {r.gateway.health_state for r in routable}
        if "recovering" in states:
            return "recovering"
        if "degraded" in states or len(routable) < len(self.replicas):
            return "degraded"
        return "ok"

    def fleet_table(self) -> list:
        """The ``GET /debug/fleet`` body: one row per replica — state,
        live/free KV blocks, queue depth, dispatches per token, last
        rebuild — computed by the same reads as the per-replica
        ``/metrics``/``/debug/profile`` surfaces."""
        return [r.row() for r in self.replicas]

    def trace_doc(self) -> dict:
        """Merged Chrome-trace snapshot: the fleet lane (router
        decisions, failovers, migrations) as pid 0 and each replica's
        full timeline (engine phases, request lanes, counter tracks)
        as pid ``replica + 1`` — one Perfetto document for the whole
        fleet."""
        events = [{**ev, "pid": 0} for ev in self.tracer.events()]
        dropped = self.tracer.dropped
        for rep in self.replicas:
            t = rep.gateway.tracer
            events.extend({**ev, "pid": rep.index + 1}
                          for ev in t.events())
            dropped += t.dropped
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"clock": "injectable-monotonic",
                              "dropped_events": dropped,
                              "pid_map": {"0": "fleet", **{
                                  str(r.index + 1): f"replica{r.index}"
                                  for r in self.replicas}}}}

    def profile_doc(self) -> dict:
        """Fleet cost attribution: each replica's ``/debug/profile``
        document plus fleet totals (dispatches, decoded tokens and the
        aggregate dispatches-per-decoded-token rate)."""
        per = {}
        dispatches = tokens = 0
        for rep in self.replicas:
            gw = rep.gateway
            if gw.cost is None:
                continue
            per[str(rep.index)] = gw.profile_doc()
            dispatches += gw.cost.totals["dispatches"]
            tokens += gw._stat("tokens_generated")
        return {"replicas": per, "totals": {
            "dispatches": dispatches, "decoded_tokens": tokens,
            "dispatches_per_decoded_token": round(
                dispatches / max(tokens, 1), 6)}}

    # ----------------------------------------------------------- lifecycle
    def start(self):
        """Start every replica's driver thread (idempotent)."""
        for rep in self.replicas:
            rep.gateway.start()
        return self

    def shutdown(self, drain=True, timeout=None):
        """Close every replica's front door and stop its driver
        (``drain=True`` lets in-flight work finish). Returns True when
        every driver exited."""
        for rep in self.replicas:
            with rep.gateway._lock:
                rep.gateway._closed = True
        ok = True
        for rep in self.replicas:
            ok = rep.gateway.shutdown(drain=drain, timeout=timeout) and ok
        return ok
