"""One replica of the engine fleet: a supervised gateway plus the
fleet-side bookkeeping the router and the ``/debug/fleet`` table read.

A replica IS a PR-7 :class:`~paddle_tpu.serving.server.ServingGateway`
— its own paged pool, prefix trie, scheduler, supervisor, tracer and
cost observatory — shared-nothing except for the compiled programs
(the fleet hands same-geometry replicas one jit-cache dict) and the
fleet's shared metrics registry (each replica registers through a
``registry.labeled(replica=...)`` view, so one ``/metrics`` scrape
covers the fleet with every series labeled by replica).

The load/affinity accessors here are scrape-style reads of host
bookkeeping the replica's driver thread writes (ints and short lists
under the GIL — the same discipline as the gateway's scrape-time
gauges): the router calls them from submit threads while the driver
steps.
"""
from __future__ import annotations

import numpy as np


class FleetReplica:
    """Fleet-side handle for one supervised engine replica."""

    def __init__(self, index, gateway):
        self.index = int(index)
        self.gateway = gateway
        #: router admission flag — False while draining (live work
        #: migrates out, new work routes around it)
        self.accepting = True
        #: set by the fleet's failover hook when this replica's driver
        #: died past its restart budget (its live requests were
        #: re-admitted on siblings)
        self.dead = False

    # ------------------------------------------------------------ signals
    @property
    def alive(self) -> bool:
        return not self.dead and not self.gateway.closed

    @property
    def routable(self) -> bool:
        return self.alive and self.accepting

    @property
    def state(self) -> str:
        """``dead`` | ``draining`` | the gateway's health state
        (``ok``/``degraded``/``recovering``) — the ``/debug/fleet``
        state column."""
        if self.dead:
            return "dead"
        if not self.accepting or self.gateway.closed:
            return "draining"
        return self.gateway.health_state

    def live_kv_blocks(self) -> int:
        """Distinct pool blocks live slots reference (paged), or the
        dense equivalent (active slots × per-slot block budget is
        meaningless there, so active slots stand in) — the KV half of
        the load signal."""
        eng = self.gateway.engine
        if getattr(eng, "_paged", False):
            return int(eng.cache.occupancy()["live"])
        return int(eng.num_active)

    def free_kv_blocks(self) -> int:
        eng = self.gateway.engine
        if getattr(eng, "_paged", False):
            return int(eng.cache.pool.num_free)
        return int(eng.cache.num_free)

    def load(self) -> int:
        """The router's load scalar: live KV blocks + waiting-room
        depth. Both are monotone in how long a new admission would
        wait, and both are already maintained host-side — reading them
        costs two ints."""
        return self.live_kv_blocks() + int(self.gateway.queue_depth)

    def can_hold(self, request) -> bool:
        """Whether this replica's engine can hold ``request`` to
        completion — the ``engine.validate`` KV-length bound, checked
        fleet-side so routing, failover and migration never place a
        request on a replica whose ``max_seq_len`` is too small for it
        (per-replica geometries are a feature; an oversized adoption
        would crash the target's driver mid-recompute and cascade)."""
        try:
            need = (int(np.asarray(request.prompt).reshape(-1).shape[0])
                    + int(request.max_new_tokens))
        except Exception:
            return True         # malformed: let validate() raise the 400
        return need <= self.gateway.engine.max_seq_len

    def prefix_match_tokens(self, prompt) -> int:
        """Longest cached-prefix coverage (tokens) this replica's trie
        holds for ``prompt`` — a side-effect-free probe
        (``lookup(record=False)``: no stats, no LRU touches), so
        routing never perturbs the hit/miss accounting the bench
        banks."""
        pc = self.gateway.engine.prefix_cache
        if pc is None or prompt is None:
            return 0
        try:
            return pc.block_size * len(pc.lookup(prompt, record=False))
        except Exception:
            return 0        # racing a driver-side trie mutation: cold

    def tier_match_tokens(self, prompt) -> int:
        """Tokens of ``prompt`` this replica's OWN host tier could
        readmit beyond the trie frontier: the contiguous run of
        spilled blocks continuing the trie match (README "Tiered KV
        prefix cache" — the PR-16 capacity-aware placement follow-on).
        The affinity router adds it to :meth:`prefix_match_tokens`, so
        a chain that spilled under pool pressure still attracts its
        prefix family to the replica that HOLDS it (a host-RAM readmit)
        instead of a sibling that would pull it host-to-host over the
        cache plane. Side-effect-free like the trie probe; 0 on
        tierless replicas, so every existing routing order is
        unchanged."""
        pc = self.gateway.engine.prefix_cache
        if pc is None or prompt is None or pc.tier is None:
            return 0
        try:
            # len-1 bound like every admission-side probe: a lookup
            # never covers the final prompt token (the suffix prefill
            # needs one token to sample from)
            keys = pc._blocks_of(prompt, len(prompt) - 1)
            covered = len(pc.lookup(prompt, record=False))
            n = 0
            for depth in range(covered, len(keys)):
                if not pc.tier.has(keys[:depth + 1]):
                    break
                n += 1
            return pc.block_size * n
        except Exception:
            return 0        # racing a driver-side tier mutation: cold

    def class_counts(self) -> dict:
        """Per-class occupancy ``{class_name: count}`` over this
        replica's engine-held work — running/prefilling slots plus the
        scheduler queue (the gateway intake is not yet classed). A
        scrape-style read like :meth:`load`."""
        eng = self.gateway.engine
        counts = {}
        try:
            seqs = [s for s in eng._slots if s is not None and not s.done]
            seqs += [s for s in eng.scheduler.queue
                     if getattr(s, "done", False) is False]
            for seq in seqs:
                pclass = getattr(seq, "pclass", None)
                name = pclass.name if pclass is not None \
                    else eng.classes.default
                counts[name] = counts.get(name, 0) + 1
        except Exception:
            return counts   # racing a driver-side mutation: partial
        return counts

    def class_pressure(self, request) -> int:
        """The load on this replica that could NOT be displaced for
        ``request``: engine-held work of class rank >= the request's
        resolved rank (equals never displace equals), plus the unclassed
        gateway intake. The class-headroom router's primary signal — a
        latency request never lands on a replica saturated with
        equal-or-higher-rank work while a sibling holds preemptible
        batch load."""
        eng = self.gateway.engine
        try:
            rank = eng.classes.resolve(
                getattr(request, "priority_class", None)).rank
        except ValueError:
            rank = 0        # unknown class 400s at submit; rank moot
        pressure = int(self.gateway.queue_depth) \
            - int(eng.scheduler.num_queued)
        pressure = max(pressure, 0)     # intake-only share of the queue
        try:
            seqs = [s for s in eng._slots if s is not None and not s.done]
            seqs += list(eng.scheduler.queue)
            for seq in seqs:
                pclass = getattr(seq, "pclass", None)
                if pclass is None or pclass.rank >= rank:
                    pressure += 1
        except Exception:
            pass            # racing a driver-side mutation: partial
        return pressure

    # --------------------------------------------------------- debug table
    def row(self) -> dict:
        """One ``/debug/fleet`` row — state + the router's live signals
        + the cost-attribution columns, computed exactly as the
        ``/metrics``/``/debug/profile`` surfaces compute them (same
        carried-counter reads, same dispatches-per-decoded-token
        formula), so the fleet table can never disagree with the
        per-replica scrape."""
        gw = self.gateway
        eng = gw.engine
        row = {
            "replica": self.index,
            "state": self.state,
            "accepting": bool(self.accepting),
            "num_slots": int(eng.num_slots),
            "active_slots": int(eng.num_active),
            "queue_depth": int(gw.queue_depth),
            "live_kv_blocks": self.live_kv_blocks(),
            "free_kv_blocks": self.free_kv_blocks(),
            "load": self.load(),
            "tokens_generated": int(gw._stat("tokens_generated")),
            "restarts": int(gw.restarts),
            "last_rebuild_age_s": (
                None if gw.last_restart_at is None
                else round(gw._clock() - gw.last_restart_at, 3)),
        }
        if gw.cost is not None:
            row["dispatches"] = int(gw.cost.totals["dispatches"])
            row["dispatches_per_decoded_token"] = round(
                gw.cost.totals["dispatches"]
                / max(gw._stat("tokens_generated"), 1), 4)
        if eng.prefix_cache is not None:
            hits = gw._pc_stat("hits")
            misses = gw._pc_stat("misses")
            row["prefix_hits"] = int(hits)
            row["prefix_hit_rate"] = round(
                hits / max(hits + misses, 1), 4)
            if eng.prefix_cache.tier is not None:
                # the cache-plane columns (README "Tiered KV prefix
                # cache"), same carried reads as /fleet/cacheplane
                row["tier_blocks"] = eng.prefix_cache.tier.num_blocks
                row["tier_hits"] = int(gw._pc_stat("tier_hits"))
                row["tier_transfers_in"] = int(
                    gw._pc_stat("tier_transfers"))
        if eng.classes.active:
            # per-class occupancy + the policy counters (README
            # "Multi-tenant SLO serving") — present only with a
            # multi-class table, so a policy-off fleet table is
            # unchanged
            row["classes"] = self.class_counts()
            row["policy_preemptions"] = int(
                gw._stat("policy_preemptions"))
        return row

    def __repr__(self):
        return (f"FleetReplica(index={self.index}, state={self.state}, "
                f"load={self.load()})")
