"""Request routing policy for the engine fleet (README "Engine
fleet").

One gateway fronts N shared-nothing engine replicas; the router decides
which replica admits each request. Two signals matter at fleet scale
(the Gemma-on-TPU serving study and the AlpaServe-style placement
results, PAPERS.md: routing/replication policy — not the kernel —
dominates fleet goodput):

- **Load**: a replica's live KV blocks plus its waiting-room depth —
  the same occupancy the engine's own admission control and /metrics
  gauges read. Routing to the least-loaded replica bounds queue wait.
- **Prefix affinity**: each replica owns its own prefix trie, so a
  request routed away from the replica that cached its prompt prefix
  re-prefills from scratch. Affinity routing sends a request to the
  replica with the LONGEST cached prefix — but only within a LOAD BAND
  of the least-loaded replica, so cache hits survive fan-out without
  letting one hot prefix melt a single replica.

Policies are pure host-side functions of the replicas' current
signals: no clock reads, no randomness — under a
:class:`~paddle_tpu.serving.faults.VirtualClock` (or any fixed load
state) a submission order routes identically on every replay, which is
what makes the fleet chaos matrix deterministic. Ties break toward the
LOWEST replica index, always.

``rank()`` returns the full preference order (best first): the fleet
retries down the list when a replica's waiting room is full, so a
burst sheds sideways before it 429s.
"""
from __future__ import annotations

import itertools


class Router:
    """Policy base: rank replicas for one request, best first."""

    name = "base"

    def rank(self, request, replicas):
        raise NotImplementedError

    def route(self, request, replicas):
        """The chosen replica (rank head), or None with nothing
        routable."""
        order = self.rank(request, replicas)
        return order[0] if order else None


class RoundRobinRouter(Router):
    """Rotate admissions across replicas in index order — the
    load-blind, affinity-blind baseline the fleet bench compares
    against."""

    name = "round-robin"

    def __init__(self):
        self._turn = itertools.count()

    def rank(self, request, replicas):
        reps = sorted(replicas, key=lambda r: r.index)
        if not reps:
            return []
        k = next(self._turn) % len(reps)
        return reps[k:] + reps[:k]


class LeastLoadedRouter(Router):
    """Route to the replica with the lowest load — live KV blocks +
    waiting-room depth (:meth:`~.replica.FleetReplica.load`). Ties
    break to the lowest replica index (deterministic, pinned by
    tests)."""

    name = "least-loaded"

    def rank(self, request, replicas):
        return sorted(replicas, key=lambda r: (r.load(), r.index))


class PrefixAffinityRouter(Router):
    """Least-loaded composed with prefix affinity: among the replicas
    whose load is within ``band`` of the minimum (the load band), the
    longest cached-prefix match wins — so a warm trie keeps attracting
    its prefix family and the aggregate hit-rate survives fan-out —
    while a replica loaded past the band is skipped no matter how warm
    its trie is (affinity must never invert into a hot spot). Within
    the band ties break by load, then index; out-of-band replicas rank
    after the band by plain least-loaded order.

    ``band`` is in load units (KV blocks + queued requests). ``0``
    restricts affinity to exact-minimum-load replicas; the default 16
    tolerates roughly one mid-flight request of imbalance.
    """

    name = "affinity"

    def __init__(self, band=16):
        if int(band) < 0:
            raise ValueError(f"band must be >= 0, got {band}")
        self.band = int(band)

    def rank(self, request, replicas):
        reps = list(replicas)
        if not reps:
            return []
        loads = {r.index: r.load() for r in reps}
        floor = min(loads.values())
        in_band = [r for r in reps if loads[r.index] - floor <= self.band]
        out = [r for r in reps if loads[r.index] - floor > self.band]
        prompt = getattr(request, "prompt", None)
        # capacity-aware placement (the PR-16 tiered-cache follow-on):
        # a replica's warmth is its trie coverage PLUS what its own
        # host tier could readmit in place — so a chain that spilled
        # under pool pressure still attracts its prefix family to the
        # replica HOLDING it (host-RAM readmit) instead of a sibling
        # that would pull the chain host-to-host over the cache plane
        # after placement. Tierless replicas probe 0, leaving every
        # pre-tier routing order unchanged (duck-typed: router unit
        # stubs predating the tier probe simply contribute 0).
        def warmth(r):
            tier = getattr(r, "tier_match_tokens", None)
            return (r.prefix_match_tokens(prompt)
                    + (tier(prompt) if tier is not None else 0))
        in_band.sort(key=lambda r: (-warmth(r), loads[r.index], r.index))
        out.sort(key=lambda r: (loads[r.index], r.index))
        return in_band + out


class ClassHeadroomRouter(Router):
    """Class-aware placement (README "Multi-tenant SLO serving"): rank
    by the replica's CLASS PRESSURE for this request — the load that
    could not be displaced for it (work of equal-or-higher class rank
    plus unclassed intake, :meth:`~.replica.FleetReplica.class_pressure`)
    — then by total load, then index. A latency request routes to the
    replica whose occupancy is mostly preemptible batch work (low
    pressure) over an equally-busy sibling running latency work (high
    pressure), so a burst lands where the policy scheduler can actually
    clear slots for it; batch requests see every slot as pressure and
    degrade to plain least-loaded. With no class table every request
    resolves to one rank and this IS least-loaded routing.

    ``rebalance``/``drain_replica`` are the matching actuator: drain a
    replica of best-effort load to absorb a latency burst, and the
    pressure signal immediately steers the burst at it.
    """

    name = "class-headroom"

    def rank(self, request, replicas):
        return sorted(replicas,
                      key=lambda r: (r.class_pressure(request),
                                     r.load(), r.index))


#: CLI / serve_fleet() name -> constructor
ROUTERS = {
    RoundRobinRouter.name: RoundRobinRouter,
    LeastLoadedRouter.name: LeastLoadedRouter,
    PrefixAffinityRouter.name: PrefixAffinityRouter,
    ClassHeadroomRouter.name: ClassHeadroomRouter,
}


def make_router(policy, **kw) -> Router:
    """Build a router from its policy name (``round-robin`` |
    ``least-loaded`` | ``affinity`` | ``class-headroom``); a
    :class:`Router` instance passes through unchanged."""
    if isinstance(policy, Router):
        return policy
    try:
        return ROUTERS[str(policy)](**kw)
    except KeyError:
        raise ValueError(
            f"unknown router policy {policy!r}; choose from "
            f"{sorted(ROUTERS)}") from None
