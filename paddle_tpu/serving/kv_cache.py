"""Slot-based paged KV cache for continuous-batching decode.

The cache is two dense arrays ``[L, num_slots, max_seq_len, Hkv, D]``
(the paddle cache layout the ragged Pallas decode kernel reads in place,
``kernels/pallas_decode.py``) plus a host-side ``lengths[num_slots]``
mirror and a free-slot list. "Paged" here is at slot granularity — the
TPU-friendly degenerate page size of one sequence per page: admission
claims a free slot, finish releases it, and the freed slot's stale rows
are never touched again (the ragged kernel skips KV blocks past
``lengths[b]``, so garbage costs no HBM traffic and no zeroing pass).

The device arrays are functionally updated (donated through the jitted
writers on non-CPU backends, so XLA updates in place); the host mirror is
the scheduling truth — device-side lengths are always re-fed from it, so
a freed slot resets by writing one host int, not a device op.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def _write_prefill(cache_k, cache_v, pk, pv, slot):
    # pk/pv: [L, S_pad, Hkv, D] -> one slot's leading rows. Rows past the
    # real prompt length hold prefill padding garbage; they sit beyond
    # lengths[slot] (masked) until the decode loop overwrites them.
    ck = jax.lax.dynamic_update_slice(cache_k, pk[:, None], (0, slot, 0, 0, 0))
    cv = jax.lax.dynamic_update_slice(cache_v, pv[:, None], (0, slot, 0, 0, 0))
    return ck, cv


@functools.lru_cache(maxsize=None)
def _writer(donate):
    # module-level so every cache instance (one per engine, one engine
    # per model.generate call) shares the jitted program instead of
    # re-tracing it
    return jax.jit(_write_prefill, donate_argnums=(0, 1) if donate else ())


class SlotKVCache:
    """KV-cache manager: device arrays + slot allocator + lengths mirror."""

    def __init__(self, num_layers, num_slots, max_seq_len, num_kv_heads,
                 head_dim, dtype=jnp.float32, donate=None):
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len)
        shape = (num_layers, num_slots, max_seq_len, num_kv_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # host mirror is the source of truth; device lengths are re-fed
        # from it every step
        self.lengths = np.zeros(num_slots, np.int32)
        self._free = list(range(num_slots))
        if donate is None:
            # donation is a no-op (warning) on CPU; an in-place cache
            # update is the whole point everywhere else
            donate = jax.default_backend() != "cpu"
        self._write = _writer(bool(donate))

    # ------------------------------------------------------------- slots
    @property
    def num_free(self) -> int:
        return len(self._free)

    def alloc(self):
        """Claim a free slot (lowest index first, deterministic)."""
        if not self._free:
            return None
        self._free.sort()
        return self._free.pop(0)

    def free(self, slot: int):
        if slot in self._free:
            raise ValueError(f"slot {slot} double-freed")
        self.lengths[slot] = 0
        self._free.append(slot)

    # ------------------------------------------------------------ writes
    def write_prefill(self, slot, pk, pv, prompt_len):
        """Install a prefilled prompt's K/V into ``slot``."""
        if pk.shape[1] > self.max_seq_len:
            raise ValueError(
                f"prefill length {pk.shape[1]} exceeds max_seq_len "
                f"{self.max_seq_len}")
        self.k, self.v = self._write(self.k, self.v, pk, pv, np.int32(slot))
        self.lengths[slot] = int(prompt_len)

    def update(self, new_k, new_v):
        """Adopt the decode step's functionally-updated cache arrays."""
        self.k, self.v = new_k, new_v
