"""Slot-based paged KV cache for continuous-batching decode.

The cache is two dense arrays ``[L, num_slots, max_seq_len, Hkv, D]``
(the paddle cache layout the ragged Pallas decode kernel reads in place,
``kernels/pallas_decode.py``) plus a host-side ``lengths[num_slots]``
mirror and a free-slot pool. "Paged" here is at slot granularity — the
TPU-friendly degenerate page size of one sequence per page: admission
claims a free slot, finish releases it, and the freed slot's stale rows
are never touched again (the ragged kernel skips KV blocks past
``lengths[b]``, so garbage costs no HBM traffic and no zeroing pass).

The device arrays are functionally updated (donated through the jitted
writers on non-CPU backends, so XLA updates in place); the host mirror is
the scheduling truth — device-side lengths are always re-fed from it, so
a freed slot resets by writing one host int, not a device op.

Block copy programs (the prefix-cache transport, ``serving/prefix_cache``):
``copy_block_in`` installs one published pool block into a slot's rows and
``copy_block_out`` publishes one slot block into the pool. Both are single
compile-once jitted programs — shapes depend only on the cache/pool
geometry; the slot / row / block indices are runtime scalars — so cache
hits, evictions, and publishes never add traces.

:class:`PagedKVCache` is the zero-copy successor ("Ragged Paged
Attention", PAPERS.md): the :class:`~.block_manager.BlockManager` pool
IS the cache — there is no per-slot dense array at all. Each live slot
owns a row of a host block table ``[num_slots, max_blocks]`` naming the
physical pool blocks that spell its logical cache; prefix-cache hits
install by *referencing* published block ids (no ``copy_block_in``
dispatch, no private copy — N holders share one block), decode growth
appends fresh private blocks lazily, and retirement *donates* full
prompt blocks to the trie instead of copying them out. The same
alloc/free/``write_prefill`` surface as :class:`SlotKVCache` keeps the
engine's cold path identical; the decode/suffix programs read the pool
through runtime table arguments (``serving/decode.py``), so the
compile-once contract survives unchanged.
"""
from __future__ import annotations

import functools
import heapq

import jax
import jax.numpy as jnp
import numpy as np


class PoolExhausted(RuntimeError):
    """KV block pool exhausted: live sequences + pinned prefix blocks
    exceed the pool. A RuntimeError subclass for back-compat with
    callers that caught the old untyped raise, but TYPED so the engine
    can catch it and preempt the youngest sequence by recompute
    (``ContinuousBatchingEngine`` donates the victim's chain to the
    prefix trie and re-queues it) instead of taking the server down.
    Carries the pool occupancy snapshot at the failed allocation."""

    def __init__(self, live_blocks=0, pinned_blocks=0, free_blocks=0,
                 message=None):
        self.live_blocks = int(live_blocks)
        self.pinned_blocks = int(pinned_blocks)
        self.free_blocks = int(free_blocks)
        super().__init__(message or (
            f"KV block pool exhausted: live sequences + pinned prefix "
            f"blocks exceed the pool (live={self.live_blocks}, "
            f"pinned={self.pinned_blocks}, free={self.free_blocks}); "
            f"size the pool to at least num_slots * max_blocks + prefix "
            f"budget"))


def quantize_kv_rows(x):
    """Per-row-per-head symmetric int8 quantization of K/V rows — THE
    quantization rule of the int8 block pool (README "Quantized
    serving"); every append path (prefill scatter, chunk write, decode
    append, spec-verify write, multi-tick in-loop append) routes
    through this one function so the grid can never drift between
    sites. ``x [..., Hkv, D]`` → ``(q int8 same shape,
    scale f32 [..., Hkv])`` with ``scale = amax|x| / 127`` per
    (row, head): each row quantizes INDEPENDENTLY — no neighbor, no
    stale pool garbage, no earlier append influences it — which is
    what makes quantized streams deterministic under restore()/replay
    and lets truncate/donate move blocks without touching values.
    All-zero rows carry scale 0 and dequantize to exact zeros."""
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / 127.0
    q = jnp.clip(jnp.round(xf / jnp.maximum(scale, 1e-30)[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, scale


FP8_MAX = 448.0   # float8_e4m3fn finite max — the saturation bound


def quantize_kv_rows_fp8(x):
    """THE fp8 write rule, ``quantize_kv_rows``' e4m3 sibling:
    ``x [..., Hkv, D]`` → ``float8_e4m3fn`` same shape via a saturating
    cast (clip to ±448 first: e4m3fn overflow is NaN, not a saturate).
    No scale is computed or written — the pool's per-BLOCK scale planes
    are the constant 1.0 (``BlockManager`` docstring): e4m3's exponent
    IS the per-value scale, and any data-dependent block scale would
    tie a block's bytes to which program first wrote it (decode appends
    cover one row, prefill chunks cover the whole block), breaking
    restore()/replay byte-identity. Rows still quantize independently,
    so every append path shares this one rule exactly like int8's."""
    xf = x.astype(jnp.float32)
    return jnp.clip(xf, -FP8_MAX, FP8_MAX).astype(jnp.float8_e4m3fn)


def _write_prefill(cache_k, cache_v, pk, pv, slot):
    # pk/pv: [L, S_pad, Hkv, D] -> one slot's leading rows. Rows past the
    # real prompt length hold prefill padding garbage; they sit beyond
    # lengths[slot] (masked) until the decode loop overwrites them.
    ck = jax.lax.dynamic_update_slice(cache_k, pk[:, None], (0, slot, 0, 0, 0))
    cv = jax.lax.dynamic_update_slice(cache_v, pv[:, None], (0, slot, 0, 0, 0))
    return ck, cv


def _copy_block_in(cache_k, cache_v, pool_k, pool_v, slot, row0, block_id):
    # pool block [L, 1, bs, Hkv, D] -> cache rows [row0, row0+bs) of slot
    L, _, bs, Hkv, D = pool_k.shape
    bk = jax.lax.dynamic_slice(pool_k, (0, block_id, 0, 0, 0),
                               (L, 1, bs, Hkv, D))
    bv = jax.lax.dynamic_slice(pool_v, (0, block_id, 0, 0, 0),
                               (L, 1, bs, Hkv, D))
    ck = jax.lax.dynamic_update_slice(cache_k, bk, (0, slot, row0, 0, 0))
    cv = jax.lax.dynamic_update_slice(cache_v, bv, (0, slot, row0, 0, 0))
    return ck, cv


def _copy_block_out(pool_k, pool_v, cache_k, cache_v, slot, row0, block_id):
    # cache rows [row0, row0+bs) of slot -> pool block (publish)
    L, _, bs, Hkv, D = pool_k.shape
    bk = jax.lax.dynamic_slice(cache_k, (0, slot, row0, 0, 0),
                               (L, 1, bs, Hkv, D))
    bv = jax.lax.dynamic_slice(cache_v, (0, slot, row0, 0, 0),
                               (L, 1, bs, Hkv, D))
    pk = jax.lax.dynamic_update_slice(pool_k, bk, (0, block_id, 0, 0, 0))
    pv = jax.lax.dynamic_update_slice(pool_v, bv, (0, block_id, 0, 0, 0))
    return pk, pv


def _prefill_scatter_coords(pool_k, pk, table_row, prompt_len):
    # THE prefill scatter-coordinate rule, shared by the plain and
    # quantized writers (the clamp/drop semantics must not fork):
    # rows [0, prompt_len) map through the slot's block table; rows
    # past prompt_len (bucket padding) map to the sentinel ``nb`` and
    # DROP — they must not land in the pool, where the trailing
    # private block is real but any row beyond it would clip-alias
    # another sequence's block.
    S = pk.shape[1]
    nb, bs = pool_k.shape[1], pool_k.shape[2]
    pos = jnp.arange(S, dtype=jnp.int32)
    bi = jnp.minimum(pos // bs, table_row.shape[0] - 1)
    phys = jnp.where(pos < prompt_len, jnp.take(table_row, bi), nb)
    return phys, pos % bs


def _paged_write_prefill(pool_k, pool_v, pk, pv, table_row, prompt_len):
    # pk/pv: [L, S_pad, Hkv, D] -> scatter through the block table
    # (coordinate rule + padding-drop: _prefill_scatter_coords)
    phys, row = _prefill_scatter_coords(pool_k, pk, table_row,
                                        prompt_len)
    pool_k = pool_k.at[:, phys, row].set(pk, mode="drop")
    pool_v = pool_v.at[:, phys, row].set(pv, mode="drop")
    return pool_k, pool_v


def _paged_write_prefill_q(pool_k, pool_v, pool_ks, pool_vs, pk, pv,
                           table_row, prompt_len):
    # the quantized twin of _paged_write_prefill: the prefill program's
    # full-precision K/V rows quantize ON WRITE (quantize_kv_rows) and
    # land int8 in the pool with their per-row-per-head scales written
    # to the SAME (block, row) coordinates (shared rule:
    # _prefill_scatter_coords) — one drop-mode scatter each, so
    # padding rows vanish from data and scales alike
    phys, row = _prefill_scatter_coords(pool_k, pk, table_row,
                                        prompt_len)
    qk, sk = quantize_kv_rows(pk)
    qv, sv = quantize_kv_rows(pv)
    pool_k = pool_k.at[:, phys, row].set(qk, mode="drop")
    pool_v = pool_v.at[:, phys, row].set(qv, mode="drop")
    pool_ks = pool_ks.at[:, phys, row].set(sk, mode="drop")
    pool_vs = pool_vs.at[:, phys, row].set(sv, mode="drop")
    return pool_k, pool_v, pool_ks, pool_vs


def _paged_write_prefill_f8(pool_k, pool_v, pk, pv, table_row,
                            prompt_len):
    # the fp8 twin: same coordinate rule, but the write is a saturating
    # e4m3 cast of the data alone — the per-block scale planes are the
    # constant 1.0 and are never touched by an append
    # (quantize_kv_rows_fp8 docstring), so only the data scatters
    phys, row = _prefill_scatter_coords(pool_k, pk, table_row,
                                        prompt_len)
    pool_k = pool_k.at[:, phys, row].set(quantize_kv_rows_fp8(pk),
                                         mode="drop")
    pool_v = pool_v.at[:, phys, row].set(quantize_kv_rows_fp8(pv),
                                         mode="drop")
    return pool_k, pool_v


@functools.lru_cache(maxsize=None)
def _writer(donate):
    # module-level so every cache instance (one per engine, one engine
    # per model.generate call) shares the jitted program instead of
    # re-tracing it
    return jax.jit(_write_prefill, donate_argnums=(0, 1) if donate else ())


@functools.lru_cache(maxsize=None)
def _paged_writer(donate, quantized=False, tp=1):
    # donate the POOL arrays (the pool is the cache being updated);
    # the int8 writer donates the scale planes too. ``quantized`` is
    # the pool's kv mode: False (store at pool dtype), "int8"/True
    # (per-row quantize-on-write, scales scatter beside the data) or
    # "fp8" (saturating e4m3 cast, data only — the per-block planes
    # are constant and never written). On a tensor-parallel pool
    # (tp > 1) the writer runs under shard_map with the pool (and the
    # prefill K/V it scatters) partitioned on the head axis — NOT
    # auto-GSPMD: the scatter must hand the pool back with exactly the
    # sharding the sharded step programs expect, or the first
    # post-prefill step pays a re-specialization and the compile-once
    # pin breaks (README "Tensor-parallel serving").
    fp8 = quantized == "fp8"
    int8 = bool(quantized) and not fp8
    impl = (_paged_write_prefill_f8 if fp8
            else _paged_write_prefill_q if int8 else _paged_write_prefill)
    if tp > 1:
        from jax.sharding import PartitionSpec as P
        from .decode import _pool_pspec, _tp_mesh
        # THE pool spec, not a local re-spelling: the scatter must hand
        # the pool back under exactly the sharding the sharded step
        # programs expect (scale planes shard on the same head axis)
        kv = P(None, None, "tp")            # pk/pv [L, S, Hkv, D]
        rep = P()
        if int8:
            pool, sc = _pool_pspec("int8")
            in_specs = (pool, pool, sc, sc, kv, kv, rep, rep)
            out_specs = (pool, pool, sc, sc)
        else:
            # the fp8 writer touches the DATA only, so its spec set is
            # the plain writer's (with the fp8 pool's data spec)
            pool = _pool_pspec("fp8")[0] if fp8 else _pool_pspec(False)
            in_specs = (pool, pool, kv, kv, rep, rep)
            out_specs = (pool, pool)
        impl = jax.shard_map(impl, mesh=_tp_mesh(tp), in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    if int8:
        return jax.jit(impl, donate_argnums=(0, 1, 2, 3) if donate else ())
    return jax.jit(impl, donate_argnums=(0, 1) if donate else ())


@functools.lru_cache(maxsize=None)
def _block_in(donate):
    # donate the CACHE arrays (they are the ones functionally updated)
    return jax.jit(_copy_block_in, donate_argnums=(0, 1) if donate else ())


@functools.lru_cache(maxsize=None)
def _block_out(donate):
    # donate the POOL arrays (publish updates the pool in place)
    return jax.jit(_copy_block_out, donate_argnums=(0, 1) if donate else ())


def copy_compilations() -> int:
    """Total traces of the block copy programs (both donate modes) — the
    prefix-cache half of the bounded-compile contract: stays at one per
    (geometry, donate) no matter how many hits/publishes run."""
    return sum(fn._cache_size()
               for fn in (_block_in(True), _block_in(False),
                          _block_out(True), _block_out(False)))


# ------------------------------------------------- tier transfer programs
# The host-RAM spill tier's device side (README "Tiered KV prefix
# cache"): fetch slices one pool block out for the d2h spill, inject
# scatters a readmitted block back. Same compile-once rule as the block
# copy programs above: the block id is a runtime np.int32 scalar
# (dynamic_slice / dynamic_update_slice), so one trace per (quantized,
# tp[, donate]) serves every block — a python-int index would bake into
# the dispatch-cache key and compile once per block id.

def _tier_fetch_impl(pool_k, pool_v, block_id):
    # pool block [L, 1, bs, Hkv, D] -> standalone device buffers the
    # host tier copies down (np.asarray is the d2h)
    L, _, bs, Hkv, D = pool_k.shape
    bk = jax.lax.dynamic_slice(pool_k, (0, block_id, 0, 0, 0),
                               (L, 1, bs, Hkv, D))
    bv = jax.lax.dynamic_slice(pool_v, (0, block_id, 0, 0, 0),
                               (L, 1, bs, Hkv, D))
    return bk, bv


def _scale_block_slice(planes, block_id):
    # one block's scale planes, rank-generic: int8 planes are per-row
    # [L, nb, bs, Hkv], fp8 planes per-block [L, nb, Hkv] — the block
    # axis is axis 1 in both, so one slice rule serves both pools
    return jax.lax.dynamic_slice(
        planes, (0, block_id) + (0,) * (planes.ndim - 2),
        (planes.shape[0], 1) + planes.shape[2:])


def _tier_fetch_q_impl(pool_k, pool_v, pool_ks, pool_vs, block_id):
    # quantized twin: the int8/fp8 data block travels WITH its fp32
    # scale planes — same block id, no separate bookkeeping
    bk, bv = _tier_fetch_impl(pool_k, pool_v, block_id)
    bks = _scale_block_slice(pool_ks, block_id)
    bvs = _scale_block_slice(pool_vs, block_id)
    return bk, bv, bks, bvs


def _tier_inject_impl(pool_k, pool_v, bk, bv, block_id):
    # readmission: one spilled block's buffers -> pool block ``block_id``
    pk = jax.lax.dynamic_update_slice(pool_k, bk, (0, block_id, 0, 0, 0))
    pv = jax.lax.dynamic_update_slice(pool_v, bv, (0, block_id, 0, 0, 0))
    return pk, pv


def _tier_inject_q_impl(pool_k, pool_v, pool_ks, pool_vs,
                        bk, bv, bks, bvs, block_id):
    pk, pv = _tier_inject_impl(pool_k, pool_v, bk, bv, block_id)
    at = lambda planes: (0, block_id) + (0,) * (planes.ndim - 2)  # noqa: E731
    pks = jax.lax.dynamic_update_slice(pool_ks, bks, at(pool_ks))
    pvs = jax.lax.dynamic_update_slice(pool_vs, bvs, at(pool_vs))
    return pk, pv, pks, pvs


_TIER_PROGRAMS = []   # every distinct jitted tier program, for the counter


def _tier_pspecs(quantized, tp):
    # the block buffer [L, 1, bs, Hkv, D] partitions on the SAME head
    # axis as the pool (serving/decode._pool_pspec — THE spec, not a
    # re-spelling), so fetch hands out shards the host gathers and
    # inject hands the pool back exactly as the sharded step programs
    # expect it
    from .decode import _pool_pspec
    if quantized:
        # quantized is the kv mode string here ("int8"/"fp8" — True is
        # accepted as int8): the fp8 pool's per-block planes drop the
        # row axis, so their spec differs from int8's per-row planes
        pool, sc = _pool_pspec("int8" if quantized is True else quantized)
        return (pool, pool, sc, sc), (pool, pool, sc, sc)
    pool = _pool_pspec(False)
    return (pool, pool), (pool, pool)


@functools.lru_cache(maxsize=None)
def _tier_fetch(quantized=False, tp=1):
    # no donation: the spill READS the pool (eviction frees the block's
    # id, not its storage — pool arrays are dense and preallocated)
    impl = _tier_fetch_q_impl if quantized else _tier_fetch_impl
    if tp > 1:
        from jax.sharding import PartitionSpec as P
        from .decode import _tp_mesh
        pool_specs, block_specs = _tier_pspecs(quantized, tp)
        impl = jax.shard_map(impl, mesh=_tp_mesh(tp),
                             in_specs=pool_specs + (P(),),
                             out_specs=block_specs, check_vma=False)
    fn = jax.jit(impl)
    _TIER_PROGRAMS.append(fn)
    return fn


@functools.lru_cache(maxsize=None)
def _tier_inject(donate, quantized=False, tp=1):
    # donate the POOL arrays (readmission updates the pool in place)
    impl = _tier_inject_q_impl if quantized else _tier_inject_impl
    if tp > 1:
        from jax.sharding import PartitionSpec as P
        from .decode import _tp_mesh
        pool_specs, block_specs = _tier_pspecs(quantized, tp)
        impl = jax.shard_map(impl, mesh=_tp_mesh(tp),
                             in_specs=pool_specs + block_specs + (P(),),
                             out_specs=pool_specs, check_vma=False)
    nargs = 4 if quantized else 2
    fn = jax.jit(impl,
                 donate_argnums=tuple(range(nargs)) if donate else ())
    _TIER_PROGRAMS.append(fn)
    return fn


def tier_compilations() -> int:
    """Total traces of the tier transfer programs — the spill/readmit
    half of the bounded-compile contract: stays at one per (geometry,
    quantized, tp, donate) no matter how many blocks spill or readmit,
    and none of them is an engine jit-cache key, so
    ``decode_compilations() == 1`` holds inclusive of readmitted
    chains."""
    return sum(fn._cache_size() for fn in list(_TIER_PROGRAMS))


class SlotKVCache:
    """Dense per-slot KV cache — the LEGACY compatibility path.

    :class:`PagedKVCache` is the engine default (``paged_attn=True``):
    it subsumes this layout's whole job with zero-copy prefix sharing
    and block-granular HBM, and the chunked-prefill scheduler exists
    only on it. SlotKVCache stays as the ``paged_attn=False`` shim —
    token-identical, one dense ``[L, num_slots, max_seq_len, Hkv, D]``
    array pair, one-shot prefill only — for A/B pinning in tests and
    for backends where the table-gather pattern is hostile. No new
    features land here.

    The free-slot pool is a min-heap plus a membership set: ``alloc`` is
    O(log n) and still deterministic (lowest index first), ``free``'s
    double-free guard is O(1) — the seed version's ``slot in list`` scan
    plus sort-on-alloc was O(n)/O(n log n) per admission.
    """

    def __init__(self, num_layers, num_slots, max_seq_len, num_kv_heads,
                 head_dim, dtype=jnp.float32, donate=None):
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len)
        shape = (num_layers, num_slots, max_seq_len, num_kv_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # host mirror is the source of truth; device lengths are re-fed
        # from it every step
        self.lengths = np.zeros(num_slots, np.int32)
        self._free_heap = list(range(num_slots))  # already heap-ordered
        self._free_set = set(self._free_heap)
        if donate is None:
            # donation is a no-op (warning) on CPU; an in-place cache
            # update is the whole point everywhere else
            donate = jax.default_backend() != "cpu"
        self._donate = bool(donate)
        self._write = _writer(self._donate)

    # ------------------------------------------------------------- slots
    @property
    def num_free(self) -> int:
        return len(self._free_set)

    def alloc(self):
        """Claim a free slot (lowest index first, deterministic)."""
        if not self._free_set:
            return None
        slot = heapq.heappop(self._free_heap)
        self._free_set.discard(slot)
        return slot

    def free(self, slot: int):
        if slot in self._free_set:
            raise ValueError(f"slot {slot} double-freed")
        self.lengths[slot] = 0
        heapq.heappush(self._free_heap, slot)
        self._free_set.add(slot)

    # ------------------------------------------------------------ writes
    def write_prefill(self, slot, pk, pv, prompt_len):
        """Install a prefilled prompt's K/V into ``slot``."""
        if pk.shape[1] > self.max_seq_len:
            raise ValueError(
                f"prefill length {pk.shape[1]} exceeds max_seq_len "
                f"{self.max_seq_len}")
        self.k, self.v = self._write(self.k, self.v, pk, pv, np.int32(slot))
        self.lengths[slot] = int(prompt_len)

    def update(self, new_k, new_v):
        """Adopt the decode step's functionally-updated cache arrays."""
        self.k, self.v = new_k, new_v

    def kv_args(self):
        """The cache arrays as the suffix program takes them — the
        dense twin of :meth:`PagedKVCache.kv_args` (always plain
        ``(k, v)``: the dense shim never quantizes)."""
        return self.k, self.v

    def slot_kv_bytes(self, slot) -> int:
        """HBM bytes of the slot's valid rows (rows × per-row bytes) —
        the dense twin of :meth:`PagedKVCache.slot_kv_bytes` for the
        ``/debug/requests`` cost column."""
        per_row = (2 * self.k.size * np.dtype(self.k.dtype).itemsize
                   // (self.num_slots * self.max_seq_len))
        return int(self.lengths[slot]) * per_row

    # ------------------------------------------------------ block copies
    def copy_block_in(self, slot, row0, pool, block_id):
        """Install pool block ``block_id`` into rows [row0, row0+bs) of
        ``slot`` (a prefix-cache hit). One jitted program total — the
        three indices are runtime scalars."""
        self.k, self.v = _block_in(self._donate)(
            self.k, self.v, pool.k, pool.v, np.int32(slot),
            np.int32(row0), np.int32(block_id))

    def copy_block_out(self, slot, row0, pool, block_id):
        """Publish rows [row0, row0+bs) of ``slot`` into pool block
        ``block_id`` (sequence retirement). One jitted program total."""
        pool.k, pool.v = _block_out(self._donate)(
            pool.k, pool.v, self.k, self.v, np.int32(slot),
            np.int32(row0), np.int32(block_id))


class PagedKVCache:
    """Block-table KV cache: slot allocator + host tables over a shared
    :class:`~.block_manager.BlockManager` pool — the zero-copy decode
    cache (module docstring). Surface-compatible with
    :class:`SlotKVCache` where the engine's cold path needs it
    (``alloc``/``free``/``num_free``/``lengths``/``write_prefill``/
    ``update``); the paged-only surface is table bookkeeping:

    - ``install_prefix(slot, block_ids)`` — a prefix-cache hit:
      reference the published blocks in the slot's table. No copy; the
      blocks' read pins are the caller's (``PrefixCache.acquire``).
    - ``ensure_capacity(slot, rows)`` — append-block on growth: allocate
      private blocks (each carrying the slot's ownership ref) until the
      table covers ``rows`` logical rows, evicting unpinned trie blocks
      on demand when the pool runs dry.
    - ``free(slot, keep=...)`` — release the table: donated blocks
      (ownership moved to the trie at publish) are unref'd but stay
      allocated; the rest of the private tail is dropped back to the
      heap; shared prefix entries are merely forgotten (their pins are
      released by the engine through ``PrefixCache.release``).

    The pool's device arrays are the single source of KV truth; the
    decode / suffix-prefill programs update them functionally and the
    engine adopts the result via :meth:`update`.
    """

    def __init__(self, num_layers, num_slots, max_seq_len, num_kv_heads,
                 head_dim, dtype=jnp.float32, block_size=32, pool=None,
                 prefix_cache=None, donate=None, kv_dtype=None):
        from .block_manager import BlockManager
        bs = int(block_size)
        if bs < 1:
            raise ValueError(f"block_size must be >= 1, got {bs}")
        if kv_dtype not in (None, "int8", "fp8"):
            raise ValueError(
                f"kv_dtype must be None (store at pool dtype), 'int8' or "
                f"'fp8', got {kv_dtype!r}")
        self.kv_dtype = kv_dtype
        self.quantized = kv_dtype is not None
        self.fp8 = kv_dtype == "fp8"
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len)
        self.block_size = bs
        self.max_blocks = -(-self.max_seq_len // bs)
        if pool is None:
            pool = BlockManager(num_layers, self.num_slots * self.max_blocks,
                                bs, num_kv_heads, head_dim, dtype=dtype,
                                kv_dtype=kv_dtype)
        if getattr(pool, "kv_dtype", None) != kv_dtype:
            raise ValueError(
                f"pool kv_dtype {getattr(pool, 'kv_dtype', None)!r} does "
                f"not match cache kv_dtype {kv_dtype!r}: a quantized "
                f"cache needs a pool carrying THAT dtype's scale-plane "
                f"layout (int8 per-row vs fp8 per-block, and vice versa)")
        if pool.block_size != bs:
            raise ValueError(
                f"pool block_size {pool.block_size} != cache block_size "
                f"{bs}")
        if pool.num_blocks < self.num_slots * self.max_blocks:
            raise ValueError(
                f"pool of {pool.num_blocks} blocks cannot back "
                f"{self.num_slots} slots x {self.max_blocks} blocks of "
                f"live KV (worst case needs "
                f"{self.num_slots * self.max_blocks})")
        self.pool = pool
        self.prefix_cache = prefix_cache  # evict-on-demand hook (may be None)
        self.sentinel = pool.num_blocks   # out-of-pool id: writes drop
        self.lengths = np.zeros(self.num_slots, np.int32)
        self.tables = np.full((self.num_slots, self.max_blocks),
                              self.sentinel, np.int32)
        self._n_blocks = np.zeros(self.num_slots, np.int32)  # populated
        self._n_shared = np.zeros(self.num_slots, np.int32)  # leading shared
        self._free_heap = list(range(self.num_slots))
        self._free_set = set(self._free_heap)
        if donate is None:
            donate = jax.default_backend() != "cpu"
        self._donate = bool(donate)

    # ------------------------------------------------------------- slots
    @property
    def num_free(self) -> int:
        return len(self._free_set)

    def alloc(self):
        """Claim a free slot (lowest index first, deterministic)."""
        if not self._free_set:
            return None
        slot = heapq.heappop(self._free_heap)
        self._free_set.discard(slot)
        return slot

    def free(self, slot: int, keep=()):
        """Release a slot's table. ``keep`` is the set of block ids whose
        ownership moved to the prefix trie at publish (donated): they
        lose this slot's pin but stay allocated; every other private
        block drops back to the heap. Shared prefix entries (pinned via
        the trie, not owned here) are forgotten — the engine releases
        those pins separately."""
        if slot in self._free_set:
            raise ValueError(f"slot {slot} double-freed")
        for j in range(int(self._n_shared[slot]), int(self._n_blocks[slot])):
            b = int(self.tables[slot, j])
            if b in keep:
                self.pool.unref(b)   # trie adopted it; give up ownership
            else:
                self.pool.drop(b)    # unref -> 0 -> back to the heap
        self.tables[slot, :] = self.sentinel
        self._n_blocks[slot] = 0
        self._n_shared[slot] = 0
        self.lengths[slot] = 0
        heapq.heappush(self._free_heap, slot)
        self._free_set.add(slot)

    # ------------------------------------------------------------ tables
    def install_prefix(self, slot, block_ids):
        """Zero-copy prefix-hit install: the slot's leading table
        entries REFERENCE the published blocks. The caller holds the
        read pins (``PrefixCache.acquire`` at lookup); nothing is
        dispatched and nothing is copied — this is the whole point."""
        n = len(block_ids)
        if n > self.max_blocks:
            raise ValueError(
                f"prefix of {n} blocks exceeds the {self.max_blocks}-entry "
                f"table")
        for j, b in enumerate(block_ids):
            self.tables[slot, j] = int(b)
        self._n_blocks[slot] = n
        self._n_shared[slot] = n

    def _alloc_block(self):
        b = self.pool.alloc()
        while b is None and self.prefix_cache is not None \
                and self.prefix_cache._evict_one():
            b = self.pool.alloc()
        if b is None:
            # unreachable when the pool is sized num_slots*max_blocks +
            # trie budget (live demand is bounded by the table grid and
            # everything else is an evictable unpinned trie block) —
            # typed so a mis-sized shared pool degrades to
            # preemption-by-recompute (the engine catches it) instead
            # of a server-killing crash
            pool = self.pool
            raise PoolExhausted(
                live_blocks=pool.num_used,
                pinned_blocks=int((pool._ref > 0).sum()),
                free_blocks=pool.num_free)
        self.pool.ref(b)             # the slot's ownership pin
        return b

    def ensure_capacity(self, slot, rows: int):
        """Append private blocks until the slot's table covers ``rows``
        logical rows (decode growth / prefill install). Lazy on purpose:
        unwritten tail blocks stay in the pool for the prefix trie until
        a decode chunk actually needs them."""
        need = min(-(-int(rows) // self.block_size), self.max_blocks)
        n = int(self._n_blocks[slot])
        while n < need:
            self.tables[slot, n] = self._alloc_block()
            n += 1
        self._n_blocks[slot] = n

    def truncate(self, slot, rows: int):
        """Roll the slot's table back to cover exactly ``rows`` logical
        rows: every private tail block past ``ceil(rows / block_size)``
        is dropped (unref-to-zero → back to the free heap — the exact
        inverse of :meth:`ensure_capacity`'s growth, so ``num_free`` is
        restored to what a never-grown slot would show). This is the
        speculative-decode rollback primitive (README "Speculative
        decoding"): a verify span appends draft K/V through the table
        like a prefill chunk, and rejected drafts hand their blocks
        straight back here.

        Shared/donated prefix blocks are NEVER truncated: the keep
        count is clamped at the slot's installed-prefix length, so a
        ``rows`` that would reach into trie-owned blocks only drops the
        private tail (their trie pins — and every other reader's — are
        untouched; the engine releases its own read pins separately at
        retirement). Rows inside kept blocks past ``rows`` hold stale
        K/V, which the attention programs mask by length and the next
        append overwrites — same invariant as a freed slot's rows.

        ``lengths[slot]`` is clamped down to ``rows`` when it exceeds
        it (the engine normally re-sets it to the exact accepted length
        right after). No device work: the pool arrays are untouched.
        """
        keep = max(-(-int(rows) // self.block_size),
                   int(self._n_shared[slot]))
        n = int(self._n_blocks[slot])
        for j in range(keep, n):
            self.pool.drop(int(self.tables[slot, j]))
            self.tables[slot, j] = self.sentinel
        if keep < n:
            self._n_blocks[slot] = keep
        if int(self.lengths[slot]) > int(rows):
            self.lengths[slot] = int(rows)

    def slot_block_ids(self, slot):
        """Physical block ids populating the slot's table, in logical
        order — the donation candidates at retirement."""
        return [int(b) for b in self.tables[slot, :int(self._n_blocks[slot])]]

    def table_fill(self) -> float:
        """Fraction of the [num_slots, max_blocks] table grid populated —
        the ``kv_block_table_fill`` gauge."""
        return float(self._n_blocks.sum()) / float(
            self.num_slots * self.max_blocks)

    def occupancy(self) -> dict:
        """Pool occupancy split for the step-timeline counter tracks
        (``kv_blocks`` on the Chrome trace, README "Cost attribution &
        /debug/profile"): ``live`` = distinct physical blocks some live
        slot table references (shared blocks count once), ``trie`` =
        allocated blocks no live table references (trie-only
        residency), ``free`` = the pool's free heap. Host bookkeeping
        only — deterministic and sync-free."""
        refd = set()
        for slot in range(self.num_slots):
            n = int(self._n_blocks[slot])
            refd.update(int(b) for b in self.tables[slot, :n])
        live = len(refd)
        return {"live": live,
                "trie": max(self.pool.num_used - live, 0),
                "free": self.pool.num_free}

    def slot_kv_bytes(self, slot) -> int:
        """HBM bytes the slot's table currently holds (blocks × block
        bytes, scale planes included on a quantized pool) — the
        ``/debug/requests`` cost column. Dtype-aware by construction:
        the pool's per-block byte counts follow its storage dtype."""
        return int(self._n_blocks[slot]) * (
            self.pool.block_nbytes + self.pool.scale_block_nbytes)

    def used_blocks(self) -> int:
        """Allocated (live + trie) blocks — ONE table scan, shared by
        the byte gauges so a /metrics scrape never pays the
        :meth:`occupancy` walk more than once per series."""
        occ = self.occupancy()
        return occ["live"] + occ["trie"]

    def bytes_per_token(self) -> float:
        """Marginal HBM bytes one cached token costs (block data +
        scale-plane bytes / block_size). Pure constants — no occupancy
        scan — so the scrape-time gauge is free."""
        return (self.pool.block_nbytes
                + self.pool.scale_block_nbytes) / self.block_size

    def occupancy_bytes(self) -> dict:
        """Pool occupancy in BYTES, split by storage kind — the
        ``kv_pool_bytes{kind="kv|scales"}`` gauges and the
        ``serving_kv_bytes_per_token`` rate (README "Quantized
        serving"). Derived from :meth:`occupancy`'s block accounting ×
        the pool's dtype-aware per-block byte counts, so an int8 pool
        reports int8 bytes plus its fp32 scale planes and the default
        pool reports exactly what it always did with ``scales == 0``.
        ``capacity_*`` cover the whole pool (the fixed HBM budget the
        density bench holds constant); ``used_*`` cover allocated
        (live + trie) blocks; ``per_token`` is the marginal HBM cost
        of one cached token (block bytes / block_size)."""
        used = self.used_blocks()
        kv_b, sc_b = self.pool.block_nbytes, self.pool.scale_block_nbytes
        return {
            "used_kv": used * kv_b,
            "used_scales": used * sc_b,
            "capacity_kv": self.pool.num_blocks * kv_b,
            "capacity_scales": self.pool.num_blocks * sc_b,
            "per_token": self.bytes_per_token(),
        }

    # ------------------------------------------------------------ writes
    def kv_args(self):
        """The pool arrays as the decode programs take them: plain
        ``(k, v)`` on a full-precision pool, ``((k, k_scale),
        (v, v_scale))`` on an int8 pool — each quantized side is ONE
        pytree argument, so every program signature is unchanged and
        the quantized variant is simply a different trace (keyed apart
        in the engine's jit cache)."""
        p = self.pool
        if self.quantized:
            return (p.k, p.k_scale), (p.v, p.v_scale)
        return p.k, p.v

    def write_prefill(self, slot, pk, pv, prompt_len):
        """Install a prefilled prompt's K/V into ``slot`` — through the
        block table, into private pool blocks (one compile-once scatter
        per prefill bucket; the table row and length are runtime
        arguments). On an int8 pool the full-precision prefill rows
        quantize on write, scales landing beside the data."""
        if pk.shape[1] > self.max_seq_len:
            raise ValueError(
                f"prefill length {pk.shape[1]} exceeds max_seq_len "
                f"{self.max_seq_len}")
        self.ensure_capacity(slot, int(prompt_len))
        p = self.pool
        tp = getattr(p, "tp", 1)
        if self.fp8:
            # data-only write: fp8's per-block scale planes are the
            # constant 1.0 and never touched by appends
            p.k, p.v = _paged_writer(self._donate, "fp8", tp)(
                p.k, p.v, pk, pv,
                jnp.asarray(self.tables[slot]), np.int32(prompt_len))
        elif self.quantized:
            p.k, p.v, p.k_scale, p.v_scale = \
                _paged_writer(self._donate, "int8", tp)(
                    p.k, p.v, p.k_scale, p.v_scale, pk, pv,
                    jnp.asarray(self.tables[slot]), np.int32(prompt_len))
        else:
            p.k, p.v = _paged_writer(self._donate, False, tp)(
                p.k, p.v, pk, pv,
                jnp.asarray(self.tables[slot]), np.int32(prompt_len))
        self.lengths[slot] = int(prompt_len)

    def update(self, new_k, new_v):
        """Adopt the decode/suffix step's functionally-updated pool —
        ``(data, scale)`` pairs on a quantized pool (:meth:`kv_args`'
        inverse), plain arrays otherwise."""
        p = self.pool
        if self.quantized:
            (p.k, p.k_scale), (p.v, p.v_scale) = new_k, new_v
        else:
            p.k, p.v = new_k, new_v
