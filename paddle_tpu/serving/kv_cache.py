"""Slot-based paged KV cache for continuous-batching decode.

The cache is two dense arrays ``[L, num_slots, max_seq_len, Hkv, D]``
(the paddle cache layout the ragged Pallas decode kernel reads in place,
``kernels/pallas_decode.py``) plus a host-side ``lengths[num_slots]``
mirror and a free-slot pool. "Paged" here is at slot granularity — the
TPU-friendly degenerate page size of one sequence per page: admission
claims a free slot, finish releases it, and the freed slot's stale rows
are never touched again (the ragged kernel skips KV blocks past
``lengths[b]``, so garbage costs no HBM traffic and no zeroing pass).

The device arrays are functionally updated (donated through the jitted
writers on non-CPU backends, so XLA updates in place); the host mirror is
the scheduling truth — device-side lengths are always re-fed from it, so
a freed slot resets by writing one host int, not a device op.

Block copy programs (the prefix-cache transport, ``serving/prefix_cache``):
``copy_block_in`` installs one published pool block into a slot's rows and
``copy_block_out`` publishes one slot block into the pool. Both are single
compile-once jitted programs — shapes depend only on the cache/pool
geometry; the slot / row / block indices are runtime scalars — so cache
hits, evictions, and publishes never add traces.
"""
from __future__ import annotations

import functools
import heapq

import jax
import jax.numpy as jnp
import numpy as np


def _write_prefill(cache_k, cache_v, pk, pv, slot):
    # pk/pv: [L, S_pad, Hkv, D] -> one slot's leading rows. Rows past the
    # real prompt length hold prefill padding garbage; they sit beyond
    # lengths[slot] (masked) until the decode loop overwrites them.
    ck = jax.lax.dynamic_update_slice(cache_k, pk[:, None], (0, slot, 0, 0, 0))
    cv = jax.lax.dynamic_update_slice(cache_v, pv[:, None], (0, slot, 0, 0, 0))
    return ck, cv


def _copy_block_in(cache_k, cache_v, pool_k, pool_v, slot, row0, block_id):
    # pool block [L, 1, bs, Hkv, D] -> cache rows [row0, row0+bs) of slot
    L, _, bs, Hkv, D = pool_k.shape
    bk = jax.lax.dynamic_slice(pool_k, (0, block_id, 0, 0, 0),
                               (L, 1, bs, Hkv, D))
    bv = jax.lax.dynamic_slice(pool_v, (0, block_id, 0, 0, 0),
                               (L, 1, bs, Hkv, D))
    ck = jax.lax.dynamic_update_slice(cache_k, bk, (0, slot, row0, 0, 0))
    cv = jax.lax.dynamic_update_slice(cache_v, bv, (0, slot, row0, 0, 0))
    return ck, cv


def _copy_block_out(pool_k, pool_v, cache_k, cache_v, slot, row0, block_id):
    # cache rows [row0, row0+bs) of slot -> pool block (publish)
    L, _, bs, Hkv, D = pool_k.shape
    bk = jax.lax.dynamic_slice(cache_k, (0, slot, row0, 0, 0),
                               (L, 1, bs, Hkv, D))
    bv = jax.lax.dynamic_slice(cache_v, (0, slot, row0, 0, 0),
                               (L, 1, bs, Hkv, D))
    pk = jax.lax.dynamic_update_slice(pool_k, bk, (0, block_id, 0, 0, 0))
    pv = jax.lax.dynamic_update_slice(pool_v, bv, (0, block_id, 0, 0, 0))
    return pk, pv


@functools.lru_cache(maxsize=None)
def _writer(donate):
    # module-level so every cache instance (one per engine, one engine
    # per model.generate call) shares the jitted program instead of
    # re-tracing it
    return jax.jit(_write_prefill, donate_argnums=(0, 1) if donate else ())


@functools.lru_cache(maxsize=None)
def _block_in(donate):
    # donate the CACHE arrays (they are the ones functionally updated)
    return jax.jit(_copy_block_in, donate_argnums=(0, 1) if donate else ())


@functools.lru_cache(maxsize=None)
def _block_out(donate):
    # donate the POOL arrays (publish updates the pool in place)
    return jax.jit(_copy_block_out, donate_argnums=(0, 1) if donate else ())


def copy_compilations() -> int:
    """Total traces of the block copy programs (both donate modes) — the
    prefix-cache half of the bounded-compile contract: stays at one per
    (geometry, donate) no matter how many hits/publishes run."""
    return sum(fn._cache_size()
               for fn in (_block_in(True), _block_in(False),
                          _block_out(True), _block_out(False)))


class SlotKVCache:
    """KV-cache manager: device arrays + slot allocator + lengths mirror.

    The free-slot pool is a min-heap plus a membership set: ``alloc`` is
    O(log n) and still deterministic (lowest index first), ``free``'s
    double-free guard is O(1) — the seed version's ``slot in list`` scan
    plus sort-on-alloc was O(n)/O(n log n) per admission.
    """

    def __init__(self, num_layers, num_slots, max_seq_len, num_kv_heads,
                 head_dim, dtype=jnp.float32, donate=None):
        self.num_slots = int(num_slots)
        self.max_seq_len = int(max_seq_len)
        shape = (num_layers, num_slots, max_seq_len, num_kv_heads, head_dim)
        self.k = jnp.zeros(shape, dtype)
        self.v = jnp.zeros(shape, dtype)
        # host mirror is the source of truth; device lengths are re-fed
        # from it every step
        self.lengths = np.zeros(num_slots, np.int32)
        self._free_heap = list(range(num_slots))  # already heap-ordered
        self._free_set = set(self._free_heap)
        if donate is None:
            # donation is a no-op (warning) on CPU; an in-place cache
            # update is the whole point everywhere else
            donate = jax.default_backend() != "cpu"
        self._donate = bool(donate)
        self._write = _writer(self._donate)

    # ------------------------------------------------------------- slots
    @property
    def num_free(self) -> int:
        return len(self._free_set)

    def alloc(self):
        """Claim a free slot (lowest index first, deterministic)."""
        if not self._free_set:
            return None
        slot = heapq.heappop(self._free_heap)
        self._free_set.discard(slot)
        return slot

    def free(self, slot: int):
        if slot in self._free_set:
            raise ValueError(f"slot {slot} double-freed")
        self.lengths[slot] = 0
        heapq.heappush(self._free_heap, slot)
        self._free_set.add(slot)

    # ------------------------------------------------------------ writes
    def write_prefill(self, slot, pk, pv, prompt_len):
        """Install a prefilled prompt's K/V into ``slot``."""
        if pk.shape[1] > self.max_seq_len:
            raise ValueError(
                f"prefill length {pk.shape[1]} exceeds max_seq_len "
                f"{self.max_seq_len}")
        self.k, self.v = self._write(self.k, self.v, pk, pv, np.int32(slot))
        self.lengths[slot] = int(prompt_len)

    def update(self, new_k, new_v):
        """Adopt the decode step's functionally-updated cache arrays."""
        self.k, self.v = new_k, new_v

    # ------------------------------------------------------ block copies
    def copy_block_in(self, slot, row0, pool, block_id):
        """Install pool block ``block_id`` into rows [row0, row0+bs) of
        ``slot`` (a prefix-cache hit). One jitted program total — the
        three indices are runtime scalars."""
        self.k, self.v = _block_in(self._donate)(
            self.k, self.v, pool.k, pool.v, np.int32(slot),
            np.int32(row0), np.int32(block_id))

    def copy_block_out(self, slot, row0, pool, block_id):
        """Publish rows [row0, row0+bs) of ``slot`` into pool block
        ``block_id`` (sequence retirement). One jitted program total."""
        pool.k, pool.v = _block_out(self._donate)(
            pool.k, pool.v, self.k, self.v, np.int32(slot),
            np.int32(row0), np.int32(block_id))
