"""Multi-tenant SLO policy layer (README "Multi-tenant SLO serving").

Priority classes with TTFT/TPOT targets (:mod:`.classes`),
deadline-aware admission with per-class headroom and anti-starvation
aging (:mod:`.admission`), and preemption victim selection
(:mod:`.victim`). Policy, not geometry: nothing here touches a traced
shape or a jit key, and the default single-class table keeps the
engine byte-identical to the FIFO baseline.
"""
from .classes import DEFAULT_CLASS_NAME, ClassTable, PriorityClass
from .admission import PolicyScheduler
from .victim import select_victims, victim_key

__all__ = [
    "DEFAULT_CLASS_NAME",
    "ClassTable",
    "PriorityClass",
    "PolicyScheduler",
    "select_victims",
    "victim_key",
]
