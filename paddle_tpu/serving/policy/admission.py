"""Deadline-aware admission: the :class:`PolicyScheduler` (README
"Multi-tenant SLO serving").

Extends the engine's :class:`~paddle_tpu.serving.scheduler.FIFOScheduler`
so that when a multi-class table is active, admission order becomes
(effective class rank, TTFT deadline slack, FIFO tick) instead of pure
FIFO, per-class slot headroom is enforced, and the scheduler can name
which queued requests are SLO-urgent enough to justify preempting
running best-effort work. Everything else — chunked-prefill budgeting,
spec grants, fused-step choice, the ``queue`` deque identity the
gateway snapshots — is inherited unchanged, and the queue object is
only ever mutated IN PLACE (``remove`` / ``append``), never replaced.

The scheduler reads time through an injected clock (the engine's own,
a :class:`~paddle_tpu.serving.faults.VirtualClock` in tests and the SLO
bench), so admission order and urgency replay deterministically.

Effective rank = true class rank + ⌊waited / aging_s⌋ — the
anti-starvation rule: a batch request that has waited one aging
quantum competes like standard, two like latency, so best-effort
traffic always drains. Aging affects ADMISSION ORDER only; preemption
authority (:mod:`.victim`) always uses the true class rank.
"""
from __future__ import annotations

from .classes import ClassTable
from ..scheduler import FIFOScheduler


class PolicyScheduler(FIFOScheduler):
    """Class-and-deadline-aware admission over the FIFO baseline.

    ``table`` is the engine's :class:`~.classes.ClassTable`; ``clock``
    a zero-arg callable returning seconds (the engine's injected
    clock). ``slot_usage`` is a zero-arg callable returning
    ``{class_name: running_count}`` for the headroom ledger — the
    engine binds it to a walk of its slot array. ``urgency_frac`` is
    the fraction of a class's TTFT budget a queued request may burn
    waiting before it is URGENT (preemption-eligible): 0.5 means the
    policy moves at half the budget, leaving the other half for the
    victim's displacement and the prefill itself.
    """

    def __init__(self, decode_chunk=8, table=None, clock=None,
                 slot_usage=None, urgency_frac=0.5):
        super().__init__(decode_chunk)
        self.table = table if table is not None else ClassTable.single()
        self.clock = clock if clock is not None else (lambda: 0.0)
        self.slot_usage = slot_usage
        if not (0.0 < float(urgency_frac) <= 1.0):
            raise ValueError(
                f"urgency_frac must be in (0, 1], got {urgency_frac}")
        self.urgency_frac = float(urgency_frac)
        # guard-discipline: the scheduler records admission decisions
        # through the same nullable tracer idiom as the engine — the
        # engine syncs this alias at the top of every step
        self.tracer = None

    def _tr(self):
        """Tracer alias for this decision (None = recording off)."""
        return self.tracer

    # ------------------------------------------------------ priority core
    def _pclass(self, seq):
        pclass = getattr(seq, "pclass", None)
        return pclass if pclass is not None else self.table.resolve(None)

    def _waited(self, seq, now):
        t = getattr(seq, "t_submit", None)
        return max(0.0, now - t) if t is not None else 0.0

    def slack_s(self, seq, now=None):
        """TTFT deadline slack in seconds: target minus time already
        waited (negative = already past target; +inf = no target)."""
        if now is None:
            now = self.clock()
        pclass = self._pclass(seq)
        if pclass.ttft_slo_s is None:
            return float("inf")
        return pclass.ttft_slo_s - self._waited(seq, now)

    def effective_rank(self, seq, now):
        """True class rank plus the anti-starvation aging credit."""
        rank = self._pclass(seq).rank
        if self.table.aging_s:
            rank += int(self._waited(seq, now) / self.table.aging_s)
        return rank

    def _priority_key(self, now):
        """Admission sort key, most-deserving FIRST under ascending
        sort: (-effective rank, deadline slack, FIFO tick). Within a
        rank the tightest TTFT deadline goes first; with equal slack
        (e.g. two no-target classes at inf) seniority decides, which
        collapses to exact FIFO inside any single class."""
        def key(seq):
            return (-self.effective_rank(seq, now),
                    self.slack_s(seq, now),
                    getattr(seq, "queue_tick", 0))
        return key

    # -------------------------------------------------------- admission
    def admissions(self, num_free, hit_len_fn=None):
        """Pop up to ``num_free`` sequences in priority order, holding
        back reserved headroom.

        Headroom: a class with ``reserved_slots = k`` keeps
        ``max(0, k - running_k)`` slots off-limits to every OTHER
        class, so a best-effort flood can never occupy the whole
        engine. A class always admits into its own reservation first;
        admission of any class stops when the remaining free slots
        would dip below the headroom owed to everyone else. The
        admitted set is then handed to the same prefix-hit bookkeeping
        and uncovered-suffix ordering as the FIFO baseline, so slot
        assignment math downstream is unchanged."""
        tr = self._tr()
        now = self.clock()
        used = dict(self.slot_usage()) if self.slot_usage is not None else {}
        ordered = sorted(self.queue, key=self._priority_key(now))
        out = []
        for seq in ordered:
            if len(out) >= num_free:
                break
            pclass = self._pclass(seq)
            # headroom owed to OTHER classes after this admission
            owed = 0
            for c in self.table:
                if c.name == pclass.name or not c.reserved_slots:
                    continue
                owed += max(0, c.reserved_slots - used.get(c.name, 0))
            free_after = num_free - len(out) - 1
            if free_after < owed:
                if tr is not None:
                    tr.instant("policy.headroom_hold",
                               cls=pclass.name, owed=owed)
                continue    # later (lower-priority) classes may still fit
            out.append(seq)
            used[pclass.name] = used.get(pclass.name, 0) + 1
        for seq in out:
            self.queue.remove(seq)     # in place: gateway snapshots self.queue
        if hit_len_fn is not None:
            for seq in out:
                seq.prefix_hit_tokens = int(hit_len_fn(seq))
            if len(out) > 1:
                out.sort(key=lambda s: s.work_len - s.prefix_hit_tokens)
        return out

    # -------------------------------------------------------- preemption
    def urgent(self, now=None):
        """Queued sequences at risk of missing their TTFT target:
        waited past ``urgency_frac`` of the class budget. Sorted by the
        same priority key as admission, so the engine services the
        most-deserving urgency first. Requests with no TTFT target are
        never urgent — a class without a deadline never displaces
        anyone."""
        if now is None:
            now = self.clock()
        hot = []
        for seq in self.queue:
            pclass = self._pclass(seq)
            if pclass.ttft_slo_s is None:
                continue
            if self._waited(seq, now) >= pclass.ttft_slo_s * self.urgency_frac:
                hot.append(seq)
        hot.sort(key=self._priority_key(now))
        return hot
