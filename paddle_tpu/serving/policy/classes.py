"""Priority classes for multi-tenant SLO serving (README "Multi-tenant
SLO serving"; ROADMAP multi-tenant item a).

A :class:`PriorityClass` names one tenant tier — ``latency`` /
``standard`` / ``batch`` in the canonical three-way split — with its
TTFT/TPOT SLO targets, its preemption rank, and the slot headroom the
scheduler reserves for it. A :class:`ClassTable` is the engine's closed
set of classes: every request resolves against it at validate time (an
unknown ``priority_class`` is a ValueError — the HTTP 400, never a
driver crash), and the default table is a SINGLE neutral class with no
targets, so an engine built without policy knobs schedules exactly like
the FIFO baseline and every banked stream stays byte-identical.

Classes are POLICY, not geometry: they change admission order and
preemption choices — host-side decisions — never a traced shape or a
jit key, so they join no jit-cache or fleet geometry tuple (the
``host_tier_bytes`` rule).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

#: the neutral class every request gets when no table is configured —
#: rank 0, no SLO targets, no reserved headroom
DEFAULT_CLASS_NAME = "standard"


@dataclass(frozen=True)
class PriorityClass:
    """One tenant tier.

    ``rank`` is the preemption authority (higher outranks lower: an
    admission-starved request of rank r may displace running work of
    rank < r, never >= r). ``ttft_slo_s`` / ``tpot_slo_s`` are the SLO
    targets in seconds (None = no target; a class with no TTFT target
    never triggers preemption). ``reserved_slots`` is admission
    headroom: that many KV slots are held back from other classes so a
    burst of best-effort work can never fully lock this class out of
    the engine."""
    name: str
    rank: int = 0
    ttft_slo_s: Optional[float] = None
    tpot_slo_s: Optional[float] = None
    reserved_slots: int = 0

    def doc(self) -> dict:
        """Debug/banner row — the EFFECTIVE values, spelled in ms like
        the CLI knobs that set them."""
        return {
            "name": self.name,
            "rank": int(self.rank),
            "ttft_slo_ms": (None if self.ttft_slo_s is None
                            else round(self.ttft_slo_s * 1e3, 3)),
            "tpot_slo_ms": (None if self.tpot_slo_s is None
                            else round(self.tpot_slo_s * 1e3, 3)),
            "reserved_slots": int(self.reserved_slots),
        }


class ClassTable:
    """The engine's closed priority-class set.

    ``classes`` is an ordered list of :class:`PriorityClass` with
    unique names; ``default`` names the class an unlabeled request
    (``priority_class=None``) resolves to. ``aging_s`` is the
    anti-starvation quantum: every full ``aging_s`` a request waits in
    the queue raises its EFFECTIVE admission rank by one, so batch
    traffic always drains eventually no matter how steady the
    latency-class arrival stream is (aging moves admission order only —
    preemption authority always uses the true class rank, so an aged
    batch request never starts displacing anyone)."""

    def __init__(self, classes, default=None, aging_s=30.0):
        classes = list(classes)
        if not classes:
            raise ValueError("ClassTable needs at least one class")
        names = [c.name for c in classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate class names in {names}")
        for c in classes:
            for attr in ("ttft_slo_s", "tpot_slo_s"):
                v = getattr(c, attr)
                if v is not None and float(v) <= 0:
                    raise ValueError(
                        f"class {c.name!r}: {attr} must be > 0 or None, "
                        f"got {v}")
            if int(c.reserved_slots) < 0:
                raise ValueError(
                    f"class {c.name!r}: reserved_slots must be >= 0, "
                    f"got {c.reserved_slots}")
        if aging_s is not None and float(aging_s) <= 0:
            raise ValueError(f"aging_s must be > 0 or None, got {aging_s}")
        self.classes = tuple(classes)
        self._by_name = {c.name: c for c in classes}
        default = default if default is not None else classes[-1].name
        if default not in self._by_name:
            raise ValueError(
                f"default class {default!r} not in {sorted(self._by_name)}")
        self.default = default
        self.aging_s = None if aging_s is None else float(aging_s)

    # ------------------------------------------------------- constructors
    @classmethod
    def single(cls) -> "ClassTable":
        """The neutral table: one rank-0 class, no targets — the
        policy-off baseline every engine gets by default."""
        return cls([PriorityClass(DEFAULT_CLASS_NAME)])

    @classmethod
    def coerce(cls, value) -> "ClassTable":
        """Engine-knob coercion: None -> the neutral single-class
        table, a ClassTable passes through, a spec string/list parses
        (the CLI form)."""
        if value is None:
            return cls.single()
        if isinstance(value, cls):
            return value
        return cls.parse(value)

    @classmethod
    def parse(cls, classes, slo_ttft_ms=None, slo_tpot_ms=None,
              aging_s=30.0) -> "ClassTable":
        """Parse the CLI spec (``--classes`` / ``--slo-ttft-ms`` /
        ``--slo-tpot-ms``).

        ``classes`` is a comma list (or sequence) of
        ``name[*][:reserved_slots]`` entries, highest priority FIRST —
        ranks descend with list position. A ``*`` suffix on the name
        marks the default class for unlabeled requests (at most one;
        with no marker the LAST listed — lowest-priority — class is
        the default, so legacy traffic rides best-effort).
        ``slo_ttft_ms`` / ``slo_tpot_ms`` are aligned comma lists (or
        sequences) of per-class targets in milliseconds; 0 (or a
        missing tail entry) means no target for that class.

        Example: ``--classes "latency*:1,standard,batch"
        --slo-ttft-ms 250,1000,0`` — three classes, one slot reserved
        for ``latency``, 250 ms / 1 s TTFT targets on the top two
        tiers, unlabeled requests land on ``latency``.
        """
        if isinstance(classes, str):
            entries = [e.strip() for e in classes.split(",") if e.strip()]
        else:
            entries = [str(e).strip() for e in classes]
        if not entries:
            raise ValueError("--classes names no classes")

        def _targets(spec, what):
            if spec is None:
                return []
            if isinstance(spec, str):
                parts = [p.strip() for p in spec.split(",")]
            else:
                parts = list(spec)
            out = []
            for p in parts:
                v = float(p) if p not in ("", None) else 0.0
                if v < 0:
                    raise ValueError(f"{what} entries must be >= 0 "
                                     f"(0 = no target), got {v}")
                out.append(v / 1e3 if v else None)
            if len(out) > len(entries):
                raise ValueError(
                    f"{what} names {len(out)} targets for "
                    f"{len(entries)} classes")
            return out

        ttft = _targets(slo_ttft_ms, "--slo-ttft-ms")
        tpot = _targets(slo_tpot_ms, "--slo-tpot-ms")
        built, default = [], None
        for i, entry in enumerate(entries):
            name, _, res = entry.partition(":")
            name = name.strip()
            if name.endswith("*"):
                name = name[:-1].strip()
                if default is not None:
                    raise ValueError(
                        f"--classes marks two defaults "
                        f"({default!r} and {name!r})")
                default = name
            if not name or not name.replace("-", "").replace(
                    "_", "").isalnum():
                raise ValueError(f"bad class name {entry!r}")
            built.append(PriorityClass(
                name=name,
                rank=len(entries) - 1 - i,
                ttft_slo_s=ttft[i] if i < len(ttft) else None,
                tpot_slo_s=tpot[i] if i < len(tpot) else None,
                reserved_slots=int(res) if res.strip() else 0))
        return cls(built, default=default, aging_s=aging_s)

    # ------------------------------------------------------------ queries
    @property
    def active(self) -> bool:
        """Whether this table changes ANY scheduling decision: more
        than one class, any SLO target, or any reserved headroom. The
        neutral single-class table is inactive — the engine keeps the
        plain FIFO scheduler and every baseline stays byte-identical."""
        return (len(self.classes) > 1
                or any(c.ttft_slo_s is not None or c.tpot_slo_s is not None
                       or c.reserved_slots for c in self.classes))

    def resolve(self, name) -> PriorityClass:
        """The class for one request's ``priority_class`` (None -> the
        default class). Raises ValueError on an unknown name — the
        submit-time 400, validated on the caller's thread."""
        if name is None:
            return self._by_name[self.default]
        try:
            return self._by_name[str(name)]
        except KeyError:
            raise ValueError(
                f"unknown priority_class {name!r}; this engine serves "
                f"{sorted(self._by_name)}") from None

    def __iter__(self):
        return iter(self.classes)

    def __len__(self):
        return len(self.classes)

    def doc(self) -> list:
        """The EFFECTIVE class table (banner / ``/debug`` surfaces):
        one row per class plus the default marker."""
        return [dict(c.doc(), default=(c.name == self.default))
                for c in self.classes]

    def __repr__(self):
        return (f"ClassTable({[c.name for c in self.classes]}, "
                f"default={self.default!r})")
