"""Preemption victim selection (README "Multi-tenant SLO serving").

When an SLO-urgent request cannot admit (no free KV slot and no
reclaimable headroom), the policy layer displaces running work through
the engine's existing preempt/restore donate-chain path — the PR-7
mechanism that snapshots the PRNG key and re-derives the exact
continuation, so a victim's stream stays byte-identical after it
restores. This module only decides WHO: pure functions of the slot
array, no clock reads, no engine state mutation, so victim choice
replays deterministically under a VirtualClock.

Ordering — (lowest class, most-recently-admitted, least-lost-work):

1. lowest class rank first — batch pays before standard, standard
   before latency, and NOTHING at or above the urgent request's own
   rank is ever a candidate (preemption authority is the true class
   rank, never the aged admission rank);
2. most-recently-admitted first — the newest admission has the least
   decode momentum and, under the aging rule, the most queue patience
   left when it re-queues;
3. least generated tokens first — preemption-by-recompute replays the
   victim's accepted tokens as prefill work, so fewer tokens lost is
   less recompute donated back;
4. highest request_id first — a pure determinism tiebreak.
"""
from __future__ import annotations


def victim_key(seq):
    """Sort key implementing the (lowest class, most-recently-admitted,
    least-lost-work) order; ``min()`` / ``sorted()`` over candidates
    picks the cheapest victim first."""
    pclass = getattr(seq, "pclass", None)
    rank = pclass.rank if pclass is not None else 0
    admitted = seq.t_admitted if seq.t_admitted is not None else 0.0
    return (rank, -admitted, len(seq.tokens), -seq.request_id)


def select_victims(slots, need, below_rank):
    """The ``need`` cheapest preemption victims among running
    sequences of class rank strictly below ``below_rank``.

    ``slots`` is the engine's slot array (None = free). Finished
    sequences are skipped — they release their slot at teardown without
    help. Returns fewer than ``need`` (possibly none) when the running
    set has nothing below the urgent rank: a latency burst can starve
    BEHIND other latency work, and that is correct — equals never
    displace equals, or two urgent requests would thrash each other's
    slots forever."""
    if need <= 0:
        return []
    candidates = []
    for seq in slots:
        if seq is None or seq.done:
            continue
        pclass = getattr(seq, "pclass", None)
        rank = pclass.rank if pclass is not None else 0
        if rank < below_rank:
            candidates.append(seq)
    candidates.sort(key=victim_key)
    return candidates[:need]
