"""Automatic prefix caching: block-granular KV reuse across requests.

The dominant serving pattern is many requests sharing a long system
prompt / few-shot preamble; without reuse every admission re-prefills
that shared prefix from scratch. This module keys published KV blocks by
their *token content* so a new request's admission can skip the device
work for every prompt block some earlier request already computed:

- **Hash-trie**: each node is one ``block_size``-token block, keyed by
  its exact token tuple under its parent (the tuple IS the hash key, so
  a hash collision can never alias different token content — dict
  equality confirms the match). A path root→node spells a prompt prefix.
- **Lookup** walks the trie over a prompt's full blocks and returns the
  longest cached chain — capped so at least the final prompt token is
  always prefilled (the engine needs its logits to sample token 0).
- **Acquire/release**: matched blocks are ref-pinned for the sequence's
  lifetime (a pinned block can't be evicted out from under a later
  publish dedupe); retirement releases the pins.
- **Publish**: on retirement every full *prompt* block not already in
  the trie is copied slot→pool (``kv_cache.copy_block_out``, one jitted
  program) and inserted. Pool pressure evicts LRU zero-ref leaf blocks
  first; if the pool is exhausted by pinned blocks the remaining
  publishes are skipped, never failed — the cache degrades to fewer
  hits, not errors.
- **Copy-on-install (the dense COW discipline)**: on the dense engine a
  hit COPIES its matched blocks into the sequence's private slot
  (``copy_block_in``), so pool blocks are write-once/read-many and two
  sequences sharing a prefix can diverge freely — their decode appends
  land in their own slots. At slot granularity install-copy is the
  aliasing-safe form of COW.
- **Zero-copy install + donation (the paged engine)**: with block-table
  paged attention (:class:`~.kv_cache.PagedKVCache`) a hit installs by
  *referencing* the matched block ids in the sequence's table — no
  device dispatch at all — and N concurrent holders physically share
  one block (refcount = N readers). Divergent continuations are still
  safe: every write lands at a logical row >= the covered prefix, which
  maps to a privately-owned tail block, never a shared one. Retirement
  publishes by :meth:`publish_donate` — full prompt blocks already
  sitting in the sequence's private tail are ADOPTED by the trie
  in place (ownership handoff, no ``copy_block_out``), so the paged
  path runs the whole hit/publish lifecycle with zero copy dispatches.
  Donation covers *generated* full blocks too, not just prompt blocks —
  the decode loop wrote them through the same table into the same
  private tail, so adopting them is equally free, and a multi-turn
  resubmission of an assistant turn hits that turn's own blocks.
  PREEMPTION rides the same path (``engine._preempt``, README "Fault
  tolerance & chaos testing"): a sequence displaced under pool
  pressure donates its written chain exactly like retirement, so its
  recovery-by-recompute readmission is usually a zero-copy hit on its
  own blocks — preempt-by-donation is what makes recompute cheap.

- **Host-RAM spill tier** (``host_tier_bytes > 0``, README "Tiered KV
  prefix cache"): eviction stops meaning deletion. When
  :meth:`PrefixCache._evict_one` drops a zero-ref leaf, its KV block
  (and, on an int8 pool, its scale planes) spills device→host into a
  :class:`HostTier` keyed by the block's full root→node token path,
  under a separate ``host_tier_bytes`` budget with its own LRU. A later
  lookup whose trie walk runs off the resident frontier probes the tier
  for the continuation and streams the spilled chain back h2d — each
  block re-allocated through the same :meth:`BlockManager.alloc` /
  eviction path publishes use, re-linked as a live trie node, and then
  matched exactly like an always-resident block — so acquire/install/
  donate/truncate/preempt/restore never see a difference. The tier also
  speaks digests: every spilled chain is addressable by a content hash
  (:meth:`HostTier.chain_digests`), which is what the fleet cache plane
  uses to move a chain host-to-host from the replica that spilled it to
  the replica about to need it (``fleet/fleet.py``).

Compile discipline: lookups/inserts/evictions are pure host work; the
only device programs are the two block-copy programs (compile-once, see
``kv_cache.py``), the tier fetch/inject pair (compile-once for the same
reason — runtime-scalar block ids, ``kv_cache.tier_compilations``) and
the bucketed suffix prefill (``decode.py``), so the engine's
``decode_compilations() == 1`` contract survives any mix of hits,
misses, evictions, spills, readmissions, and divergence.
"""
from __future__ import annotations

import hashlib
import itertools
import threading

import numpy as np


class HostTier:
    """Host-RAM spill tier: evicted trie blocks' KV as numpy buffers.

    One entry per spilled block, keyed by the block's full root→node
    token path (a tuple of token tuples — the same content identity the
    trie uses, so readmission can never alias different tokens) and
    cross-indexed by a chain digest (sha1 over the path's tokens) for
    the fleet cache plane, where replicas compare chains without
    shipping token streams.

    Own LRU under its own byte budget: inserts stamp a fresh tick and
    evict minimum-tick entries until the tier fits. Evicting an entry
    cascades to its descendants — a spilled block whose parent is
    neither resident in the trie nor present in the tier can never be
    readmitted (readmission extends the trie frontier contiguously), so
    keeping orphans would be dead weight that lies to the byte gauge.

    Thread-safety: unlike the trie (driver-thread-only by engine
    contract), the tier is touched from fleet submit threads too (the
    cache plane exports/admits entries while the owning driver spills
    and readmits), so every method takes the instance lock. Buffers are
    immutable by convention — export hands out references, never
    copies, which is what makes the fleet's host-to-host transfer a
    pointer move within one process."""

    def __init__(self, capacity_bytes):
        self.capacity_bytes = int(capacity_bytes)
        # path -> [bufs, nbytes, tick, digest, shared]
        self._entries = {}
        self._by_digest = {}          # digest -> path
        self._tick = itertools.count(1)
        self._bytes = 0
        self._lock = threading.Lock()
        # staging recycler (BlockManager.recycle_staging): called with
        # a dead entry's buffers UNLESS the entry is shared — an
        # exported entry's buffers are referenced by a sibling tier
        # (the fleet cache plane's pointer-move transfer), so recycling
        # them here would hand the sibling the next spill's bytes
        self.on_recycle = None

    # ------------------------------------------------------------ digests
    @staticmethod
    def chain_digests(keys):
        """One digest per depth of a block-key chain: ``out[i]`` hashes
        ``keys[:i+1]``. Incremental (one pass for every depth) and
        content-only, so two replicas that never exchanged state compute
        identical digests for identical prefixes — the fleet cache
        plane's addressing scheme."""
        h = hashlib.sha1()
        out = []
        for key in keys:
            h.update(np.asarray(key, np.int64).tobytes())
            out.append(h.hexdigest())
        return out

    # ------------------------------------------------------------- access
    def _remove_locked(self, path):
        bufs, nbytes, _, digest, shared = self._entries.pop(path)
        self._bytes -= nbytes
        self._by_digest.pop(digest, None)
        return bufs, nbytes, shared

    def _recycle(self, bufs, shared):
        """Return a dead entry's buffers to the spill staging pool —
        unless a sibling tier still references them (class docstring's
        buffers-are-immutable convention: shared buffers are never
        reused, they just age out)."""
        cb = self.on_recycle
        if cb is not None and not shared:
            cb(bufs)

    def put(self, path, bufs, shared=False) -> int:
        """Insert (or refresh) one spilled block's buffers under
        ``path``; trims the tier back to budget and returns how many
        OTHER entries the trim dropped (the ``tier_evictions`` stat).
        The freshest entry carries the newest tick, so the trim reaps
        cold chains, not the spill that triggered it — unless the entry
        alone exceeds the whole budget, in which case it drops too (the
        tier degrades to empty, never over budget)."""
        path = tuple(path)
        nbytes = sum(int(b.nbytes) for b in bufs.values())
        digest = self.chain_digests(path)[-1]
        dropped = 0
        recycle = []
        with self._lock:
            if path in self._entries:
                old, _, old_shared = self._remove_locked(path)
                if old is not bufs:
                    recycle.append((old, old_shared))
            self._entries[path] = [bufs, nbytes, next(self._tick),
                                   digest, bool(shared)]
            self._by_digest[digest] = path
            self._bytes += nbytes
            while self._bytes > self.capacity_bytes and self._entries:
                victim = min(self._entries.items(),
                             key=lambda kv: kv[1][2])[0]
                # cascade: descendants of the victim become unreachable
                doomed = [p for p in self._entries
                          if p[:len(victim)] == victim]
                for p in doomed:
                    dead, _, dead_shared = self._remove_locked(p)
                    recycle.append((dead, dead_shared))
                    if p != path:
                        dropped += 1
        for dead, dead_shared in recycle:
            self._recycle(dead, dead_shared)
        return dropped

    def pop(self, path):
        """Remove and return ``(bufs, shared)`` for ``path``
        (readmission: the block is going back to HBM; a re-eviction
        re-spills it — ``shared`` must ride along so a degrade re-put
        keeps the sibling-referenced flag), or None."""
        with self._lock:
            if path not in self._entries:
                return None
            bufs, _, shared = self._remove_locked(path)
            return bufs, shared

    def has(self, path) -> bool:
        with self._lock:
            return tuple(path) in self._entries

    def export_digest(self, digest):
        """Fleet cache plane read: ``(path, bufs, nbytes)`` for the
        chain digest, by reference (buffers are immutable), touching
        the LRU tick — a chain siblings keep pulling stays warm. None
        when the digest is unknown (or was just evicted: the plane
        treats that as a miss and stops the transfer)."""
        with self._lock:
            path = self._by_digest.get(digest)
            if path is None:
                return None
            entry = self._entries[path]
            entry[2] = next(self._tick)
            # the export hands out buffer REFERENCES: from here on a
            # sibling tier may hold them, so this entry's buffers can
            # never be recycled into the local staging pool
            entry[4] = True
            return path, entry[0], entry[1]

    # ------------------------------------------------------------- intro
    @property
    def num_blocks(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    def digest_table(self) -> dict:
        """Scrape-style snapshot for ``/fleet/cacheplane``: digest →
        {depth, nbytes}."""
        with self._lock:
            return {e[3]: {"depth": len(p), "nbytes": e[1]}
                    for p, e in self._entries.items()}


class _Node:
    """One cached block: a trie edge keyed by its token tuple."""

    __slots__ = ("tokens", "parent", "children", "block_id", "tick")

    def __init__(self, tokens, parent, block_id):
        self.tokens = tokens        # the block's exact token tuple
        self.parent = parent        # _Node or None (root-level block)
        self.children = {}          # token tuple -> _Node
        self.block_id = block_id    # index into the BlockManager pool
        self.tick = 0               # LRU stamp (updated on touch)


class PrefixCache:
    """Hash-trie over prompt token blocks + LRU eviction policy.

    Owns logical identity and lifecycle; physical blocks live in the
    :class:`~.block_manager.BlockManager` passed in. All methods run on
    the engine-driver thread (the engine is single-threaded by
    contract), so no locks.
    """

    def __init__(self, pool, max_blocks=None, host_tier_bytes=0):
        self.pool = pool
        self.block_size = pool.block_size
        # trie residency budget. On the dense engine the pool IS the
        # budget (publish allocates from it, exhaustion evicts). On the
        # paged engine the pool also backs live KV, so donation enforces
        # this explicit cap instead: adopt first, then evict LRU down to
        # budget. None = bounded by the pool alone.
        self.max_blocks = None if max_blocks is None else int(max_blocks)
        # host-RAM spill tier (README "Tiered KV prefix cache"): 0
        # (default) keeps eviction = deletion, byte-identical to every
        # banked baseline; > 0 turns eviction into a d2h spill and
        # lookup into a possible h2d readmission
        self.host_tier_bytes = int(host_tier_bytes)
        if self.host_tier_bytes < 0:
            raise ValueError(
                f"host_tier_bytes must be >= 0, got {host_tier_bytes}")
        self.tier = (HostTier(self.host_tier_bytes)
                     if self.host_tier_bytes else None)
        if self.tier is not None and hasattr(pool, "recycle_staging"):
            # dead tier entries hand their staging buffers back to the
            # pool's per-shape free lists (one allocation per shape,
            # not per spill)
            self.tier.on_recycle = pool.recycle_staging
        # CostObservatory for the tier ledger — installed by the
        # engine's _co() sync (gateway-owned observatories arrive after
        # construction), read via a local so a concurrent uninstall
        # can't race
        self.cost = None
        self._root = {}              # token tuple -> _Node
        self._nodes = 0              # live trie nodes (== pool.num_used)
        self._tick = itertools.count(1)
        self.stats = {"lookups": 0, "hits": 0, "misses": 0,
                      "hit_blocks": 0, "hit_tokens": 0,
                      "published_blocks": 0, "evictions": 0,
                      "skipped_publishes": 0, "donated_blocks": 0,
                      "spilled_blocks": 0, "tier_hits": 0,
                      "readmitted_blocks": 0, "tier_evictions": 0,
                      "tier_transfers": 0}

    # ------------------------------------------------------------- lookup
    def _blocks_of(self, prompt, max_tokens):
        """Token tuples of the prompt's full blocks within max_tokens."""
        prompt = np.asarray(prompt).reshape(-1)
        bs = self.block_size
        return [tuple(int(t) for t in prompt[i:i + bs])
                for i in range(0, (max_tokens // bs) * bs, bs)]

    def lookup(self, prompt, record=True):
        """Longest cached chain of full prompt blocks, as a list of
        nodes (possibly empty). Never covers the final prompt token —
        the suffix prefill needs at least one token to sample from.
        ``record=False`` is a side-effect-free probe (introspection /
        tests / fleet routing) that leaves hit/miss stats and LRU ticks
        untouched — and never readmits from the host tier (a probe must
        not move bytes)."""
        prompt = np.asarray(prompt).reshape(-1)
        matched = []
        children = self._root
        keys = self._blocks_of(prompt, len(prompt) - 1)
        for key in keys:
            node = children.get(key)
            if node is None:
                break
            matched.append(node)
            children = node.children
        if record and self.tier is not None and len(matched) < len(keys):
            self._readmit(matched, keys)
        if record:
            self.stats["lookups"] += 1
            if matched:
                tick = next(self._tick)   # touch-on-read keeps hot
                for node in matched:      # chains out of LRU's reach
                    node.tick = tick
                self.stats["hits"] += 1
                self.stats["hit_blocks"] += len(matched)
                self.stats["hit_tokens"] += len(matched) * self.block_size
            else:
                self.stats["misses"] += 1
        return matched

    def acquire(self, matched):
        """Pin a lookup's matched chain for a sequence's lifetime."""
        tick = next(self._tick)
        for node in matched:
            self.pool.ref(node.block_id)
            node.tick = tick

    def release(self, matched):
        """Drop a sequence's pins (called exactly once at retirement)."""
        for node in matched:
            self.pool.unref(node.block_id)

    # ---------------------------------------------------- host tier (spill)
    def _path_of(self, node):
        """The node's full root→node token path — its tier key."""
        path = []
        while node is not None:
            path.append(node.tokens)
            node = node.parent
        return tuple(reversed(path))

    def _spill(self, node):
        """Eviction's spill half: copy the doomed block's KV (and scale
        planes) device→host into the tier before the pool id is freed.
        Pure transfer work through the compile-once fetch program —
        no new jit keys — accounted on the tier ledger (``d2h``), never
        the per-program h2d/d2h baselines."""
        bufs = self.pool.read_block(node.block_id)
        self.stats["tier_evictions"] += self.tier.put(
            self._path_of(node), bufs)
        self.stats["spilled_blocks"] += 1
        co = self.cost
        if co is not None:
            co.record_tier(
                "d2h", 1, sum(int(b.nbytes) for b in bufs.values()))

    def _readmit(self, matched, keys):
        """Readmission: the recording-lookup walk ran off the resident
        frontier — stream the spilled continuation back h2d, re-linking
        each block as a live trie node, and extend ``matched`` in place
        so the caller's acquire/install path sees readmitted blocks
        exactly like always-resident ones. Each block re-allocates
        through the same ``pool.alloc()`` + evict-on-demand path
        publishes use (the displaced LRU chains spill in turn), so the
        trie budget is displacement, not growth. Transient pins protect
        the chain being built — and the resident frontier leaf it hangs
        from — against this loop's own evictions; a pool exhausted by
        pins degrades to a partial readmit, never a failure."""
        pinned = []
        frontier = matched[-1] if matched else None
        if frontier is not None:
            # the frontier may be a zero-ref leaf; an eviction pass
            # below must not reap the node we are about to extend
            self.pool.ref(frontier.block_id)
        parent = frontier
        children = parent.children if parent is not None else self._root
        path = tuple(keys[:len(matched)])
        readmitted, nbytes = 0, 0
        try:
            for key in keys[len(matched):]:
                path = path + (key,)
                popped = self.tier.pop(path)
                if popped is None:
                    break
                bufs, buf_shared = popped
                block = self.pool.alloc()
                while block is None and self._evict_one():
                    block = self.pool.alloc()
                if block is None:      # everything pinned: degrade
                    self.tier.put(path, bufs, shared=buf_shared)
                    break
                self.pool.write_block(block, bufs)
                if not buf_shared:
                    # injected: the staging buffers are dead the moment
                    # the h2d completes — recycle_staging fences that
                    self.pool.recycle_staging(bufs)
                node = _Node(key, parent, block)
                node.tick = next(self._tick)
                children[key] = node
                self._nodes += 1
                self.pool.ref(node.block_id)
                pinned.append(node)
                matched.append(node)
                readmitted += 1
                nbytes += sum(int(b.nbytes) for b in bufs.values())
                children, parent = node.children, node
            if readmitted:
                self.stats["tier_hits"] += 1
                self.stats["readmitted_blocks"] += readmitted
                co = self.cost
                if co is not None:
                    co.record_tier("h2d", readmitted, nbytes)
                # trim back to the trie budget while the fresh chain is
                # still pinned: readmission displaces cold chains (which
                # spill in turn), it does not grow residency
                if self.max_blocks is not None:
                    while self._nodes > self.max_blocks \
                            and self._evict_one():
                        pass
        finally:
            for node in pinned:
                self.pool.unref(node.block_id)
            if frontier is not None:
                self.pool.unref(frontier.block_id)
        return matched

    # ------------------------------------------------------------ publish
    def publish(self, prompt, slot, kv_cache):
        """Insert every full prompt block into the trie, copying
        slot→pool for blocks not already cached. Runs at retirement,
        BEFORE the sequence's pins are released, so its own matched
        chain can't be evicted mid-publish. Under pool pressure evicts
        LRU zero-ref leaves; skips (never fails) when nothing is
        evictable."""
        prompt = np.asarray(prompt).reshape(-1)
        bs = self.block_size
        children, parent = self._root, None
        tick = next(self._tick)
        walked = []  # this walk's own chain, pinned against its evictions
        try:
            for i, key in enumerate(self._blocks_of(prompt, len(prompt))):
                node = children.get(key)
                if node is None:
                    block = self.pool.alloc()
                    if block is None and self._evict_one():
                        block = self.pool.alloc()
                    if block is None:  # everything pinned: degrade, not fail
                        self.stats["skipped_publishes"] += 1
                        return
                    kv_cache.copy_block_out(slot, i * bs, self.pool, block)
                    node = _Node(key, parent, block)
                    children[key] = node
                    self._nodes += 1
                    self.stats["published_blocks"] += 1
                node.tick = tick
                # pin the chain-so-far: a later block's eviction pass must
                # never reap an earlier link of the chain being published
                # (it is zero-ref until someone matches it)
                self.pool.ref(node.block_id)
                walked.append(node)
                children, parent = node.children, node
        finally:
            for node in walked:
                self.pool.unref(node.block_id)

    def publish_donate(self, tokens, block_ids):
        """Paged-path publish: insert every full token block by
        ADOPTING the retiring sequence's own pool block — an ownership
        handoff, zero copy dispatches. ``tokens`` is the sequence's
        WRITTEN row content — the prompt plus every generated token
        whose KV actually landed in the cache (the engine caps it at
        the slot's written length), so retirement donates generated
        full blocks too: a multi-turn conversation resubmitting turn
        N's assistant text as part of turn N+1's prompt hits turn N's
        own blocks. ``block_ids`` is the sequence's table in logical
        order (``PagedKVCache.slot_block_ids``); ``block_ids[i]``
        already holds exactly rows [i*bs, (i+1)*bs) because
        prefill/decode wrote through the table.

        Returns the set of adopted block ids — the caller must hand
        their ownership pins to the trie (unref-without-free) instead of
        dropping them. Blocks whose token content is already cached are
        NOT adopted (the existing node wins; the duplicate stays in the
        caller's tail and is freed with it). Needs no allocation, so it
        can never evict, skip, or fail — the paged publish degrades to
        "nothing new to donate", never to lost work."""
        tokens = np.asarray(tokens).reshape(-1)
        children, parent = self._root, None
        tick = next(self._tick)
        walked = []   # transient pins: later links can't outlive earlier
        donated = set()
        try:
            for i, key in enumerate(self._blocks_of(tokens, len(tokens))):
                if i >= len(block_ids):
                    break  # table shorter than the content (cancelled
                    # mid-chunked-prefill); donate what exists
                node = children.get(key)
                if node is None:
                    node = _Node(key, parent, int(block_ids[i]))
                    children[key] = node
                    self._nodes += 1
                    donated.add(int(block_ids[i]))
                    self.stats["published_blocks"] += 1
                    self.stats["donated_blocks"] += 1
                node.tick = tick
                self.pool.ref(node.block_id)
                walked.append(node)
                children, parent = node.children, node
        finally:
            for node in walked:
                self.pool.unref(node.block_id)
        # enforce the trie budget AFTER the walk's pins release: adopt
        # first (the freshest chain carries the newest tick, so LRU
        # reaps older cold chains, not the donation), then trim. Pinned
        # chains (live readers) are never evictable, so residency may
        # transiently exceed the budget under heavy concurrency — it
        # drains back as pins release.
        if self.max_blocks is not None:
            while self._nodes > self.max_blocks and self._evict_one():
                pass
        return donated

    # ----------------------------------------------------------- eviction
    def _iter_nodes(self):
        stack = list(self._root.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def _evict_one(self) -> bool:
        """Evict the LRU (minimum-tick) zero-ref LEAF; False when
        nothing is evictable. Leaves-first keeps every cached chain
        reachable from the root (evicting an interior node would orphan
        its still-resident descendants); the refcount invariant
        ref(parent) >= ref(child) guarantees a zero-ref leaf exists
        whenever any zero-ref node does. One O(trie) min pass per
        eviction — the trie is bounded by the pool (and, on the paged
        engine, the ``max_blocks`` budget). Evictions fire on
        publish-under-pressure (dense), on the post-donation budget trim
        (paged), and on paged decode-growth when live allocation finds
        the pool dry (``PagedKVCache._alloc_block`` — rare while the
        budget holds trie residency under the pool's live headroom).
        """
        node = None
        for n in self._iter_nodes():
            if not n.children and self.pool.refcount(n.block_id) == 0 \
                    and (node is None or n.tick < node.tick):
                node = n
        if node is None:
            return False
        if self.tier is not None:
            self._spill(node)   # eviction = demotion, not deletion
        siblings = (node.parent.children if node.parent is not None
                    else self._root)
        del siblings[node.tokens]
        self.pool.free(node.block_id)
        self._nodes -= 1
        self.stats["evictions"] += 1
        return True

    # -------------------------------------------------------------- intro
    @property
    def num_cached_blocks(self) -> int:
        return self._nodes

    def hit_rate(self) -> float:
        n = self.stats["lookups"]
        return self.stats["hits"] / n if n else 0.0
