"""Request / sequence state for the continuous-batching engine.

A :class:`GenerationRequest` is the immutable user order (prompt +
decoding knobs); a :class:`Sequence` is its mutable in-flight state —
queue position, cache slot, generated tokens, finish reason. The split
mirrors the request/sequence separation in the Orca / vLLM schedulers
(PAPERS.md): the scheduler owns Sequences, users hold Requests.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

_next_request_id = itertools.count()


@dataclass(frozen=True)
class GenerationRequest:
    """One generation order.

    ``prompt`` is a 1-D int array/list of token ids. Sampling is greedy
    when ``temperature <= 0``, else top-k temperature sampling
    (``top_k <= 0`` = no top-k filter). ``eos_token_id`` enables early
    exit; ``None`` always decodes ``max_new_tokens`` tokens. Randomness
    comes from ``seed`` (or ``prng_key`` for callers that manage keys,
    e.g. ``model.generate``'s per-row fold_in); with both unset the
    process-global generator supplies a key at submit time.

    ``timeout_s`` is a wall-clock deadline measured from submit time:
    the engine retires the sequence with ``finish_reason="timeout"`` at
    the first step boundary past it — queued (never admitted) or
    mid-decode (slot freed) alike. ``None`` = no deadline.

    ``priority_class`` names the request's tenant tier (README
    "Multi-tenant SLO serving"): it must resolve against the engine's
    class table at validate time (unknown name = ValueError = HTTP
    400). ``None`` rides the table's default class, so legacy callers
    never change behavior.
    """
    prompt: object
    max_new_tokens: int = 32
    temperature: float = 0.0
    top_k: int = 0
    eos_token_id: Optional[int] = None
    seed: Optional[int] = None
    prng_key: object = None
    timeout_s: Optional[float] = None
    priority_class: Optional[str] = None


#: the closed finish_reason vocabulary (OpenAI-style names): "stop" =
#: EOS hit, "length" = token budget spent, "cancelled" = caller cancel,
#: "timeout" = deadline expired, "error" = the request itself faulted
#: (a poisoned request isolated by the gateway's crash-recovery
#: bisection, or an unrecoverable engine failure) — the ONLY reason
#: under which output may be lost.
FINISH_REASONS = ("stop", "length", "cancelled", "timeout", "error")


class Sequence:
    """In-flight state of one request inside the engine.

    ``tokens`` holds ONLY generated ids (the first entry is the token
    sampled from the prefill logits). ``status`` walks
    queued -> [prefilling ->] running -> finished; ``prefilling`` is the
    chunked-prefill state (README "Chunked prefill"): the sequence holds
    a KV slot and ``prefilled`` prompt rows are installed, but no token
    has been sampled yet — the engine advances it one chunk per step
    until the final chunk's logits produce token 0. Short prompts skip
    the state entirely. ``finish_reason`` is one of
    :data:`FINISH_REASONS`. ``deadline`` is the absolute
    ``time.monotonic()`` instant derived from the request's
    ``timeout_s`` at submit time (``None`` = no deadline).
    """

    __slots__ = ("request", "request_id", "prompt", "tokens", "status",
                 "finish_reason", "slot", "key", "submit_step", "deadline",
                 "prefix_nodes", "prefix_hit_tokens", "prefilled",
                 "work", "restore_point", "queue_tick", "launches",
                 "pclass",
                 "t_submit", "t_admitted", "t_first_token",
                 "t_last_token", "t_finish",
                 "trace_mark", "trace_phase", "trace_chunk_i",
                 "trace_accepts")

    def __init__(self, request: GenerationRequest, key, submit_step=0,
                 deadline=None):
        self.request = request
        self.request_id = next(_next_request_id)
        self.prompt = np.asarray(request.prompt, np.int32).reshape(-1)
        self.tokens = []
        self.status = "queued"
        self.finish_reason = None
        self.slot = None
        self.key = key
        self.submit_step = submit_step
        self.deadline = deadline
        # prefix-cache state: the trie nodes this sequence's admission
        # matched and ref-pinned (released at retirement), and how many
        # prompt tokens they covered (0 = cold prefill)
        self.prefix_nodes = []
        self.prefix_hit_tokens = 0
        # chunked-prefill resume offset: prompt rows whose KV is already
        # installed (cache-hit prefix + completed chunks). Block-aligned
        # by construction while status == "prefilling".
        self.prefilled = 0
        # recovery-by-recompute state (engine.restore): ``work`` is the
        # token content the prefill paths install — the prompt for a
        # fresh sequence, prompt + tokens[:-1] for one recovered after a
        # crash or preemption (its KV is rebuilt by re-prefilling what
        # was already computed; the LAST generated token's KV is never
        # in the cache, so it re-enters as the resumed decode input).
        # ``restore_point`` is len(tokens) at the last restore — 0 means
        # a normal install, > 0 tells _install_seq the first "sampled"
        # token is already known and already streamed.
        self.work = self.prompt
        self.restore_point = 0
        # FIFO seniority stamp, set by FIFOScheduler.submit: the queue
        # position authority when an aborted admission is unwound (the
        # admitted batch is suffix-sorted, so arrival order cannot be
        # reconstructed from it)
        self.queue_tick = None
        # device launches this request has ridden so far (cost
        # attribution, README "Cost attribution & /debug/profile"):
        # +1 per prefill/suffix/chunk/decode/verify device call whose
        # packed rows or slot included this sequence — a shared launch
        # counts once per participating request. Survives preemption
        # and recovery (the recompute launches are real cost, and they
        # are charged too).
        self.launches = 0
        # resolved PriorityClass (policy/classes.py), set by
        # engine.submit from the request's priority_class name (the
        # table default when unnamed); None only for sequences built
        # outside an engine (unit tests), which every reader tolerates
        self.pclass = None
        # SLO latency stamps (engine step_clock basis — injectable, so
        # chaos tests pin them deterministically): submit, FIRST slot
        # claim (kept across preemption/recovery — queue wait measures
        # the original admission), first streamed token, retirement.
        # The derived ttft_s/tpot_s/queue_wait_s properties feed the
        # gateway's serving_tpot_seconds / serving_queue_wait_seconds
        # histograms and the /debug/requests table.
        self.t_submit = None
        self.t_admitted = None
        self.t_first_token = None
        # stamp of the most recently ACCEPTED token (step-quantized
        # like every stamp): /debug/requests derives TPOT-so-far from
        # it instead of a live clock read, so a long multi-tick step
        # shows the last sync's consistent figure rather than a
        # numerator that inflates for n ticks and snaps back
        self.t_last_token = None
        self.t_finish = None
        # request-lifecycle tracing state (profiler/tracing.py): the
        # clock mark the current phase started at, the phase's span
        # name (queued|prefill|decode|preempted|recovered), the chunk
        # index for prefill_chunk[i] spans, and the per-verify-span
        # acceptance lengths a speculative engine collects for the
        # decode span's args. All None/0 cost when tracing is off.
        self.trace_mark = None
        self.trace_phase = "queued"
        self.trace_chunk_i = 0
        self.trace_accepts = []

    # ------------------------------------------------------- SLO latencies
    @property
    def ttft_s(self):
        """Submit-to-first-token seconds (None until the first token)."""
        if self.t_submit is None or self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def queue_wait_s(self):
        """Submit-to-slot-claim seconds (None until admitted)."""
        if self.t_submit is None or self.t_admitted is None:
            return None
        return self.t_admitted - self.t_submit

    @property
    def tpot_s(self):
        """Time-per-output-token: (finish - first token) / (n - 1),
        the steady-state decode cadence this request observed. None
        until finished, or with fewer than two tokens (a one-token
        request has no inter-token gap)."""
        if self.t_first_token is None or self.t_finish is None \
                or len(self.tokens) < 2:
            return None
        return (self.t_finish - self.t_first_token) \
            / (len(self.tokens) - 1)

    @property
    def done(self) -> bool:
        return self.status == "finished"

    @property
    def prompt_len(self) -> int:
        return int(self.prompt.shape[0])

    @property
    def work_len(self) -> int:
        """Length of the prefill work content (== ``prompt_len`` unless
        the sequence was restored for recovery-by-recompute)."""
        return int(self.work.shape[0])

    @property
    def remaining(self) -> int:
        """Decode steps still needed (0 when the budget is spent)."""
        return max(self.request.max_new_tokens - len(self.tokens), 0)

    def output_ids(self) -> np.ndarray:
        return np.asarray(self.tokens, np.int32)

    def __repr__(self):
        return (f"Sequence(id={self.request_id}, status={self.status}, "
                f"slot={self.slot}, generated={len(self.tokens)}/"
                f"{self.request.max_new_tokens})")


class GenerationResult:
    """One finished request's output: the generated ids plus the
    ``finish_reason`` the engine retired it with.

    Array-like on purpose: ``__array__``/``__len__``/``__iter__`` make
    it a drop-in for the bare ``np.ndarray`` that
    ``ContinuousBatchingEngine.generate()`` used to return
    (``np.stack(outs)``, ``np.pad(out, ...)``, ``len(out)`` all keep
    working), while gateways and tests can read ``.finish_reason``.
    """

    __slots__ = ("ids", "finish_reason", "request_id")

    def __init__(self, ids, finish_reason, request_id):
        self.ids = np.asarray(ids, np.int32)
        self.finish_reason = finish_reason
        self.request_id = request_id

    def __array__(self, dtype=None, copy=None):
        return self.ids if dtype is None else self.ids.astype(dtype)

    def __len__(self):
        return len(self.ids)

    def __iter__(self):
        return iter(self.ids)

    def __getitem__(self, i):
        return self.ids[i]

    def tolist(self):
        return self.ids.tolist()

    def __repr__(self):
        return (f"GenerationResult(id={self.request_id}, "
                f"finish_reason={self.finish_reason!r}, "
                f"ids={self.ids.tolist()})")
