"""Admission + step-size policy for the continuous-batching engine.

Orca-style iteration-level scheduling (PAPERS.md): the schedulable unit
is ONE decode step, so a request can join or leave the batch between any
two steps. The FIFO policy here does two jobs:

- **Admission**: pop queued sequences into free cache slots, oldest
  first, at the top of every engine step.
- **Prefill budgeting** (chunked prefill, README "Chunked prefill"):
  sequences whose uncovered prompt exceeds the engine's
  ``prefill_chunk`` enter a PREFILLING pipeline instead of running one
  monopolizing device call; :meth:`FIFOScheduler.prefill_plan` hands
  the engine at most ``budget`` prompt tokens of that backlog per step,
  oldest sequence first, with non-final chunk boundaries aligned to the
  KV block size — so every step still runs the fused decode tick for
  all live slots and no decode batch ever waits behind an entire long
  prompt.
- **Chunk fusion**: when nothing schedulable can change for a while
  (queue empty, no prefill backlog), tell the engine to run several
  decode steps in one fused device call (a ``lax.scan`` inside the
  jitted step) — the largest power of two fitting both ``decode_chunk``
  and every active sequence's remaining budget. This amortizes per-step
  host dispatch (the tunneled-TPU round trip is the expensive part)
  without ever delaying an admission or a pending prefill chunk: any
  queued request or in-flight prefill forces single-stepping.
  The compiled step-size set is bounded at
  ``{1, 2, 4, …, decode_chunk}`` — log2(chunk)+1 programs.

EOS is the one event a fused chunk cannot see coming; a sequence that
hits EOS mid-chunk wastes the chunk's tail tokens (they are computed and
discarded). That is the standard multi-step-scheduling trade — bound it
by keeping ``decode_chunk`` modest, or set it to 1 to disable fusion.
"""
from __future__ import annotations

import itertools
from collections import deque


class FIFOScheduler:
    """First-come-first-served admission; fused chunks when safe."""

    def __init__(self, decode_chunk: int = 8):
        self.decode_chunk = max(int(decode_chunk), 1)
        self.queue = deque()
        self.prefilling = deque()   # admitted, mid-chunked-prefill (FIFO)
        self._plan_carry = 0        # sub-block budget owed to the plan head
        self._intake = itertools.count()  # FIFO seniority stamps

    def submit(self, seq):
        # the tick, not request_id, is the queue-order authority: a
        # sequence re-enqueued for recovery (engine.restore) keeps its
        # old id but arrives at its NEW queue position. Guarded setattr:
        # the scheduler stays duck-typed — unit tests submit plain
        # strings as queue entries, which reject attribute assignment;
        # only real Sequences ever reach the engine's admission unwind,
        # the stamp's one consumer.
        try:
            seq.queue_tick = next(self._intake)
        except AttributeError:
            pass
        self.queue.append(seq)

    @property
    def num_queued(self) -> int:
        return len(self.queue)

    @property
    def num_prefilling(self) -> int:
        return len(self.prefilling)

    # ------------------------------------------------- chunked prefill
    def enter_prefill(self, seq):
        """Admission handed ``seq`` a slot but its uncovered prompt is
        too long for one call: queue it for per-step chunking."""
        self.prefilling.append(seq)

    def leave_prefill(self, seq) -> bool:
        """Drop a sequence from the prefill pipeline (final chunk done,
        cancellation, or deadline expiry). Returns whether it was
        there. An emptied pipeline clears the plan carry eagerly: the
        engine stops calling :meth:`prefill_plan` while nothing is
        prefilling, so without this a sub-block grant banked against a
        cancelled prompt would leak into a LATER unrelated prompt's
        first chunk grant."""
        try:
            self.prefilling.remove(seq)
            if not self.prefilling:
                self._plan_carry = 0
            return True
        except ValueError:
            return False

    def prefill_plan(self, budget: int, align: int = 1, cap=None):
        """This step's chunk assignments: ``[(seq, n_tokens), ...]``,
        oldest PREFILLING sequence first, spending at most ``budget``
        prompt tokens total. A sequence's chunk is capped at its
        remaining uncovered prompt; a NON-final chunk end is rounded
        down to an ``align`` (KV block size) boundary so a partially
        prefilled prompt is always a whole-block prefix plus a host
        resume offset — leftover budget smaller than one block stops
        the plan rather than splitting a block. A grant too small to
        release even one block is not LOST, though: it carries to the
        next step's plan head (capped at one block), so a throttled
        per-step budget — e.g. the engine's headroom-adaptive grant
        under heavy decode load — still accumulates into whole-block
        progress instead of starving the pipeline behind one misaligned
        prompt. Sequences stay queued until :meth:`leave_prefill`; FIFO
        order is never reshuffled, so a long prompt cannot be starved
        by later arrivals. ``cap`` bounds the carried total: the
        engine's packed token buffer (and the chunk compile bucket) is
        sized for at most ``cap`` chunk tokens per step, so a banked
        carry must never push a full-cap grant past it — the carry only
        ever matters when the grant is throttled BELOW the cap."""
        budget = int(budget) + self._plan_carry
        if cap is not None:
            budget = min(budget, int(cap))
        self._plan_carry = 0
        plan = []
        for seq in self.prefilling:
            if budget <= 0:
                break
            # work_len, not prompt_len: a sequence restored for
            # recovery-by-recompute chunks through prompt + generated
            # content (engine.restore), a fresh one through its prompt
            remaining = seq.work_len - seq.prefilled
            n = min(budget, remaining)
            if n < remaining:           # non-final: block-align the cut
                n -= (seq.prefilled + n) % align
                if n <= 0:
                    break
            plan.append((seq, n))
            budget -= n
        if not plan and self.prefilling:
            # blocked head: bank the sub-block grant for the next step
            self._plan_carry = min(budget, int(align))
        return plan

    def spec_grants(self, wants, budget):
        """Per-slot DRAFT-token grants for a speculative verify step
        (README "Speculative decoding"): each running slot's verify
        span spends ``1 + grant`` positions of the step's packed token
        buffer, and the drafts share that buffer's headroom with the
        prefill-chunk grant — ``budget`` is whatever the chunk plan
        left. Greedy in the given order (the engine passes slot order:
        deterministic, stable across steps, so acceptance statistics
        are never reshuffled by admission churn); each grant is capped
        at its row's request. Returns a list aligned with ``wants``.
        """
        b = max(int(budget), 0)
        grants = []
        for want in wants:
            g = min(max(int(want), 0), b)
            grants.append(g)
            b -= g
        return grants

    def admissions(self, num_free: int, hit_len_fn=None):
        """Sequences to admit this step (pops up to ``num_free``).

        ``hit_len_fn(seq) -> int`` makes admission prefix-cache-aware:
        it is THE admission-time prefix lookup — the engine's hook
        records the hit, pins the matched chain (so nothing this step
        does can evict it before install), and returns the covered
        token count, which lands on ``seq.prefix_hit_tokens``. The
        admitted SET stays strictly the FIFO head (fairness — a hit
        never jumps a colder request's place in line); the batch is
        then ordered by ascending uncovered-suffix length, which keeps
        slot assignment and admission bookkeeping deterministic under
        any hit mix (device-call count is unchanged — the engine
        buckets either way). The sort is stable, so equal-suffix
        sequences keep FIFO order.
        """
        out = []
        while self.queue and len(out) < num_free:
            out.append(self.queue.popleft())
        if hit_len_fn is not None:
            for seq in out:
                seq.prefix_hit_tokens = int(hit_len_fn(seq))
            if len(out) > 1:
                # work_len, not prompt_len: the hit is measured against
                # the prefill work content, which for a restored
                # sequence includes its generated tokens
                out.sort(key=lambda s: s.work_len - s.prefix_hit_tokens)
        return out

    def remove(self, seq) -> bool:
        """Drop a still-queued sequence (cancellation / deadline expiry
        before admission). Returns whether it was found."""
        try:
            self.queue.remove(seq)
            return True
        except ValueError:
            return False

    def requeue_front(self, seq):
        """Put an admission-aborted sequence back at the queue HEAD
        (the engine's PoolExhausted repair path): it was popped this
        step but never installed, so restoring its FIFO position keeps
        admission order deterministic under preemption retries."""
        self.queue.appendleft(seq)

    def choose_decode_ticks(self, active_seqs, max_ticks: int) -> int:
        """How many on-device decode ticks the next MULTI-TICK step
        should fuse behind one host sync (engine ``decode_ticks > 1``,
        README "Multi-tick decode"). Unlike :meth:`choose_num_steps`,
        the program's tick count is a RUNTIME argument with per-slot
        EOS/budget retirement masked on device, so the choice is pure
        latency policy — no compile set to bound, no per-slot budget
        clamp needed:

        - **mixed traffic** (prefill backlog) clamps to 1: fusing n
          ticks would delay the next prompt chunk by n-1 ticks, the
          TTFT head-of-line blocking chunking exists to remove;
        - **waiting queue** shrinks n to the smallest active remaining
          budget: the earliest GUARANTEED retirement then lands exactly
          on a sync boundary, so a waiting request's admission is never
          pushed past a slot's known budget cut (an early EOS inside
          the block remains the standard multi-step trade — the device
          masks its cost, the host sees it at the sync);
        - otherwise n runs to the LARGEST active remaining budget
          (capped at ``max_ticks``): near-finished rows retire
          on-device mid-block instead of shrinking the block for
          everyone — the whole point of the alive mask.
        """
        if max_ticks <= 1 or self.prefilling or not active_seqs:
            return 1
        horizon = (min if self.queue else max)(
            s.remaining for s in active_seqs)
        return max(1, min(int(max_ticks), horizon))

    def choose_num_steps(self, active_seqs) -> int:
        """How many decode steps to fuse into the next device call:
        the largest power of two that fits both ``decode_chunk`` and
        every active sequence's remaining budget. Powers of two keep the
        compiled step-size set bounded (⊆ {1, 2, 4, …, decode_chunk})
        while letting a near-finished batch still fuse most of its tail
        instead of falling back to single-stepping. EOS-enabled
        sequences may finish early inside a chunk (tail discarded).
        In-flight chunked prefills also force single-stepping: fusing n
        decode ticks would delay the next prompt chunk by n-1 ticks,
        exactly the TTFT head-of-line blocking chunking exists to
        remove."""
        if self.decode_chunk == 1 or self.queue or self.prefilling \
                or not active_seqs:
            return 1
        m = min(s.remaining for s in active_seqs)
        n = 1
        while n * 2 <= min(m, self.decode_chunk):
            n *= 2
        return n
