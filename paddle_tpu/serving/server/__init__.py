"""Async serving gateway: streaming HTTP front-end over the
continuous-batching engine.

Two layers (both stdlib-only):

- :mod:`.gateway` — :class:`ServingGateway`, the engine-driver thread
  plus a thread-safe front door handing back per-token
  :class:`TokenStream` iterators, with cancellation, deadlines,
  bounded-queue admission control, and graceful drain;
- :mod:`.httpd` — :class:`ServingHTTPServer` / :func:`serve`, the
  OpenAI-style HTTP surface (``POST /v1/completions`` blocking + SSE,
  ``GET /healthz``, ``GET /metrics`` in Prometheus text format, and
  the debug surface: ``GET /debug/trace?steps=N`` Chrome-trace
  capture + ``GET /debug/requests`` live request table — README
  "Tracing & debugging").

Run one with ``python -m paddle_tpu.serving.server`` (or
``scripts/serve.py``). ``--replicas N`` / :func:`serve_fleet` fronts N
shared-nothing engine replicas behind the same surface (README "Engine
fleet"): routed admissions, ``replica``-labeled metrics,
``GET /debug/fleet``, ``POST /fleet/drain|rebalance``, and
failover-to-sibling on replica death.
"""
from .gateway import (GatewayClosedError, QueueFullError, ServingGateway,
                      TokenStream, TraceBusyError, WatchdogTimeout)
from .httpd import ServingHTTPServer, serve, serve_fleet

__all__ = [
    "ServingGateway", "TokenStream", "QueueFullError",
    "GatewayClosedError", "WatchdogTimeout", "TraceBusyError",
    "ServingHTTPServer", "serve", "serve_fleet",
]
