"""CLI entry point: ``python -m paddle_tpu.serving.server``.

Stands up a LLaMA-family model behind the async gateway and serves
OpenAI-style completions over HTTP until SIGINT/SIGTERM, then drains
gracefully (in-flight requests finish; new ones get 503).

The ``tiny`` preset is the CPU-runnable smoke config; ``350m`` is the
bench-sized model for real chips. Prompts are token-id arrays (the
framework ships no tokenizer) — see README "Serving over HTTP" for
curl examples.
"""
from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def build_model(preset, decode_attention, seed):
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         llama_tiny)
    paddle.seed(seed)
    if preset == "tiny":
        return LlamaForCausalLM(llama_tiny(decode_attention=decode_attention))
    if preset == "350m":
        return LlamaForCausalLM(LlamaConfig(
            vocab_size=32000, hidden_size=1024, intermediate_size=2816,
            num_hidden_layers=24, num_attention_heads=16,
            num_key_value_heads=16, max_position_embeddings=2048,
            dtype="bfloat16", decode_attention=decode_attention))
    raise ValueError(f"unknown preset {preset!r}")


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.server",
        description="Streaming HTTP serving gateway over the "
                    "continuous-batching engine.")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8000,
                    help="0 = ephemeral (printed at startup)")
    ap.add_argument("--preset", choices=("tiny", "350m"), default="tiny")
    ap.add_argument("--decode-attention", choices=("pallas", "jnp"),
                    default="jnp",
                    help="ragged Pallas decode kernel or the jnp oracle")
    ap.add_argument("--replicas", type=int, default=1,
                    help="engine fleet size (README 'Engine fleet'): "
                         ">1 fronts N shared-nothing engine replicas "
                         "behind one routed gateway — per-replica "
                         "paged pool/prefix trie/supervisor, compiled "
                         "programs shared per pool geometry, "
                         "replica-labeled /metrics, /debug/fleet, "
                         "POST /fleet/drain|rebalance, and failover-"
                         "to-sibling on replica death")
    ap.add_argument("--router",
                    choices=("round-robin", "least-loaded", "affinity",
                             "class-headroom"),
                    default="affinity",
                    help="fleet routing policy (--replicas > 1): "
                         "round-robin, least-loaded (live KV blocks + "
                         "queue depth), affinity (longest cached-"
                         "prefix match within a load band; the "
                         "default), or class-headroom (lowest "
                         "non-displaceable class pressure for the "
                         "request's priority class — pair with "
                         "--classes)")
    ap.add_argument("--affinity-band", type=int, default=16,
                    help="affinity router's load band (KV blocks + "
                         "queued requests): replicas loaded more than "
                         "this past the minimum are skipped no matter "
                         "how warm their trie is")
    ap.add_argument("--num-slots", default="8",
                    help="KV slots per engine; with --replicas > 1 a "
                         "comma list gives each replica its own value "
                         "(e.g. 8,4 — differing pool geometries keep "
                         "isolated jit caches)")
    ap.add_argument("--max-seq-len", type=int, default=None)
    ap.add_argument("--decode-chunk", type=int, default=1,
                    help=">1 fuses decode ticks (adds streaming latency)")
    ap.add_argument("--max-queue", type=int, default=64,
                    help="waiting-room bound before 429s")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="automatic prefix caching: reuse KV blocks of "
                         "shared prompt prefixes across requests")
    ap.add_argument("--prefix-blocks", type=int, default=None,
                    help="prefix-cache pool size in blocks (default: "
                         "num_slots * max_seq_len / block_size)")
    ap.add_argument("--prefix-block-size", type=int, default=32,
                    help="tokens per cached KV block")
    ap.add_argument("--host-tier-bytes", type=int, default=0,
                    help="host-RAM spill tier behind the prefix trie, in "
                         "bytes (0 disables; needs --prefix-cache): "
                         "evicted chains spill d2h and readmit on a hit; "
                         "with --replicas the per-replica tiers form the "
                         "fleet cache plane (/fleet/cacheplane)")
    ap.add_argument("--paged-attn", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="block-table paged attention (DEFAULT: the block "
                         "pool IS the KV cache, prefix hits install "
                         "zero-copy and concurrent holders share physical "
                         "blocks); --no-paged-attn selects the legacy "
                         "dense per-slot cache")
    ap.add_argument("--prefill-chunk", type=int, default=512,
                    help="chunked prefill: max prompt tokens prefilled "
                         "per engine step (paged engine only; bounds TTFT "
                         "under mixed traffic; 0 disables)")
    ap.add_argument("--ragged-step", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="unified ragged step (DEFAULT, paged only): "
                         "decode rows + prefill chunks ride ONE device "
                         "program per step; --no-ragged-step keeps the "
                         "two-program chunk+decode interleave")
    ap.add_argument("--headroom-mult", type=float, default=2.0,
                    help="adaptive chunk budget: grant ~this many "
                         "decode-steps' worth of measured throughput to "
                         "prefill chunks per step (unified step only; "
                         "0 pins the fixed prefill-chunk cap)")
    ap.add_argument("--decode-ticks", type=int, default=1,
                    help="multi-tick decode (unified ragged engine "
                         "only): fuse up to this many on-device decode "
                         "ticks behind ONE host sync when every "
                         "running slot is in pure decode — EOS/budget "
                         "cuts are masked on device, streams stay "
                         "byte-identical, and the host round-trip "
                         "amortizes n-fold (tokens stream in bursts "
                         "of up to n). Mixed traffic clamps back to "
                         "single-tick. 1 = off (the baseline)")
    ap.add_argument("--kv-dtype", choices=("pool", "int8", "fp8"),
                    default="pool",
                    help="KV cache storage dtype (README 'Quantized "
                         "serving'): 'pool' stores at the model dtype "
                         "(the default — every banked baseline), "
                         "'int8' serves from the block-quantized pool "
                         "(unified ragged paged engine only; appends "
                         "quantize on write, the attention kernels "
                         "upcast in-register after the table-indirect "
                         "DMA, ~4x pool HBM cut vs fp32 = ~4x "
                         "concurrent slots at a fixed budget), 'fp8' "
                         "stores float8_e4m3fn with per-BLOCK scale "
                         "planes — fewer scale bytes per cached token "
                         "than int8's per-row planes and no quantize "
                         "arithmetic on the append path")
    ap.add_argument("--quantize-weights",
                    action=argparse.BooleanOptionalAction, default=False,
                    help="int8 weight-only decode matmuls: convert the "
                         "decode-path projection weights once at engine "
                         "build (per-channel absmax scales, dequant "
                         "fused into the matmul) — weight HBM traffic "
                         "drops ~4x vs fp32 at a measured-not-assumed "
                         "quality cost")
    ap.add_argument("--quantize-activations",
                    action=argparse.BooleanOptionalAction, default=False,
                    help="int8xint8 decode projections (requires "
                         "--quantize-weights; unified ragged paged "
                         "engine only): quantize each projection input "
                         "per-row at runtime and contract int8 against "
                         "the int8 weights with int32 accumulate — the "
                         "per-layer weight dequant disappears from the "
                         "decode step entirely (greedy divergence "
                         "measured in DENSITY_BENCH.json, not assumed)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree (README 'Tensor-"
                         "parallel serving'): shard every serving "
                         "program over this many devices on a heads-"
                         "sharded mesh with the paged KV pool "
                         "partitioned per shard (unified ragged paged "
                         "engine only; must divide the model's head "
                         "counts). On CPU set XLA_FLAGS="
                         "--xla_force_host_platform_device_count=N "
                         "before launch. 1 = single-chip (the "
                         "baseline)")
    ap.add_argument("--collective-dtype", choices=("fp", "int8"),
                    default="fp",
                    help="wire dtype of the per-layer tensor-parallel "
                         "all-reduce: 'fp' is a plain psum, 'int8' "
                         "runs it EQuARX-style block-quantized (~3.5x "
                         "fewer cross-chip bytes; greedy divergence "
                         "measured in TP_BENCH.json, not assumed). "
                         "Ignored (no collectives) at --tp 1")
    ap.add_argument("--fused-tick", action=argparse.BooleanOptionalAction,
                    default=False,
                    help="one-kernel decode (unified ragged paged "
                         "engine only; README 'One-kernel decode'): "
                         "run the decode tick's entire layer stack as "
                         "ONE Pallas program with the layer loop as "
                         "the grid dimension — a tick is O(1) device "
                         "launches instead of O(layers), streams stay "
                         "byte-identical, and the jaxpr launch census "
                         "on GET /debug/profile pins the count. "
                         "Composes with --decode-ticks (the fused "
                         "program is the multi-tick body)")
    ap.add_argument("--collective-overlap",
                    action=argparse.BooleanOptionalAction, default=False,
                    help="TP compute/collective overlap (requires "
                         "--tp > 1): the per-layer all-reduce pair "
                         "runs a chunked reduce-scatter/all-gather "
                         "schedule interleaved with the next "
                         "projection's compute — wire format (incl. "
                         "EQuARX int8) and the collective-bytes "
                         "ledger stay exact, streams byte-identical")
    ap.add_argument("--spec-decode", action=argparse.BooleanOptionalAction,
                    default=False,
                    help="speculative multi-token decode (paged only): "
                         "a prompt-lookup n-gram drafter proposes up to "
                         "--spec-k tokens per slot, one ragged-span "
                         "verify scores them, rejected KV rolls back by "
                         "block-tail truncation; streams byte-identical "
                         "to speculation off")
    ap.add_argument("--spec-k", type=int, default=4,
                    help="max draft tokens per verify span")
    ap.add_argument("--trace", action=argparse.BooleanOptionalAction,
                    default=False,
                    help="record request-lifecycle/step-phase tracing "
                         "from startup into the ring buffer (read it "
                         "back with GET /debug/trace?steps=0); off = "
                         "zero-cost until /debug/trace?steps=N opens a "
                         "capture window")
    ap.add_argument("--trace-buffer", type=int, default=65536,
                    help="trace ring-buffer capacity in events (oldest "
                         "dropped past it)")
    ap.add_argument("--cost", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="device-boundary cost observatory (exact "
                         "dispatch/transfer/compile accounting behind "
                         "GET /debug/profile and the "
                         "serving_dispatches_total metrics); --no-cost "
                         "reduces every cost site to one attribute "
                         "check")
    ap.add_argument("--watchdog-deadline", type=float, default=30.0,
                    help="supervised driver: a step slower than this "
                         "(seconds) is classified hung and the engine is "
                         "rebuilt with in-flight requests recovered by "
                         "recompute (0 disables the watchdog)")
    ap.add_argument("--max-restarts", type=int, default=8,
                    help="engine rebuild budget after fatal/hung step "
                         "faults before the gateway gives up (0 disables "
                         "crash recovery)")
    ap.add_argument("--classes", default=None,
                    help="multi-tenant SLO priority classes (README "
                         "'Multi-tenant SLO serving'): comma list of "
                         "name[*][:reserved_slots], highest priority "
                         "first — e.g. 'latency:1,standard,batch*'. "
                         "'*' marks the default class for unlabeled "
                         "requests (else the last listed). Requests "
                         "pick a tier via the priority_class body "
                         "field or X-Priority-Class header; unknown "
                         "names 400. Default: one neutral class "
                         "(policy off, FIFO baseline)")
    ap.add_argument("--slo-ttft-ms", default=None,
                    help="per-class TTFT SLO targets in ms, aligned "
                         "with --classes (comma list; 0 or a missing "
                         "tail entry = no target). An urgent waiter "
                         "past half its target preempts strictly-"
                         "lower-class running work by recompute")
    ap.add_argument("--slo-tpot-ms", default=None,
                    help="per-class TPOT SLO targets in ms, aligned "
                         "with --classes (comma list; 0 = no target). "
                         "Observed per finished request into "
                         "serving_slo_misses_total{class,slo='tpot'}")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--quiet", action="store_true",
                    help="suppress per-request access logs")
    args = ap.parse_args(argv)

    from .httpd import serve, serve_fleet
    try:
        slots = [int(s) for s in str(args.num_slots).split(",")
                 if s.strip()]
    except ValueError:
        ap.error(f"--num-slots must be an int or a comma list of ints, "
                 f"got {args.num_slots!r}")
    if not slots:
        ap.error(f"--num-slots must name at least one value, "
                 f"got {args.num_slots!r}")
    if len(slots) > 1 and args.replicas <= 1:
        ap.error("--num-slots with a comma list needs --replicas > 1 "
                 "(one value per replica)")
    if len(slots) > 1 and len(slots) != args.replicas:
        ap.error(f"--num-slots names {len(slots)} values for "
                 f"--replicas {args.replicas}")
    model = build_model(args.preset, args.decode_attention, args.seed)
    kv_dtype = None if args.kv_dtype == "pool" else args.kv_dtype
    if args.replicas > 1:
        num_slots = slots if len(slots) > 1 else slots[0]
        server = serve_fleet(
            model, replicas=args.replicas, router=args.router,
            affinity_band=args.affinity_band,
            host=args.host, port=args.port, num_slots=num_slots,
            max_seq_len=args.max_seq_len, decode_chunk=args.decode_chunk,
            max_queue=args.max_queue, model_name=f"llama-{args.preset}",
            prefix_cache=args.prefix_cache,
            prefix_blocks=args.prefix_blocks,
            prefix_block_size=args.prefix_block_size,
            host_tier_bytes=args.host_tier_bytes,
            paged_attn=args.paged_attn, prefill_chunk=args.prefill_chunk,
            ragged_step=args.ragged_step,
            headroom_mult=args.headroom_mult or None,
            spec_decode=args.spec_decode, spec_k=args.spec_k,
            decode_ticks=args.decode_ticks, kv_dtype=kv_dtype,
            quantize_weights=args.quantize_weights,
            quantize_activations=args.quantize_activations,
            tp=args.tp, collective_dtype=args.collective_dtype,
            fused_tick=args.fused_tick,
            collective_overlap=args.collective_overlap,
            classes=args.classes, slo_ttft_ms=args.slo_ttft_ms,
            slo_tpot_ms=args.slo_tpot_ms,
            trace=args.trace, trace_buffer=args.trace_buffer,
            cost=args.cost,
            watchdog_deadline_s=args.watchdog_deadline or None,
            max_restarts=args.max_restarts,
            log_fn=None if args.quiet else
            (lambda m: print(m, file=sys.stderr)))
        fleet = server.fleet
        print(json.dumps({
            "listening": server.url, "preset": args.preset,
            "replicas": len(fleet.replicas),
            "router": fleet.router.name,
            "num_slots": [r.gateway.engine.num_slots
                          for r in fleet.replicas],
            "prefix_cache": bool(args.prefix_cache),
            "paged_attn": bool(args.paged_attn),
            "prefill_chunk": [r.gateway.engine.prefill_chunk
                              for r in fleet.replicas],
            "spec_decode": fleet.replicas[0].gateway.engine.spec_decode,
            "decode_ticks":
                fleet.replicas[0].gateway.engine.decode_ticks,
            # effective-value idiom: the engines' actual storage dtype
            # and weight mode, not the flag spelling
            "kv_dtype": fleet.replicas[0].gateway.engine.kv_dtype,
            "quantize_weights":
                fleet.replicas[0].gateway.engine.quantize_weights,
            "quantize_activations":
                fleet.replicas[0].gateway.engine.quantize_activations,
            # effective-value idiom: the engines' ACTUAL mesh shape
            # (devices per replica on the "tp" axis) and the wire
            # dtype their per-layer all-reduce really runs
            "tp": fleet.replicas[0].gateway.engine.tp,
            "mesh_shape":
                {"tp": fleet.replicas[0].gateway.engine.tp},
            "collective_dtype":
                fleet.replicas[0].gateway.engine.collective_dtype,
            # effective-value idiom: whether the engines' decode tick
            # really runs the one-kernel program / overlap schedule
            "fused_tick": fleet.replicas[0].gateway.engine.fused_tick,
            "collective_overlap":
                fleet.replicas[0].gateway.engine.collective_overlap,
            # effective-value idiom: the parsed class table the fleet's
            # engines actually schedule with (ranks, ms targets,
            # reserved headroom, the default marker) — not the flag
            # spelling
            "classes": fleet.classes.doc(),
            "trace": fleet.tracer.enabled,
            "cost": fleet.replicas[0].gateway.cost is not None,
            "endpoints": ["/v1/completions", "/healthz", "/metrics",
                          "/debug/trace", "/debug/requests",
                          "/debug/profile", "/debug/fleet",
                          "/fleet/drain", "/fleet/rebalance",
                          "/fleet/cacheplane"]}),
            flush=True)
        stop = threading.Event()
        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, lambda *_: stop.set())
        stop.wait()
        print("# draining fleet...", file=sys.stderr)
        server.shutdown(drain=True, timeout=60)
        print("# stopped", file=sys.stderr)
        return 0
    server = serve(
        model, host=args.host, port=args.port, num_slots=slots[0],
        max_seq_len=args.max_seq_len, decode_chunk=args.decode_chunk,
        max_queue=args.max_queue, model_name=f"llama-{args.preset}",
        prefix_cache=args.prefix_cache, prefix_blocks=args.prefix_blocks,
        prefix_block_size=args.prefix_block_size,
        host_tier_bytes=args.host_tier_bytes,
        paged_attn=args.paged_attn, prefill_chunk=args.prefill_chunk,
        ragged_step=args.ragged_step,
        headroom_mult=args.headroom_mult or None,
        spec_decode=args.spec_decode, spec_k=args.spec_k,
        decode_ticks=args.decode_ticks, kv_dtype=kv_dtype,
        quantize_weights=args.quantize_weights,
        quantize_activations=args.quantize_activations,
        tp=args.tp, collective_dtype=args.collective_dtype,
        fused_tick=args.fused_tick,
        collective_overlap=args.collective_overlap,
        classes=args.classes, slo_ttft_ms=args.slo_ttft_ms,
        slo_tpot_ms=args.slo_tpot_ms,
        trace=args.trace, trace_buffer=args.trace_buffer,
        cost=args.cost,
        watchdog_deadline_s=args.watchdog_deadline or None,
        max_restarts=args.max_restarts,
        log_fn=None if args.quiet else
        (lambda m: print(m, file=sys.stderr)))
    print(json.dumps({"listening": server.url, "preset": args.preset,
                      "num_slots": slots[0],
                      "prefix_cache": bool(args.prefix_cache),
                      "paged_attn": bool(args.paged_attn),
                      # report what actually runs: the engine's
                      # block-rounded chunk, 0 when chunking is off or
                      # the dense engine ignores it
                      "prefill_chunk":
                      server.gateway.engine.prefill_chunk,
                      # report what actually runs: the dense engine
                      # ignores --ragged-step
                      "ragged_step": server.gateway.engine.ragged_step,
                      "spec_decode": server.gateway.engine.spec_decode,
                      "spec_k": server.gateway.engine.spec_k,
                      # report what actually runs: the engine's
                      # effective multi-tick fuse depth (1 = off)
                      "decode_ticks": server.gateway.engine.decode_ticks,
                      # effective-value idiom: the engine's actual KV
                      # storage dtype ("int8" or the pool array dtype)
                      # and whether decode weights really run int8
                      "kv_dtype": server.gateway.engine.kv_dtype,
                      "quantize_weights":
                      server.gateway.engine.quantize_weights,
                      "quantize_activations":
                      server.gateway.engine.quantize_activations,
                      # effective-value idiom: the EFFECTIVE mesh
                      # shape (the "tp" axis the programs actually
                      # shard over; 1 = no mesh) and the wire dtype
                      # of the per-layer all-reduce
                      "tp": server.gateway.engine.tp,
                      "mesh_shape": {"tp": server.gateway.engine.tp},
                      "collective_dtype":
                      server.gateway.engine.collective_dtype,
                      # effective-value idiom: whether the decode tick
                      # really runs the one-kernel program / overlap
                      # schedule (README "One-kernel decode")
                      "fused_tick": server.gateway.engine.fused_tick,
                      "collective_overlap":
                      server.gateway.engine.collective_overlap,
                      # effective-value idiom: the EFFECTIVE class
                      # table the engine schedules with (parsed ranks,
                      # ms targets, reserved headroom, default marker)
                      "classes": server.gateway.engine.classes.doc(),
                      # report what actually runs: whether the tracer
                      # is RECORDING now (the persistent --trace mode)
                      # and the effective ring capacity
                      "trace": server.gateway.tracer.enabled,
                      "trace_buffer": server.gateway.tracer.capacity,
                      # effective-value idiom: whether the cost
                      # observatory is actually accounting
                      "cost": server.gateway.cost is not None,
                      "watchdog_deadline_s":
                      server.gateway.watchdog_deadline_s,
                      "max_restarts": server.gateway.max_restarts,
                      "endpoints": ["/v1/completions", "/healthz",
                                    "/metrics", "/debug/trace",
                                    "/debug/requests",
                                    "/debug/profile"]}), flush=True)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    print("# draining...", file=sys.stderr)
    server.shutdown(drain=True, timeout=60)
    print("# stopped", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
