"""Async request gateway over the continuous-batching engine.

The engine is single-threaded by contract (``step()`` mutates slot
state, host length mirrors, and jitted-program caches with no locks).
This module makes it servable without breaking that contract: ONE
driver thread owns the engine and pumps ``step()``; every other thread
talks to the gateway through a thread-safe front door —

- :meth:`ServingGateway.submit` enqueues a request from any thread and
  hands back a :class:`TokenStream`, a per-token iterator fed by the
  engine's ``on_token`` callback the moment each token reaches the
  host;
- :meth:`TokenStream.cancel` flags a request from any thread; the
  driver applies it between steps via ``engine.cancel`` — the KV slot
  frees mid-decode and the ragged decode kernel skips it from the next
  step on, so cancellation costs nothing;
- admission control is a bounded waiting-room: submissions past
  ``max_queue`` raise :class:`QueueFullError` (the HTTP layer's 429)
  instead of growing an unbounded backlog;
- :meth:`ServingGateway.shutdown` drains gracefully — the front door
  closes, in-flight sequences run to completion, then the driver
  exits (or ``drain=False`` cancels everything in flight).

Deadlines ride on the engine itself (``GenerationRequest.timeout_s``,
checked at step boundaries), so a request expires whether it is queued
or mid-decode, and the gateway just observes the ``"timeout"`` finish.

The compile-once property survives serving: the gateway adds no
device-side work, so ``decode_compilations()`` stays at one per
``(num_slots, max_seq_len, n_steps)`` no matter the HTTP traffic mix —
pinned by tests/test_serving_server.py.
"""
from __future__ import annotations

import atexit
import collections
import itertools
import queue
import threading
import time
import weakref

import numpy as np

from ...profiler.metrics import STEP_BUCKETS, TTFT_BUCKETS, MetricsRegistry


class QueueFullError(RuntimeError):
    """Waiting room at capacity — shed load (HTTP 429)."""


class GatewayClosedError(RuntimeError):
    """Gateway is draining or stopped — no new work (HTTP 503)."""


class TokenStream:
    """Live handle for one submitted request.

    Iterating yields generated token ids as the engine produces them and
    stops when the sequence finishes; ``finish_reason`` is set by then.
    ``result()`` drains to completion and returns
    ``(ids, finish_reason)``. Both are safe from any single consumer
    thread; ``cancel()`` is safe from any thread.
    """

    def __init__(self, gateway, request, stream_id):
        self.gateway = gateway
        self.request = request
        self.id = stream_id
        self.finish_reason = None
        self.seq = None            # set by the driver at engine-submit
        self.submit_time = time.monotonic()
        self.first_token_time = None
        self.finish_time = None
        self._events = queue.SimpleQueue()  # ("token", id) | ("finish", r) | ("error", msg)
        self._collected = []
        self._cancel = False
        self._waiting = True       # still counted against max_queue
        self._drained = False      # consumer saw the finish event

    # ------------------------------------------------------- consumer side
    def __iter__(self):
        # event-driven on purpose: the driver sets finish_reason BEFORE
        # queueing the finish event, so gating on finish_reason here
        # would drop still-queued tokens of a finished stream
        while not self._drained:
            kind, payload = self._events.get()
            if kind == "token":
                self._collected.append(payload)
                yield payload
            elif kind == "finish":
                self._drained = True
            else:
                self._drained = True
                raise RuntimeError(payload)

    def result(self):
        """Block until the sequence finishes; return
        ``(np.int32 ids, finish_reason)``."""
        for _ in self:
            pass
        return np.asarray(self._collected, np.int32), self.finish_reason

    def tokens(self):
        """Tokens consumed so far (complete after ``result()`` /
        exhausting the iterator)."""
        return list(self._collected)

    @property
    def done(self):
        """Finished engine-side (tokens may still await consumption)."""
        return self.finish_reason is not None

    def cancel(self):
        """Request cancellation (idempotent, any thread). The driver
        applies it between engine steps."""
        self._cancel = True
        self.gateway._wake.set()

    # --------------------------------------------------------- driver side
    def _push_token(self, token):
        self._events.put(("token", int(token)))

    def _push_finish(self, reason):
        self.finish_time = time.monotonic()
        self.finish_reason = reason
        self._events.put(("finish", reason))

    def _push_error(self, msg):
        self.finish_time = time.monotonic()
        self.finish_reason = "error"
        self._events.put(("error", str(msg)))


class _RateWindow:
    """Sliding-window event rate (the tokens/s gauge): O(1) record via a
    deque of (second-bucket, count) pairs, pruned at read time."""

    def __init__(self, window_s=10.0):
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._buckets = collections.deque()  # (int second, count)

    def record(self, n=1):
        sec = int(time.monotonic())
        with self._lock:
            if self._buckets and self._buckets[-1][0] == sec:
                self._buckets[-1][1] += n
            else:
                self._buckets.append([sec, n])

    def rate(self):
        now = time.monotonic()
        horizon = now - self.window_s
        with self._lock:
            while self._buckets and self._buckets[0][0] < horizon:
                self._buckets.popleft()
            total = sum(c for _, c in self._buckets)
        return total / self.window_s


class ServingGateway:
    """Thread-safe front door + engine-driver thread.

    ``max_queue`` bounds the waiting room: requests submitted but not
    yet decoding (gateway intake + engine scheduler queue). Running
    sequences never count — capacity there is ``num_slots``.
    """

    def __init__(self, engine, max_queue=64, idle_wait_s=0.02,
                 registry=None, start=True):
        self.engine = engine
        self.max_queue = int(max_queue)
        self.idle_wait_s = float(idle_wait_s)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._intake = collections.deque()   # TokenStreams pre engine-submit
        self._live = {}                      # seq.request_id -> TokenStream
        self._backlog = 0                    # waiting-room occupancy
        self._closed = False
        self._drain = True
        self._ids = itertools.count(1)
        engine.on_token = self._on_token
        engine.on_finish = self._on_finish
        self._init_metrics(registry)
        self._thread = threading.Thread(target=self._run,
                                        name="engine-driver", daemon=True)
        # a daemon driver killed mid-XLA-dispatch at interpreter teardown
        # aborts the process (observed: LLVM "Invalid size request") —
        # stop it via atexit instead. weakref so the hook never keeps a
        # dropped gateway alive.
        ref = weakref.ref(self)
        self._atexit_hook = lambda: (lambda gw: gw and gw.shutdown(
            drain=False, timeout=10))(ref())
        atexit.register(self._atexit_hook)
        if start:
            self._thread.start()

    # ------------------------------------------------------------- metrics
    def _init_metrics(self, registry):
        self.registry = registry if registry is not None else \
            MetricsRegistry()
        r = self.registry
        self._m_requests = r.counter(
            "serving_requests_total", "Requests accepted by the gateway.")
        self._m_rejected = r.counter(
            "serving_rejected_total",
            "Requests shed by admission control (queue full).")
        self._m_finished = r.counter(
            "serving_finished_total",
            "Finished sequences by finish_reason.")
        self._m_tokens = r.counter(
            "serving_generated_tokens_total", "Generated tokens.")
        self._m_ttft = r.histogram(
            "serving_ttft_seconds", "Submit-to-first-token latency.",
            buckets=TTFT_BUCKETS)
        self._m_latency = r.histogram(
            "serving_request_latency_seconds",
            "Submit-to-finish latency per request.")
        self._rate = _RateWindow()
        r.gauge("serving_queue_depth",
                "Requests waiting for a slot (intake + scheduler queue)."
                ).set_fn(lambda: self._backlog)
        r.gauge("serving_active_slots",
                "KV slots currently decoding.").set_fn(
            lambda: self.engine.num_active)
        r.gauge("serving_num_slots", "KV slot capacity.").set(
            self.engine.num_slots)
        r.gauge("serving_tokens_per_second",
                "Generated tokens/s over a 10s sliding window.").set_fn(
            self._rate.rate)
        r.gauge("serving_decode_compilations",
                "Decode-program traces (compile-once contract: stays at "
                "one per (num_slots, max_seq_len, n_steps)).").set_fn(
            self.engine.decode_compilations)
        r.counter("serving_prefill_copy_dispatches_total",
                  "Block copy-in dispatches spent installing prefix "
                  "hits (dense engine only; the paged path pins this "
                  "at 0 — hits install by reference).").set_fn(
            lambda: self.engine.stats["prefill_copy_dispatches"])
        r.counter("serving_prefill_chunks_total",
                  "Chunked-prefill device chunks run (one per sequence "
                  "per step while a long cold prompt is interleaved "
                  "with decode; 0 with chunking off or on the dense "
                  "engine).").set_fn(
            lambda: self.engine.stats["prefill_chunks"])
        # per-step telemetry: the SAME duration/token measurements the
        # engine's headroom EWMAs (adaptive chunk budget) read — the
        # driver observes them after every step() it pumps
        self._m_step_dur = r.histogram(
            "serving_step_duration_seconds",
            "Engine step() wall duration (admission + prefill grant + "
            "decode + retire).", buckets=STEP_BUCKETS)
        r.gauge("serving_step_tokens",
                "Tokens the last engine step processed on device "
                "(decode rows x fused ticks + prefill chunk tokens)."
                ).set_fn(lambda: self.engine.stats["last_step_tokens"])
        r.gauge("serving_prefill_headroom_tokens",
                "Current headroom-adaptive chunk-token grant per step "
                "(prefill_chunk is the cap; fixed at it until the "
                "EWMAs have signal or with adaptivity off).").set_fn(
            lambda: self.engine.stats["headroom"])
        cache = getattr(self.engine, "cache", None)
        if getattr(self.engine, "_paged", False) and cache is not None:
            # paged-attention surface: physical sharing + table pressure
            # (scrape-time reads of host bookkeeping; driver is the only
            # writer, a scrape reads ints under the GIL)
            r.gauge("kv_blocks_shared",
                    "Pool blocks physically shared by concurrent "
                    "readers (refcount >= 2) — the zero-copy win."
                    ).set_fn(lambda: cache.pool.num_shared)
            r.gauge("kv_block_table_fill",
                    "Fraction of the [num_slots, max_blocks] block "
                    "table grid populated by live sequences."
                    ).set_fn(cache.table_fill)
        pc = getattr(self.engine, "prefix_cache", None)
        if pc is not None:
            # scrape-time counters backed by the cache's own monotonic
            # stats (the driver thread is the only writer; a scrape reads
            # one int — no sync needed beyond the GIL)
            r.counter("serving_prefix_cache_hits_total",
                      "Admissions that matched a cached prefix chain."
                      ).set_fn(lambda: pc.stats["hits"])
            r.counter("serving_prefix_cache_misses_total",
                      "Admissions with no cached prefix."
                      ).set_fn(lambda: pc.stats["misses"])
            r.counter("serving_prefix_cache_evictions_total",
                      "Cached blocks evicted under pool pressure."
                      ).set_fn(lambda: pc.stats["evictions"])
            r.counter("serving_prefill_tokens_saved_total",
                      "Prompt tokens served from cached KV blocks "
                      "instead of device prefill."
                      ).set_fn(lambda: self.engine.stats[
                          "prefill_tokens_saved"])
            r.gauge("kv_prefix_blocks",
                    "Prefix-cache pool blocks in use (published + "
                    "pinned).").set_fn(lambda: pc.pool.num_used)
            r.gauge("kv_prefix_blocks_capacity",
                    "Prefix-cache pool size in blocks.").set(
                pc.pool.num_blocks)

    # ---------------------------------------------------------- front door
    def submit(self, request) -> TokenStream:
        """Enqueue from any thread. Raises ValueError/TypeError on a bad
        request, QueueFullError past ``max_queue``, GatewayClosedError
        after shutdown began."""
        # validate on the caller's thread: a bad request must 400 here,
        # not poison the driver loop later
        self.engine.validate(request)
        with self._lock:
            if self._closed:
                raise GatewayClosedError("gateway is draining")
            if self._backlog >= self.max_queue:
                self._m_rejected.inc()
                raise QueueFullError(
                    f"waiting room full ({self.max_queue} requests)")
            self._backlog += 1
            stream = TokenStream(self, request, f"cmpl-{next(self._ids)}")
            self._intake.append(stream)
        self._m_requests.inc()
        self._wake.set()
        return stream

    @property
    def queue_depth(self):
        return self._backlog

    @property
    def closed(self):
        return self._closed

    # ------------------------------------------------------- engine events
    def _leave_waiting_room(self, stream):
        if stream._waiting:
            stream._waiting = False
            with self._lock:
                self._backlog -= 1

    def _on_token(self, seq, token):
        stream = self._live.get(seq.request_id)
        self._m_tokens.inc()
        self._rate.record()
        if stream is None:
            return
        if stream.first_token_time is None:
            stream.first_token_time = time.monotonic()
            self._m_ttft.observe(stream.first_token_time
                                 - stream.submit_time)
            self._leave_waiting_room(stream)
        stream._push_token(token)

    def _on_finish(self, seq):
        stream = self._live.pop(seq.request_id, None)
        self._m_finished.inc(reason=seq.finish_reason)
        if stream is None:
            return
        self._leave_waiting_room(stream)  # finished while still queued
        self._m_latency.observe(time.monotonic() - stream.submit_time)
        stream._push_finish(seq.finish_reason)

    # ------------------------------------------------------- driver thread
    def _admit_intake(self):
        while True:
            with self._lock:
                if not self._intake:
                    return
                stream = self._intake.popleft()
            if stream._cancel:
                self._leave_waiting_room(stream)
                self._m_finished.inc(reason="cancelled")
                stream._push_finish("cancelled")
                continue
            try:
                seq = self.engine.submit(stream.request)
            except Exception as e:  # validated at submit(); belt+braces
                self._leave_waiting_room(stream)
                stream._push_error(e)
                continue
            stream.seq = seq
            self._live[seq.request_id] = stream

    def _apply_cancels(self):
        for stream in [s for s in self._live.values() if s._cancel]:
            self.engine.cancel(stream.seq)  # fires _on_finish

    def _run(self):
        try:
            while True:
                self._admit_intake()
                self._apply_cancels()
                if self.engine.has_work():
                    self.engine.step()
                    self._m_step_dur.observe(
                        self.engine.stats["last_step_duration_s"])
                    continue
                with self._lock:
                    drained = not self._intake and not self._live
                    if self._closed and drained:
                        return
                self._wake.wait(self.idle_wait_s)
                self._wake.clear()
        except BaseException as e:
            # the driver is the only thread that can unblock consumers —
            # a dying engine must not strand them mid-result()
            with self._lock:
                self._closed = True
                stranded = list(self._intake) + list(self._live.values())
                self._intake.clear()
                self._live.clear()
            for s in stranded:
                s._push_error(f"engine driver died: {e!r}")
            raise

    # ------------------------------------------------------------ shutdown
    def shutdown(self, drain=True, timeout=None):
        """Close the front door; ``drain=True`` lets in-flight and
        queued work finish, ``drain=False`` cancels it. Blocks until the
        driver exits (or ``timeout``). Returns True if it did."""
        with self._lock:
            self._closed = True
            streams = ([] if drain else
                       list(self._intake) + list(self._live.values()))
        for s in streams:
            s._cancel = True
        self._wake.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
        atexit.unregister(self._atexit_hook)
        return not self._thread.is_alive()
