"""Async request gateway over the continuous-batching engine.

The engine is single-threaded by contract (``step()`` mutates slot
state, host length mirrors, and jitted-program caches with no locks).
This module makes it servable without breaking that contract: ONE
driver thread owns the engine and pumps ``step()``; every other thread
talks to the gateway through a thread-safe front door —

- :meth:`ServingGateway.submit` enqueues a request from any thread and
  hands back a :class:`TokenStream`, a per-token iterator fed by the
  engine's ``on_token`` callback the moment each token reaches the
  host;
- :meth:`TokenStream.cancel` flags a request from any thread; the
  driver applies it between steps via ``engine.cancel`` — the KV slot
  frees mid-decode and the ragged decode kernel skips it from the next
  step on, so cancellation costs nothing;
- admission control is a bounded waiting-room: submissions past
  ``max_queue`` raise :class:`QueueFullError` (the HTTP layer's 429)
  instead of growing an unbounded backlog;
- :meth:`ServingGateway.shutdown` drains gracefully — the front door
  closes, in-flight sequences run to completion, then the driver
  exits (or ``drain=False`` cancels everything in flight).

Deadlines ride on the engine itself (``GenerationRequest.timeout_s``,
checked at step boundaries), so a request expires whether it is queued
or mid-decode, and the gateway just observes the ``"timeout"`` finish.

The driver loop is SUPERVISED (README "Fault tolerance & chaos
testing"): an exception out of ``engine.step()`` no longer kills
serving forever. The supervisor classifies each step failure —

- **transient** (:class:`~..faults.TransientFault`, or any type in
  ``transient_types``): retry the same engine with bounded backoff; a
  streak past ``max_transient_retries`` escalates to fatal;
- **hung**: a step whose measured duration (injectable ``clock``)
  overran ``watchdog_deadline_s`` — treated as fatal, and externally
  visible either way through the
  ``serving_watchdog_last_step_age_seconds`` gauge and ``/healthz``;
- **fatal** (everything else): rebuild the engine via
  ``engine_factory`` and RECOVER every in-flight request by recompute
  — each live sequence's prompt + generated-so-far tokens are known
  host-side, so ``engine.restore()`` re-enqueues them as (chunked)
  prefills and streams continue byte-identically for greedy requests;
  the factory shares the model-level jit cache, so the rebuilt engine
  re-traces nothing (``decode_compilations()`` stays 1).

If a fault recurs while the last recovery's readmissions are still
live, the supervisor assumes a POISON request is pinned to the crash
and bisects the readmitted set: half re-enters, half parks outside the
engine; the half the fault follows keeps shrinking until a single
culprit remains, which is the ONLY request failed
(``finish_reason="error"`` — SSE clients get a final error event,
blocking clients a JSON 500) while every bystander — parked or
readmitted — runs to completion. ``max_restarts`` bounds the total
rebuild budget; past it the gateway gives up and strands with errors
(the pre-supervision behavior).

The compile-once property survives serving AND recovery: the gateway
adds no device-side work, so ``decode_compilations()`` stays at one per
``(num_slots, max_seq_len, n_steps)`` no matter the HTTP traffic mix
or how many times the engine was rebuilt — pinned by
tests/test_serving_server.py and tests/test_fault_tolerance.py.
"""
from __future__ import annotations

import atexit
import collections
import itertools
import queue
import threading
import time
import weakref

import numpy as np

from ...profiler.cost import PROGRAM_KINDS, CostObservatory
from ...profiler.metrics import (QUEUE_WAIT_BUCKETS, SPEC_ACCEPT_BUCKETS,
                                 STEP_BUCKETS, TPOT_BUCKETS, TTFT_BUCKETS,
                                 MetricsRegistry)
from ...profiler.tracing import TID_GATEWAY, SpanTracer
from ..faults import TransientFault

#: engine ``stats`` counters whose /metrics series must stay monotonic
#: across crash-recovery rebuilds: a rebuilt engine starts its stats at
#: zero, so the gateway carries each dead incarnation's final count as a
#: base (the ``serving_preemptions_total`` pattern, generalized) and
#: every scrape reads base + live. Only true counters belong here —
#: gauges (headroom, last_step_*) must NOT be summed across engines.
#: MULTI-ENGINE scrapes (the fleet): the carry is PER GATEWAY — each
#: replica's gateway owns its own ``(base, engine)`` snapshot and
#: registers its series through a ``registry.labeled(replica=...)``
#: view, so N replicas share one /metrics document, every series is
#: distinguished by its ``replica`` label, and any SINGLE replica
#: rebuilding re-bases only its own series (the others never move) —
#: a fleet scrape can never observe a counter going backwards.
CARRIED_ENGINE_STATS = (
    "preemptions", "policy_preemptions", "prefill_copy_dispatches",
    "prefill_chunks", "prefill_tokens_saved", "spec_proposed",
    "spec_accepted", "spec_tokens", "decode_calls", "tokens_generated",
    "mtick_syncs", "mtick_ticks")

#: same carry for the prefix cache's own stats dict (a rebuild builds a
#: fresh trie — and a fresh host tier — zeroing every counter here).
CARRIED_PREFIX_STATS = ("hits", "misses", "evictions",
                        "spilled_blocks", "tier_hits",
                        "readmitted_blocks", "tier_evictions",
                        "tier_transfers")


class QueueFullError(RuntimeError):
    """Waiting room at capacity — shed load (HTTP 429)."""


class TraceBusyError(RuntimeError):
    """A step-bounded trace capture is already in progress (HTTP 409) —
    captures serialize so two debuggers cannot clear each other's
    buffer mid-window."""


class GatewayClosedError(RuntimeError):
    """Gateway is draining or stopped — no new work (HTTP 503)."""


class WatchdogTimeout(RuntimeError):
    """An engine step overran the supervisor's watchdog deadline —
    classified "hung" and recovered like a fatal fault. (A step that
    never returns at all cannot be preempted from inside its own
    thread; it is visible externally through ``/healthz``'s
    ``last_step_age_s`` and the watchdog gauge, for an orchestrator's
    liveness probe to act on.)"""


class TokenStream:
    """Live handle for one submitted request.

    Iterating yields generated token ids as the engine produces them and
    stops when the sequence finishes; ``finish_reason`` is set by then.
    ``result()`` drains to completion and returns
    ``(ids, finish_reason)``. Both are safe from any single consumer
    thread; ``cancel()`` is safe from any thread.
    """

    def __init__(self, gateway, request, stream_id):
        self.gateway = gateway
        self.request = request
        self.id = stream_id
        self.finish_reason = None
        self.seq = None            # set by the driver at engine-submit
        self.submit_time = time.monotonic()
        self.first_token_time = None
        self.finish_time = None
        self._events = queue.SimpleQueue()  # ("token", id) | ("finish", r) | ("error", msg)
        self._collected = []
        self._cancel = False
        self._waiting = True       # still counted against max_queue
        self._drained = False      # consumer saw the finish event

    # ------------------------------------------------------- consumer side
    def __iter__(self):
        # event-driven on purpose: the driver sets finish_reason BEFORE
        # queueing the finish event, so gating on finish_reason here
        # would drop still-queued tokens of a finished stream
        while not self._drained:
            kind, payload = self._events.get()
            if kind == "token":
                self._collected.append(payload)
                yield payload
            elif kind == "finish":
                self._drained = True
            else:
                self._drained = True
                raise RuntimeError(payload)

    def result(self):
        """Block until the sequence finishes; return
        ``(np.int32 ids, finish_reason)``."""
        for _ in self:
            pass
        return np.asarray(self._collected, np.int32), self.finish_reason

    def tokens(self):
        """Tokens consumed so far (complete after ``result()`` /
        exhausting the iterator)."""
        return list(self._collected)

    @property
    def done(self):
        """Finished engine-side (tokens may still await consumption)."""
        return self.finish_reason is not None

    def cancel(self):
        """Request cancellation (idempotent, any thread). The driver
        applies it between engine steps."""
        self._cancel = True
        self.gateway._wake.set()

    # --------------------------------------------------------- driver side
    def _push_token(self, token):
        self._events.put(("token", int(token)))

    def _push_finish(self, reason):
        self.finish_time = time.monotonic()
        self.finish_reason = reason
        self._events.put(("finish", reason))

    def _push_error(self, msg):
        self.finish_time = time.monotonic()
        self.finish_reason = "error"
        self._events.put(("error", str(msg)))


class _RateWindow:
    """Sliding-window event rate (the tokens/s gauge): O(1) record via a
    deque of (second-bucket, count) pairs, pruned at read time."""

    def __init__(self, window_s=10.0):
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._buckets = collections.deque()  # (int second, count)

    def record(self, n=1):
        sec = int(time.monotonic())
        with self._lock:
            if self._buckets and self._buckets[-1][0] == sec:
                self._buckets[-1][1] += n
            else:
                self._buckets.append([sec, n])

    def rate(self):
        now = time.monotonic()
        horizon = now - self.window_s
        with self._lock:
            while self._buckets and self._buckets[0][0] < horizon:
                self._buckets.popleft()
            total = sum(c for _, c in self._buckets)
        return total / self.window_s


class ServingGateway:
    """Thread-safe front door + engine-driver thread.

    ``max_queue`` bounds the waiting room: requests submitted but not
    yet decoding (gateway intake + engine scheduler queue). Running
    sequences never count — capacity there is ``num_slots``.
    """

    def __init__(self, engine, max_queue=64, idle_wait_s=0.02,
                 registry=None, start=True, engine_factory=None,
                 watchdog_deadline_s=None, max_transient_retries=3,
                 retry_backoff_s=0.02, max_restarts=8,
                 transient_types=(TransientFault,), clock=None,
                 fault_hook=None, tracer=None, trace=False,
                 trace_buffer=65536, cost=True, on_fatal=None,
                 stream_id_prefix="cmpl"):
        self.engine = engine
        self.max_queue = int(max_queue)
        self.idle_wait_s = float(idle_wait_s)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._intake = collections.deque()   # TokenStreams pre engine-submit
        self._live = {}                      # seq.request_id -> TokenStream
        self._backlog = 0                    # waiting-room occupancy
        self._closed = False
        self._drain = True
        self._ids = itertools.count(1)
        # stream-id namespace: the fleet gives each replica's gateway
        # its own prefix so ids stay unique across the whole fleet
        # (completion ids are client-visible and land in the router
        # decision log)
        self._id_prefix = str(stream_id_prefix)
        # ----------------------------------------------- supervision state
        # engine_factory() -> a fresh engine with the SAME config and the
        # SAME shared jit_cache (so recovery never re-traces); None
        # disables crash recovery (a fatal fault strands, pre-PR-7 style)
        self.engine_factory = engine_factory
        self.watchdog_deadline_s = (None if not watchdog_deadline_s
                                    else float(watchdog_deadline_s))
        self.max_transient_retries = int(max_transient_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.max_restarts = int(max_restarts)
        self.transient_types = tuple(transient_types)
        self._clock = clock if clock is not None else time.monotonic
        self._fault_hook = fault_hook        # re-installed on every rebuild
        # fleet failover hook: called (gateway, [(stream, seq|None)])
        # from the dying driver thread when supervision is exhausted,
        # BEFORE the streams are stranded with errors; returning True
        # means the callee (the fleet) took ownership — it re-admits
        # each live sequence on a sibling replica via restore() — and
        # the handed-off streams get no error event here.
        self.on_fatal = on_fatal
        self._transient_streak = 0
        self._restarts = 0
        self.last_restart_at = None          # clock() of the last rebuild
        # dead engine incarnations' summed counter stats (see
        # CARRIED_ENGINE_STATS): every /metrics series derived from
        # engine (or prefix-cache) stats reads through _stat()/
        # _pc_stat(), so a crash-recovery rebuild can never scrape as a
        # counter going backwards — pinned under the fault matrix by
        # tests/test_cost_observatory.py. The (base, pc_base, engine)
        # triple swaps in ONE attribute store: a scrape mid-rebuild
        # must never pair the new base with the old engine's stats
        # (double count, then a backwards step at the engine swap).
        self._counter_state = (dict.fromkeys(CARRIED_ENGINE_STATS, 0),
                               dict.fromkeys(CARRIED_PREFIX_STATS, 0),
                               engine)
        self._last_step_done = self._clock()
        self._recovering = False
        self._fault_at = None                # clock() of the fault being
        self.restart_latencies = []          # recovered; -> latency sample
        # poison-quarantine / bisection state (module docstring):
        self._probation = set()   # ids readmitted by the last recovery
        self._suspect_ids = None  # active bisection half (None = off)
        self._parked = []         # Sequences held out of the engine
        # live-migration intake/outtake (the fleet's request-migration
        # plane): adopt() enqueues (stream, seq) pairs arriving FROM a
        # sibling (seq None = never engine-admitted, submit fresh);
        # request_migration() enqueues (stream, handoff) pairs leaving
        # for one. Both are drained by the driver between steps — the
        # engine mutation (restore/evict) happens only on its thread.
        self._migrate_in = collections.deque()
        self._migrate_out = collections.deque()
        # ------------------------------------------------ tracing state
        # (README "Tracing & debugging") the gateway OWNS the tracer so
        # one timeline survives engine rebuilds; it is installed on
        # every engine incarnation. trace=True records from startup
        # (the --trace flag); otherwise the tracer sits disabled —
        # zero-cost — until /debug/trace?steps=N opens a capture
        # window via capture_trace().
        self.tracer = tracer if tracer is not None else \
            SpanTracer(capacity=trace_buffer, clock=self._clock)
        #: public: whether tracing records continuously (``--trace``) —
        #: the HTTP layer keys its /debug/trace default on it (a
        #: parameterless GET must SNAPSHOT a persistent buffer, never
        #: clear hours of history)
        self.trace_persistent = bool(trace)
        if self.trace_persistent:
            self.tracer.enable()
        self._capture = None        # {"remaining": n, "done": Event}
        # ---------------------------------------------- cost observatory
        # (README "Cost attribution & /debug/profile") gateway-owned
        # like the tracer, so dispatch/transfer/compile accounting is
        # monotonic across engine rebuilds; ON by default (host-side
        # dict updates, a handful per step) — ``cost=False`` reduces
        # every engine cost site to the one _co() attribute check.
        self.cost = CostObservatory(clock=self._clock) if cost else None
        self._pcapture = None       # /debug/profile capture window
        engine.tracer = self.tracer
        engine.cost = self.cost
        engine.on_token = self._on_token
        engine.on_finish = self._on_finish
        engine.on_policy_preempt = self._on_policy_preempt
        if fault_hook is not None:
            engine.fault_hook = fault_hook
        self._init_metrics(registry)
        self._thread = threading.Thread(target=self._run,
                                        name="engine-driver", daemon=True)
        # a daemon driver killed mid-XLA-dispatch at interpreter teardown
        # aborts the process (observed: LLVM "Invalid size request") —
        # stop it via atexit instead. weakref so the hook never keeps a
        # dropped gateway alive.
        ref = weakref.ref(self)
        self._atexit_hook = lambda: (lambda gw: gw and gw.shutdown(
            drain=False, timeout=10))(ref())
        atexit.register(self._atexit_hook)
        if start:
            self.start()

    def start(self):
        """Start the engine-driver thread (for gateways built with
        ``start=False`` — tests and benches submit their whole workload
        first so a fault plan's step indices are deterministic relative
        to the traffic). Idempotent once running; returns self."""
        if not self._thread.is_alive():
            self._thread.start()
        return self

    # ------------------------------------------------------------- helpers
    def _tr(self):
        """The tracer when recording, else None — the gateway's guard
        for its own instrumentation sites (the engine's ``_tr()``
        discipline; the guard-discipline static test pins that every
        recording site in ``serving/`` routes through one of these)."""
        t = self.tracer
        return t if t.enabled else None

    @property
    def _stat_base(self) -> dict:
        """Dead incarnations' summed engine-stat counters."""
        return self._counter_state[0]

    def _stat(self, key) -> int:
        """A monotonic engine-stat counter: dead incarnations' carried
        base + the live engine's count (CARRIED_ENGINE_STATS). Reads
        base and engine from ONE snapshot so a mid-rebuild scrape
        cannot mix epochs."""
        base, _, eng = self._counter_state
        return base[key] + eng.stats[key]

    def _pc_stat(self, key) -> int:
        """Same carry for prefix-cache stats (zero with no trie)."""
        _, pc_base, eng = self._counter_state
        pc = eng.prefix_cache
        return pc_base[key] + (pc.stats[key] if pc is not None else 0)

    def _class_labels(self, seq) -> dict:
        """Label kwargs for one sequence's latency observations: the
        ``class`` label with a MULTI-CLASS table active, {} otherwise —
        a policy-off gateway's histogram series keep their empty label
        sets, byte-identical to before the policy subsystem existed."""
        if self._m_slo_miss is None:
            return {}
        pclass = getattr(seq, "pclass", None)
        return {"class": pclass.name} if pclass is not None else {}

    # ------------------------------------------------------------- metrics
    def _init_metrics(self, registry):
        self.registry = registry if registry is not None else \
            MetricsRegistry()
        r = self.registry
        self._m_requests = r.counter(
            "serving_requests_total", "Requests accepted by the gateway.")
        self._m_rejected = r.counter(
            "serving_rejected_total",
            "Requests shed by admission control (queue full).")
        self._m_finished = r.counter(
            "serving_finished_total",
            "Finished sequences by finish_reason.")
        self._m_tokens = r.counter(
            "serving_generated_tokens_total", "Generated tokens.")
        self._m_ttft = r.histogram(
            "serving_ttft_seconds", "Submit-to-first-token latency.",
            buckets=TTFT_BUCKETS)
        self._m_latency = r.histogram(
            "serving_request_latency_seconds",
            "Submit-to-finish latency per request.")
        # SLO substrate (ROADMAP multi-tenant item b): per-request
        # latency decomposition the TTFT/TPOT-target scheduler will
        # consume. Both are gateway-owned and read the Sequence's
        # engine-clock stamps at retirement, so they survive engine
        # rebuilds and keep accumulating across restarts.
        self._m_tpot = r.histogram(
            "serving_tpot_seconds",
            "Per-request time-per-output-token: (finish - first token)"
            " / (tokens - 1), the steady-state decode cadence one "
            "request observed (engine clock; requests with a single "
            "token have no inter-token gap and are not observed).",
            buckets=TPOT_BUCKETS)
        self._m_queue_wait = r.histogram(
            "serving_queue_wait_seconds",
            "Per-request submit-to-slot-claim wait (engine clock) — "
            "the admission-control half of TTFT. Never-admitted "
            "requests (queued timeout/cancel) are not observed.",
            buckets=QUEUE_WAIT_BUCKETS)
        self._rate = _RateWindow()
        r.gauge("serving_queue_depth",
                "Requests waiting for a slot (intake + scheduler queue)."
                ).set_fn(lambda: self._backlog)
        r.gauge("serving_active_slots",
                "KV slots currently decoding.").set_fn(
            lambda: self.engine.num_active)
        r.gauge("serving_num_slots", "KV slot capacity.").set(
            self.engine.num_slots)
        r.gauge("serving_tokens_per_second",
                "Generated tokens/s over a 10s sliding window.").set_fn(
            self._rate.rate)
        r.gauge("serving_decode_compilations",
                "Decode-program traces (compile-once contract: stays at "
                "one per (num_slots, max_seq_len, n_steps)).").set_fn(
            self.engine.decode_compilations)
        r.counter("serving_prefill_copy_dispatches_total",
                  "Block copy-in dispatches spent installing prefix "
                  "hits (dense engine only; the paged path pins this "
                  "at 0 — hits install by reference). Monotonic "
                  "across engine rebuilds.").set_fn(
            lambda: self._stat("prefill_copy_dispatches"))
        r.counter("serving_prefill_chunks_total",
                  "Chunked-prefill device chunks run (one per sequence "
                  "per step while a long cold prompt is interleaved "
                  "with decode; 0 with chunking off or on the dense "
                  "engine). Monotonic across engine rebuilds.").set_fn(
            lambda: self._stat("prefill_chunks"))
        # per-step telemetry: the SAME duration/token measurements the
        # engine's headroom EWMAs (adaptive chunk budget) read — the
        # driver observes them after every step() it pumps
        self._m_step_dur = r.histogram(
            "serving_step_duration_seconds",
            "Engine step() wall duration (admission + prefill grant + "
            "decode + retire).", buckets=STEP_BUCKETS)
        r.gauge("serving_step_tokens",
                "Tokens the last engine step processed on device "
                "(decode rows x fused ticks + prefill chunk tokens)."
                ).set_fn(lambda: self.engine.stats["last_step_tokens"])
        r.gauge("serving_prefill_headroom_tokens",
                "Current headroom-adaptive chunk-token grant per step "
                "(prefill_chunk is the cap; fixed at it until the "
                "EWMAs have signal or with adaptivity off).").set_fn(
            lambda: self.engine.stats["headroom"])
        # multi-tick decode surface (README "Multi-tick decode"):
        # mean on-device decode ticks per host sync — 1.0 means the
        # host is back in the loop every token, decode_ticks means the
        # fast path is fully engaged. Counters ride the _stat() carry,
        # so a rebuild never dents the ratio.
        r.gauge("serving_decode_ticks_per_sync",
                "Mean fused on-device decode ticks per host sync on "
                "the multi-tick engine (decode_ticks=1 engines and "
                "engines that never decoded scrape 0).").set_fn(
            lambda: (self._stat("mtick_ticks")
                     / max(self._stat("mtick_syncs"), 1)))
        # speculative-decode surface (README "Speculative decoding"):
        # registered only on a speculative engine, read THROUGH
        # self.engine so a recovery rebuild re-binds them (same idiom
        # as the paged/prefix gauges below). Counters read through the
        # _stat() carry, so a rebuild never scrapes as a reset.
        self._m_spec_len = None
        if getattr(self.engine, "spec_decode", False):
            r.counter("serving_spec_proposed_total",
                      "Draft tokens submitted to verification. "
                      "Monotonic across engine rebuilds."
                      ).set_fn(lambda: self._stat("spec_proposed"))
            r.counter("serving_spec_accepted_total",
                      "Draft tokens accepted (emitted without their own "
                      "decode launch) — the speculation win. Monotonic "
                      "across engine rebuilds.").set_fn(
                lambda: self._stat("spec_accepted"))
            self._m_spec_len = r.histogram(
                "serving_spec_accept_length",
                "Tokens emitted per verify span (1 = nothing accepted, "
                "spec_k + 1 = full draft accepted).",
                buckets=SPEC_ACCEPT_BUCKETS)
            # numerator is decode_calls, NOT spec_steps: a spec engine
            # increments decode_calls only for launches that carried
            # verify rows, while spec_steps also counts chunk-only
            # launches whose tokens never enter spec_tokens — those
            # would inflate a ratio defined over decode work
            r.gauge("serving_spec_launches_per_accepted_token",
                    "Decode launches per emitted token under "
                    "speculation (1.0 = no speedup; ~1 / mean "
                    "acceptance length).").set_fn(
                lambda: (self._stat("decode_calls")
                         / max(self._stat("spec_tokens"), 1)))
        # fault-tolerance surface (README "Fault tolerance & chaos
        # testing"). Gateway-owned counters, NOT engine-stat-backed:
        # engine stats die with a rebuilt engine, and a restart must
        # never scrape as a counter reset.
        self._m_faults = r.counter(
            "serving_faults_total",
            "Engine step faults observed by the supervisor, by class "
            "(kind = transient|fatal|hung).")
        self._m_restarts = r.counter(
            "serving_engine_restarts_total",
            "Engine rebuilds after a fatal/hung step fault (recovery-"
            "by-recompute; the jit cache is shared, so a restart "
            "re-traces nothing).")
        self._m_recovered = r.counter(
            "serving_recovered_requests_total",
            "Live requests re-enqueued for recompute after an engine "
            "rebuild (each readmission counts, including bisection "
            "re-entries).")
        # zero-seed the label-free incremented counters so every
        # gateway's series exists from the first scrape — a fleet
        # replica that never restarted must scrape as an explicit 0,
        # not an absent series (dashboards diff replicas)
        for m in (self._m_requests, self._m_rejected, self._m_tokens,
                  self._m_restarts, self._m_recovered):
            m.inc(0)
        r.counter("serving_preemptions_total",
                  "Sequences preempted by recompute under KV pool "
                  "pressure (PoolExhausted: chain donated to the trie, "
                  "request re-queued). Monotonic across engine rebuilds."
                  ).set_fn(lambda: self._stat("preemptions"))
        # multi-tenant SLO surface (README "Multi-tenant SLO serving"):
        # registered only when the engine's class table is ACTIVE, so a
        # policy-off gateway's /metrics document — and the empty label
        # sets on the latency histograms — stays byte-identical to
        # before the subsystem existed. Both counters are gateway-owned
        # inc-based (the _m_faults idiom, NOT engine-stat-backed), so
        # they are monotonic across engine rebuilds by construction;
        # zero-seeded per known class so dashboards can diff tenants
        # from the first scrape.
        self._m_slo_miss = None
        self._m_policy_preempt = None
        if self.engine.classes.active:
            self._m_slo_miss = r.counter(
                "serving_slo_misses_total",
                "Finished first-tokens/requests that exceeded their "
                "priority class's SLO target, by class and slo "
                "(ttft|tpot). Classes without a target never miss.")
            self._m_policy_preempt = r.counter(
                "serving_policy_preemptions_total",
                "Sequences displaced by SLO-driven policy preemption "
                "(an urgent higher-class request claimed the slot), "
                "by the victim's class. Streams continue "
                "byte-identically after restore.")
            for c in self.engine.classes:
                self._m_policy_preempt.inc(0, victim_class=c.name)
                for slo in ("ttft", "tpot"):
                    self._m_slo_miss.inc(0, **{"class": c.name,
                                               "slo": slo})
        r.gauge("serving_watchdog_last_step_age_seconds",
                "Seconds since the last completed engine step (the "
                "supervisor's hung-step signal; an orchestrator's "
                "external liveness probe for a step that never "
                "returns).").set_fn(self.last_step_age)
        # paged/prefix gauges read THROUGH self.engine at scrape time:
        # a recovery rebuild swaps the engine (and its cache/pool/trie)
        # underneath the registry, and the gauges must follow it rather
        # than keep reporting a dead engine's bookkeeping
        if getattr(self.engine, "_paged", False) \
                and getattr(self.engine, "cache", None) is not None:
            # paged-attention surface: physical sharing + table pressure
            # (scrape-time reads of host bookkeeping; driver is the only
            # writer, a scrape reads ints under the GIL)
            r.gauge("kv_blocks_shared",
                    "Pool blocks physically shared by concurrent "
                    "readers (refcount >= 2) — the zero-copy win."
                    ).set_fn(lambda: self.engine.cache.pool.num_shared)
            r.gauge("kv_block_table_fill",
                    "Fraction of the [num_slots, max_blocks] block "
                    "table grid populated by live sequences."
                    ).set_fn(lambda: self.engine.cache.table_fill())
            # quantized-serving surface (README "Quantized serving"):
            # pool HBM in BYTES, dtype-aware via
            # PagedKVCache.occupancy_bytes() — an int8 pool reports
            # int8 data bytes under kind="kv" plus its fp32 scale
            # planes under kind="scales" (0 on the default pool), and
            # the per-cached-token marginal HBM cost the density bench
            # banks against. Allocated (live + trie) blocks x
            # per-block bytes.
            kvb = r.gauge(
                "kv_pool_bytes",
                "Allocated KV pool HBM bytes by storage kind (kv = "
                "block data at the pool dtype, scales = the int8 "
                "pool's fp32 scale planes; 0 when unquantized).")
            # each kind scans the block tables once (used_blocks);
            # per-token is pure constants — a scrape pays two cheap
            # scans total, never three occupancy_bytes() walks
            kvb.set_fn(
                lambda: (self.engine.cache.used_blocks()
                         * self.engine.cache.pool.block_nbytes),
                kind="kv")
            kvb.set_fn(
                lambda: (self.engine.cache.used_blocks()
                         * self.engine.cache.pool.scale_block_nbytes),
                kind="scales")
            r.gauge("serving_kv_bytes_per_token",
                    "Marginal HBM bytes one cached token costs (block "
                    "bytes incl. scale planes / block_size) — the "
                    "denominator of the quantized-density win."
                    ).set_fn(
                lambda: self.engine.cache.bytes_per_token())
        if getattr(self.engine, "prefix_cache", None) is not None:
            # scrape-time counters backed by the cache's own stats plus
            # the gateway's carried base (the driver thread is the only
            # writer; a scrape reads one int — no sync needed beyond
            # the GIL). A rebuild starts a fresh trie, but the base
            # keeps the series monotonic across it.
            r.counter("serving_prefix_cache_hits_total",
                      "Admissions that matched a cached prefix chain. "
                      "Monotonic across engine rebuilds.").set_fn(
                lambda: self._pc_stat("hits"))
            r.counter("serving_prefix_cache_misses_total",
                      "Admissions with no cached prefix. Monotonic "
                      "across engine rebuilds.").set_fn(
                lambda: self._pc_stat("misses"))
            r.counter("serving_prefix_cache_evictions_total",
                      "Cached blocks evicted under pool pressure. "
                      "Monotonic across engine rebuilds.").set_fn(
                lambda: self._pc_stat("evictions"))
            r.counter("serving_prefill_tokens_saved_total",
                      "Prompt tokens served from cached KV blocks "
                      "instead of device prefill. Monotonic across "
                      "engine rebuilds.").set_fn(
                lambda: self._stat("prefill_tokens_saved"))
            r.gauge("kv_prefix_blocks",
                    "Prefix-cache pool blocks in use (published + "
                    "pinned).").set_fn(
                lambda: self.engine.prefix_cache.pool.num_used)
            r.gauge("kv_prefix_blocks_capacity",
                    "Prefix-cache pool size in blocks.").set_fn(
                lambda: self.engine.prefix_cache.pool.num_blocks)
            r.gauge("serving_prefix_cached_blocks",
                    "Trie-resident cached blocks (live nodes) — the "
                    "HBM half of the tiered cache.").set_fn(
                lambda: self.engine.prefix_cache.num_cached_blocks)
            # host-RAM spill tier (README "Tiered KV prefix cache"):
            # counters registered unconditionally so a tierless engine
            # scrapes explicit zeros; occupancy gauges read 0 with the
            # tier off.
            r.counter("serving_prefix_spilled_blocks_total",
                      "Evicted trie blocks spilled device->host into "
                      "the tier instead of deleted. Monotonic across "
                      "engine rebuilds.").set_fn(
                lambda: self._pc_stat("spilled_blocks"))
            r.counter("serving_prefix_tier_hits_total",
                      "Recording lookups that readmitted at least one "
                      "spilled block from the host tier. Monotonic "
                      "across engine rebuilds.").set_fn(
                lambda: self._pc_stat("tier_hits"))
            r.counter("serving_prefix_readmitted_blocks_total",
                      "Spilled blocks streamed back host->device and "
                      "re-linked as live trie nodes. Monotonic across "
                      "engine rebuilds.").set_fn(
                lambda: self._pc_stat("readmitted_blocks"))
            r.counter("serving_prefix_tier_evictions_total",
                      "Tier entries dropped by the host-side LRU under "
                      "the host_tier_bytes budget. Monotonic across "
                      "engine rebuilds.").set_fn(
                lambda: self._pc_stat("tier_evictions"))
            r.counter("serving_prefix_tier_transfers_total",
                      "Spilled chains admitted host-to-host from a "
                      "sibling replica's tier (the fleet cache plane). "
                      "Monotonic across engine rebuilds.").set_fn(
                lambda: self._pc_stat("tier_transfers"))
            r.gauge("serving_prefix_tier_blocks",
                    "Blocks resident in the host-RAM spill tier."
                    ).set_fn(
                lambda: (self.engine.prefix_cache.tier.num_blocks
                         if self.engine.prefix_cache.tier is not None
                         else 0))
            r.gauge("serving_prefix_tier_bytes",
                    "Host bytes the spill tier currently holds."
                    ).set_fn(
                lambda: (self.engine.prefix_cache.tier.bytes_used
                         if self.engine.prefix_cache.tier is not None
                         else 0))
            r.gauge("serving_prefix_tier_bytes_capacity",
                    "The host_tier_bytes budget (0 = tier off)."
                    ).set_fn(
                lambda: (self.engine.prefix_cache.host_tier_bytes))
        # device-boundary cost surface (README "Cost attribution &
        # /debug/profile"): observatory-owned, so every series is
        # monotonic across engine rebuilds by construction. One series
        # per program kind is registered up front — unused kinds scrape
        # as 0 rather than appearing mid-flight.
        if self.cost is not None:
            co = self.cost
            disp = r.counter(
                "serving_dispatches_total",
                "Device program launches by program kind — the exact "
                "host->device dispatch count the mega-kernel work is "
                "measured against. Monotonic across engine rebuilds.")
            for kind in PROGRAM_KINDS:
                disp.set_fn((lambda k: lambda: co.kind_calls(k))(kind),
                            program=kind)
            xfer = r.counter(
                "serving_transfer_bytes_total",
                "Host<->device boundary bytes from abstract shapes "
                "(h2d: host-resident argument leaves uploaded at "
                "dispatch; d2h: result leaves the engine fetches to "
                "host). No device sync; monotonic across rebuilds.")
            xfer.set_fn(lambda: co.totals["h2d_bytes"], direction="h2d")
            xfer.set_fn(lambda: co.totals["d2h_bytes"], direction="d2h")
            # tensor-parallel collective surface (README "Tensor-
            # parallel serving"): cross-chip all-reduce wire bytes by
            # wire dtype — a SEPARATE ledger from h2d/d2h (all-reduce
            # traffic never crosses the host boundary, and logical
            # per-shard arg leaves are never double-counted into it).
            # Registered up front for both dtypes so tp=1 engines
            # scrape explicit zeros, not absent series.
            coll = r.counter(
                "serving_collective_bytes_total",
                "Cross-chip tensor-parallel all-reduce wire bytes per "
                "device by collective dtype (exact, shape-derived — "
                "the EQuARX int8 wire cut is this counter's fp/int8 "
                "ratio). 0 on tp=1 engines. Monotonic across engine "
                "rebuilds.")
            for cdt in ("fp", "int8"):
                coll.set_fn((lambda d: lambda: co.collective_bytes(d))(
                    cdt), dtype=cdt)
            # KV-tier cache-plane traffic by direction — the same
            # separate-ledger rule as collectives: spill/readmit bytes
            # never land in the per-program h2d/d2h records, so the
            # banked DISPATCH_BENCH.json baselines stay clean.
            # Registered up front for all three directions so tierless
            # engines scrape explicit zeros.
            tier = r.counter(
                "serving_tier_bytes_total",
                "KV prefix-tier bytes moved by direction (d2h: spill, "
                "h2d: readmission, peer: fleet host-to-host transfer "
                "in). A separate ledger from serving_transfer_bytes_"
                "total — cache-plane traffic never pollutes per-program "
                "transfer baselines. Monotonic across engine rebuilds.")
            for tdir in ("d2h", "h2d", "peer"):
                tier.set_fn((lambda d: lambda: co.tier_bytes(d))(tdir),
                            direction=tdir)
            r.counter("serving_program_compiles_total",
                      "Program compile (trace) events observed at the "
                      "jit-cache chokepoint — stays flat once warm "
                      "(the compile-once contract, including across "
                      "rebuilds).").set_fn(
                lambda: co.totals["compiles"])
            r.gauge("serving_dispatches_per_decoded_token",
                    "Device program launches per generated token "
                    "(all program kinds / all tokens since start) — "
                    "the ROADMAP mega-kernel item's headline; its "
                    "banked baseline lives in DISPATCH_BENCH.json."
                    ).set_fn(
                lambda: (co.totals["dispatches"]
                         / max(self._stat("tokens_generated"), 1)))

    # ---------------------------------------------------------- front door
    def submit(self, request) -> TokenStream:
        """Enqueue from any thread. Raises ValueError/TypeError on a bad
        request, QueueFullError past ``max_queue``, GatewayClosedError
        after shutdown began."""
        # validate on the caller's thread: a bad request must 400 here,
        # not poison the driver loop later
        self.engine.validate(request)
        with self._lock:
            if self._closed:
                raise GatewayClosedError("gateway is draining")
            if self._backlog >= self.max_queue:
                self._m_rejected.inc()
                raise QueueFullError(
                    f"waiting room full ({self.max_queue} requests)")
            self._backlog += 1
            stream = TokenStream(self, request,
                                 f"{self._id_prefix}-{next(self._ids)}")
            self._intake.append(stream)
        self._m_requests.inc()
        self._wake.set()
        return stream

    def adopt(self, stream, seq=None):
        """Take over a live request from a sibling gateway (fleet
        failover / live migration). Thread-safe: enqueues the pair; the
        driver re-admits between steps — ``seq`` (the sibling's evicted
        / crash-snapshotted Sequence, PRNG walk included) re-enters via
        ``engine.restore`` so its stream continues byte-identically,
        while ``seq=None`` (a request the sibling never engine-
        admitted) submits fresh. The stream is re-pointed at THIS
        gateway, so cancellation and token delivery follow it over."""
        with self._lock:
            if self._closed:
                raise GatewayClosedError("gateway is draining")
            stream.gateway = self
            if stream._waiting:
                # the waiting-room seat moves with the stream (the
                # source decremented its own count at handoff)
                self._backlog += 1
            self._migrate_in.append((stream, seq))
        self._wake.set()

    def request_migration(self, stream, handoff):
        """Ask the driver to evict ``stream``'s live sequence from this
        engine between steps and call ``handoff(stream, seq)`` — on the
        driver thread — once it is displaced (chain donated, PRNG
        snapshotted; ``seq`` is None when the request never reached the
        engine). The fleet's handoff adopts the pair on a sibling.
        Thread-safe; a no-op for streams that finish first."""
        with self._lock:
            self._migrate_out.append((stream, handoff))
        self._wake.set()

    @property
    def queue_depth(self):
        return self._backlog

    @property
    def closed(self):
        return self._closed

    # ------------------------------------------------------- engine events
    def _leave_waiting_room(self, stream):
        if stream._waiting:
            stream._waiting = False
            with self._lock:
                self._backlog -= 1

    def _on_token(self, seq, token):
        stream = self._live.get(seq.request_id)
        self._m_tokens.inc()
        self._rate.record()
        if stream is None:
            return
        if stream.first_token_time is None:
            stream.first_token_time = time.monotonic()
            self._m_ttft.observe(stream.first_token_time
                                 - stream.submit_time,
                                 **self._class_labels(seq))
            self._leave_waiting_room(stream)
            # TTFT SLO verdict from the ENGINE-clock stamp (not the
            # wall-clock wire latency above): deterministic under an
            # injected clock, so chaos replays count identical misses
            if self._m_slo_miss is not None:
                pclass = getattr(seq, "pclass", None)
                ttft = seq.ttft_s
                if (pclass is not None and pclass.ttft_slo_s is not None
                        and ttft is not None
                        and ttft > pclass.ttft_slo_s):
                    self._m_slo_miss.inc(**{"class": pclass.name,
                                            "slo": "ttft"})
        stream._push_token(token)

    def _finish_teardown(self, seq):
        """Bookkeeping shared by every terminal path — engine finishes
        (:meth:`_on_finish`) and the quarantine's poison conviction
        (:meth:`_fail_poisoned`) — so metrics and quarantine state
        cannot drift between them. Returns the stream (if any) still
        owed its terminal event."""
        stream = self._live.pop(seq.request_id, None)
        self._m_finished.inc(reason=seq.finish_reason)
        # SLO decomposition from the Sequence's engine-clock stamps
        # (None-guarded: a queued timeout was never admitted, a
        # one-token request has no TPOT)
        qw = seq.queue_wait_s
        if qw is not None:
            self._m_queue_wait.observe(qw, **self._class_labels(seq))
        tp = seq.tpot_s
        if tp is not None:
            self._m_tpot.observe(tp, **self._class_labels(seq))
            if self._m_slo_miss is not None:
                pclass = getattr(seq, "pclass", None)
                if (pclass is not None and pclass.tpot_slo_s is not None
                        and tp > pclass.tpot_slo_s):
                    self._m_slo_miss.inc(**{"class": pclass.name,
                                            "slo": "tpot"})
        # quarantine bookkeeping: any terminal outcome clears suspicion
        self._probation.discard(seq.request_id)
        if self._suspect_ids is not None:
            self._suspect_ids.discard(seq.request_id)
        if stream is None:
            return None
        self._leave_waiting_room(stream)  # finished while still queued
        self._m_latency.observe(time.monotonic() - stream.submit_time)
        return stream

    def _on_finish(self, seq):
        stream = self._finish_teardown(seq)
        if stream is not None:
            stream._push_finish(seq.finish_reason)

    def _on_policy_preempt(self, seq):
        """Engine hook: an SLO-urgent request displaced ``seq``. Counts
        by victim class on the gateway-owned counter (monotonic across
        rebuilds — the engine's own policy_preemptions stat rides the
        CARRIED_ENGINE_STATS carry in parallel)."""
        if self._m_policy_preempt is not None:
            pclass = getattr(seq, "pclass", None)
            self._m_policy_preempt.inc(
                victim_class=pclass.name if pclass is not None
                else "unknown")

    # ------------------------------------------------------- driver thread
    def _admit_intake(self):
        while True:
            with self._lock:
                if not self._intake:
                    return
                stream = self._intake.popleft()
            if stream._cancel:
                self._leave_waiting_room(stream)
                self._m_finished.inc(reason="cancelled")
                stream._push_finish("cancelled")
                continue
            try:
                seq = self.engine.submit(stream.request)
            except Exception as e:  # validated at submit(); belt+braces
                self._leave_waiting_room(stream)
                stream._push_error(e)
                continue
            stream.seq = seq
            self._live[seq.request_id] = stream

    def _admit_migrations(self):
        """Driver-side intake of requests adopted from a sibling
        gateway (fleet failover / live migration): a carried Sequence
        re-enters via ``engine.restore`` — recompute from host token
        state + the PRNG snapshot, so the stream continues
        byte-identically — and a bare request (never engine-admitted on
        the source) submits fresh. Cancellation that raced the
        migration is honored here, exactly like the intake path."""
        while True:
            with self._lock:
                if not self._migrate_in:
                    return
                stream, seq = self._migrate_in.popleft()
            if stream._cancel:
                if seq is not None and not seq.done:
                    seq.status = "finished"
                    seq.finish_reason = "cancelled"
                self._leave_waiting_room(stream)
                self._m_finished.inc(reason="cancelled")
                stream._push_finish("cancelled")
                continue
            if seq is None:
                try:
                    seq = self.engine.submit(stream.request)
                except Exception as e:
                    self._leave_waiting_room(stream)
                    stream._push_error(e)
                    continue
            elif seq.done:
                # finished in flight between gateways (shouldn't
                # happen — eviction only hands off live sequences —
                # but a terminal event beats a stranded consumer)
                self._leave_waiting_room(stream)
                self._m_finished.inc(reason=seq.finish_reason)
                stream._push_finish(seq.finish_reason)
                continue
            elif (seq.prompt_len + int(seq.request.max_new_tokens)
                    > self.engine.max_seq_len):
                # belt + braces under the fleet's can_hold selection:
                # an adoption this engine cannot hold to completion
                # must terminate cleanly, never crash the driver
                # mid-recompute (which would count as a fatal fault
                # and cascade a fresh failover of the same sequence)
                self._leave_waiting_room(stream)
                stream._push_error(
                    f"migrated sequence needs "
                    f"{seq.prompt_len + int(seq.request.max_new_tokens)}"
                    f" KV rows; this engine holds "
                    f"{self.engine.max_seq_len}")
                continue
            elif self.engine.restore(seq):
                self._m_recovered.inc()
            stream.seq = seq
            self._live[seq.request_id] = stream

    def _apply_migrate_out(self):
        """Driver-side eviction for live migration: displace each
        requested stream's sequence from this engine (chain donated,
        PRNG snapshotted — ``engine.evict``) and hand the pair to the
        fleet's ``handoff`` on this thread. A failed handoff (sibling
        draining) restores the sequence locally — a migration may be
        refused, but it may never lose a request."""
        while True:
            with self._lock:
                if not self._migrate_out:
                    return
                stream, handoff = self._migrate_out.popleft()
            seq = stream.seq
            if seq is None:
                # still in this gateway's intake (not yet admitted):
                # hand the bare request over instead
                with self._lock:
                    try:
                        self._intake.remove(stream)
                    except ValueError:
                        continue        # finished/cancelled/raced away
                    if stream._waiting:
                        self._backlog -= 1
                try:
                    handoff(stream, None)
                except Exception:
                    with self._lock:
                        if stream._waiting:
                            self._backlog += 1
                        self._intake.append(stream)
                continue
            if seq.done or self._live.get(seq.request_id) is not stream:
                continue                # finished, or already handed off
            if any(p is seq for p in self._parked) or (
                    self._suspect_ids
                    and seq.request_id in self._suspect_ids):
                continue                # mid-bisection: not migratable
            if not self.engine.evict(seq):
                continue
            del self._live[seq.request_id]
            self._probation.discard(seq.request_id)
            if stream._waiting:
                with self._lock:
                    self._backlog -= 1
            try:
                handoff(stream, seq)
            except Exception:
                # refused by the target: re-admit HERE by recompute —
                # the request stays live either way
                if stream._waiting:
                    with self._lock:
                        self._backlog += 1
                if self.engine.restore(seq):
                    self._m_recovered.inc()
                    self._live[seq.request_id] = stream

    def _apply_cancels(self):
        for stream in [s for s in self._live.values() if s._cancel]:
            seq = stream.seq
            parked = next((p for p in self._parked if p is seq), None)
            if parked is not None:
                # bisection-parked: not in any engine, cancel by hand —
                # honoring cancellation DURING recovery is part of the
                # fault-tolerance contract
                self._parked.remove(seq)
                seq.status = "finished"
                seq.finish_reason = "cancelled"
                self._on_finish(seq)
                continue
            self.engine.cancel(seq)         # fires _on_finish

    def _sweep_parked_deadlines(self):
        """Bisection-parked sequences live outside the engine, so its
        per-step deadline sweep cannot see them — a parked request's
        ``timeout_s`` must still be honored here (deadlines share the
        engine's ``time.monotonic`` basis)."""
        if not self._parked:
            return
        now = time.monotonic()
        for seq in [p for p in self._parked
                    if p.deadline is not None and now >= p.deadline]:
            self._parked.remove(seq)
            seq.status = "finished"
            seq.finish_reason = "timeout"
            self._on_finish(seq)

    def _run(self):
        try:
            while True:
                self._arm_capture()
                self._admit_migrations()
                self._admit_intake()
                self._apply_cancels()
                self._apply_migrate_out()
                self._sweep_parked_deadlines()
                self._advance_bisection()
                if self.engine.has_work():
                    self._step_supervised()
                    continue
                with self._lock:
                    drained = (not self._intake and not self._live
                               and not self._parked
                               and not self._migrate_in)
                    if self._closed and drained:
                        return
                # idle is provably not hung: refresh the watchdog
                # timestamp so last_step_age_s / the gauge measure
                # time-stuck-in-a-step, not time-without-traffic (an
                # orchestrator must not kill a healthy idle server)
                self._last_step_done = self._clock()
                self._wake.wait(self.idle_wait_s)
                self._wake.clear()
        except BaseException as e:
            # supervision exhausted (max_restarts, no factory, or a
            # non-Exception). FLEET FAILOVER first: offer every live
            # request — snapshotted exactly like a rebuild's recovery —
            # to the on_fatal hook, which re-admits them on a sibling
            # replica; only requests nobody adopted are stranded. The
            # driver is the only thread that can unblock consumers — it
            # must not strand them mid-result().
            handed = self._failover_handoff()
            with self._lock:
                self._closed = True
                stranded = (list(self._intake) + list(self._live.values())
                            + [st for st, _ in self._migrate_in])
                self._intake.clear()
                self._live.clear()
                self._parked.clear()
                self._migrate_in.clear()
            for s in stranded:
                if id(s) not in handed:
                    s._push_error(f"engine driver died: {e!r}")
            raise

    # ---------------------------------------------------------- supervisor
    def _step_supervised(self):
        """One engine step under supervision: classify any failure,
        retry transients with bounded backoff, rebuild + recover on
        fatal/hung, give up (re-raise, stranding with errors) only past
        ``max_restarts`` or without an ``engine_factory``."""
        t0 = self._clock()
        try:
            # a step that TRACED a new program (first hit of a prefill
            # bucket / decode geometry — routinely tens of seconds on a
            # real chip) is exempt from the watchdog: compile time is
            # not a hang, and classifying it as one would burn the
            # restart budget on healthy cold starts
            traces0 = (self.engine.decode_compilations()
                       + self.engine.prefill_compilations())
            self.engine.step()
            dt = self._clock() - t0
            compiled = (self.engine.decode_compilations()
                        + self.engine.prefill_compilations()) > traces0
            if self.watchdog_deadline_s is not None and not compiled \
                    and dt > self.watchdog_deadline_s:
                raise WatchdogTimeout(
                    f"engine step took {dt:.3f}s, watchdog deadline is "
                    f"{self.watchdog_deadline_s:.3f}s")
        except Exception as e:
            self._on_fault(e)
            return
        self._last_step_done = self._clock()
        self._transient_streak = 0
        self._tick_capture()
        if self._fault_at is not None:
            # first completed step on the rebuilt engine: recovery done
            self.restart_latencies.append(self._clock() - self._fault_at)
            self._fault_at = None
        self._m_step_dur.observe(self.engine.stats["last_step_duration_s"])
        if self._m_spec_len is not None:
            # drain the step's per-span acceptance lengths into the
            # histogram (driver thread is the only reader/writer)
            lens = self.engine.stats["spec_last_accept"]
            if lens:
                for m in lens:
                    self._m_spec_len.observe(m)
                self.engine.stats["spec_last_accept"] = []

    def _classify(self, exc) -> str:
        if isinstance(exc, WatchdogTimeout):
            return "hung"
        if isinstance(exc, self.transient_types):
            return "transient"
        return "fatal"

    def _on_fault(self, exc):
        kind = self._classify(exc)
        self._m_faults.inc(kind=kind)
        tr = self._tr()
        if tr is not None:
            tr.instant(
                "fault", tid=TID_GATEWAY,
                args={"kind": kind, "error": type(exc).__name__,
                      "message": str(exc)[:200]})
        if self._fault_at is None:
            self._fault_at = self._clock()
        if kind == "transient":
            self._transient_streak += 1
            if self._transient_streak <= self.max_transient_retries:
                # retry the SAME engine: injected transients fire at a
                # step boundary, so engine bookkeeping is intact; real
                # ones (a flaky transfer) are worth one cheap retry
                # before paying a rebuild
                time.sleep(self.retry_backoff_s * self._transient_streak)
                return
            self._transient_streak = 0      # escalate: streak is a wedge
        if self.engine_factory is None or self._restarts >= self.max_restarts:
            raise exc
        self._rebuild_and_recover()

    @staticmethod
    def _snapshot_live(engine):
        """The recovery snapshot shared by crash-recovery rebuilds and
        fleet failover: every live slot-holder (arrival order) with a
        best-effort PRNG-walk snapshot — per-slot current keys, so
        sampled continuations restart mid-walk; unreadable device state
        (real crashes can corrupt it) only costs sampled-stream
        identity, recovery itself runs on host token state — plus the
        still-queued sequences. Returns ``(live, queued)``."""
        try:
            keys = np.asarray(engine._keys, np.uint32)
        except Exception:
            keys = None
        live = [s for s in engine._slots if s is not None and not s.done]
        live.sort(key=lambda s: s.request_id)   # arrival order
        for s in live:
            if keys is not None and s.tokens and s.status == "running" \
                    and s.slot is not None:
                s.key = keys[s.slot].copy()
        queued = [s for s in engine.scheduler.queue if not s.done]
        return live, queued

    def _failover_handoff(self) -> frozenset:
        """The dying driver's last act (fleet failover-to-sibling):
        snapshot every live request exactly like a rebuild's recovery
        would and offer the (stream, sequence) pairs to ``on_fatal``.
        The hook returning True means the fleet adopted them onto a
        sibling replica — those streams must NOT be stranded with
        errors. Returns the ids of handed-off streams (empty without a
        hook, on refusal, or if the handoff itself fails — stranding
        is the unchanged fallback)."""
        if self.on_fatal is None:
            return frozenset()
        try:
            live, queued = self._snapshot_live(self.engine)
            seqs = live + queued + [p for p in self._parked
                                    if not p.done]
            pairs, seen = [], set()
            for seq in seqs:
                st = self._live.get(seq.request_id)
                if st is not None and st.finish_reason is None \
                        and not st._cancel:
                    pairs.append((st, seq))
                    seen.add(id(st))
            with self._lock:
                pending = list(self._intake)
                migrating = list(self._migrate_in)
            for st in pending:
                if id(st) not in seen and st.finish_reason is None \
                        and not st._cancel:
                    pairs.append((st, None))
                    seen.add(id(st))
            for st, sq in migrating:
                if id(st) not in seen and st.finish_reason is None \
                        and not st._cancel:
                    pairs.append((st, sq))
                    seen.add(id(st))
            if pairs:
                res = self.on_fatal(self, pairs)
                if res is True:
                    return frozenset(id(st) for st, _ in pairs)
                if res:     # iterable of the streams actually adopted
                    return frozenset(id(st) for st in res)
        except Exception:
            pass        # failover is best-effort; stranding still works
        return frozenset()

    def _rebuild_and_recover(self):
        """Fatal-fault recovery: rebuild the engine and re-enqueue every
        live request by recompute — modulo the poison quarantine, which
        decides who re-enters now, who parks, and (once isolated) who
        is failed as the culprit."""
        self._recovering = True
        tr = self._tr()
        tr0 = tr.now() if tr is not None else None
        old = self.engine
        # bank the dead incarnation's counter stats so every derived
        # /metrics series stays monotonic (CARRIED_ENGINE_STATS). Built
        # aside and swapped in below WITH the new engine — one store —
        # so concurrent scrapes never see base and engine from
        # different epochs.
        base, pc_base, _ = self._counter_state
        new_base = {k: base[k] + old.stats[k]
                    for k in CARRIED_ENGINE_STATS}
        live, queued = self._snapshot_live(old)
        new = self.engine_factory()
        new.on_token = self._on_token
        new.on_finish = self._on_finish
        new.on_policy_preempt = self._on_policy_preempt
        new.tracer = self.tracer     # one timeline across incarnations
        new.cost = self.cost         # one cost account, monotonic too
        if self._fault_hook is not None:
            new.fault_hook = self._fault_hook
        new_pc = dict(pc_base)
        if old.prefix_cache is not None \
                and new.prefix_cache is not old.prefix_cache:
            # bank the dead trie's stats ONLY when the factory built a
            # fresh one (its stats restart at zero). An adopted SHARED
            # PrefixCache instance rides into the new engine with its
            # counts intact — banking those too would double them on
            # every restart.
            for k in CARRIED_PREFIX_STATS:
                new_pc[k] += old.prefix_cache.stats[k]
        self.engine = new
        self._counter_state = (new_base, new_pc, new)   # atomic swap
        self._restarts += 1
        self.last_restart_at = self._clock()    # the /debug/fleet column
        self._m_restarts.inc()
        readmit, culprit = self._quarantine_plan(live)
        recovered = 0
        for s in readmit + queued:
            if new.restore(s):
                self._m_recovered.inc()
                recovered += 1
        self._probation = {s.request_id for s in readmit + queued}
        if tr is not None:
            tr.complete("rebuild", tr0, tid=TID_GATEWAY,
                        args={"restarts": self._restarts,
                              "live": len(live), "queued": len(queued)})
            tr.instant("recovery", tid=TID_GATEWAY,
                       args={"recovered": recovered,
                             "parked": len(self._parked)})
        if culprit is not None:
            self._fail_poisoned(culprit)
        self._recovering = False

    def _quarantine_plan(self, live):
        """Split the recovered slot-holders into (readmit-now, culprit).
        First fault: readmit everyone (they enter probation). A repeat
        fault while probation members are still live starts the
        bisection: suspects are the probation members present at the
        fault; half readmit as the active set, half park. Conviction
        requires RECURRENCE UNDER ACTIVE BISECTION — a fault that
        follows a single-member active set is the poison (fail it,
        unpark everyone) — so two coincidental independent faults can
        shrink an innocent request to sole-suspect, but it is only
        failed if the fault then follows it a further time; otherwise
        it finishes and is exonerated."""
        bisecting = self._suspect_ids is not None
        watched = self._suspect_ids if bisecting else self._probation
        suspects = [s for s in live if s.request_id in watched]
        bystanders = [s for s in live if s.request_id not in watched]
        if not suspects:
            # fault not attributable to any prior readmission (fresh
            # fault, or suspects all finished): plain recovery
            self._suspect_ids = None
            return live, None
        if bisecting and len(suspects) == 1:
            # the fault followed this request through the halvings and
            # recurred on it alone — it is the poison. Everyone parked
            # re-enters.
            culprit = suspects[0]
            readmit = bystanders + self._parked
            self._parked = []
            self._suspect_ids = None
            return readmit, culprit
        half = (len(suspects) + 1) // 2
        active, benched = suspects[:half], suspects[half:]
        self._parked.extend(benched)
        self._suspect_ids = {s.request_id for s in active}
        tr = self._tr()
        if tr is not None:
            tr.instant(
                "bisection", tid=TID_GATEWAY,
                args={"verdict": "halved", "active": len(active),
                      "parked": len(benched)})
        return bystanders + active, None

    def _advance_bisection(self):
        """Driver-loop bookkeeping between steps: when the active
        suspect half has fully drained without re-faulting, it is
        exonerated — the culprit (if any) hides among the parked, so
        half of them re-enter as the next suspects. With nothing parked
        left, the bisection ends (the fault did not recur: poison gone,
        or it was step-pinned rather than request-pinned)."""
        if self._suspect_ids:
            return                  # active half still live — wait
        if not self._parked:
            self._suspect_ids = None
            return
        half = (len(self._parked) + 1) // 2
        batch, self._parked = self._parked[:half], self._parked[half:]
        batch = [s for s in batch if not s.done]
        tr = self._tr()
        if batch and tr is not None:
            tr.instant(
                "bisection", tid=TID_GATEWAY,
                args={"verdict": "reenter", "reentered": len(batch),
                      "parked": len(self._parked)})
        for s in batch:
            if self.engine.restore(s):
                self._m_recovered.inc()
        ids = {s.request_id for s in batch}
        self._suspect_ids = ids if (ids or self._parked) else None
        self._probation |= ids

    def _fail_poisoned(self, seq):
        """Terminate the isolated culprit — the ONLY request a poison
        fault costs. Consumers see ``finish_reason="error"``: SSE gets
        a terminal error event, blocking a JSON 500."""
        seq.status = "finished"
        seq.finish_reason = "error"
        tr = self._tr()
        if tr is not None:
            tr.instant(
                "bisection", tid=TID_GATEWAY,
                args={"verdict": "poisoned",
                      "request_tid": tr.req_tid(seq.request_id)})
            tr.instant("finished", tid=tr.req_tid(seq.request_id),
                       args={"finish_reason": "error"})
        stream = self._finish_teardown(seq)
        if stream is not None:
            stream._push_error(
                "poisoned request: engine fault recurred pinned to this "
                "request; bystanders recovered")

    # ----------------------------------------------------- trace capture
    def _arm_capture(self):
        """Driver-side capture start: a pending window opens at a STEP
        BOUNDARY (top of the driver loop), never mid-step — so every
        step the countdown charges was recorded from its first event
        and the capture holds exactly the asked-for step spans. Arming
        runs under the gateway lock so it cannot race the handler's
        timeout cleanup — an orphaned window must never enable the
        tracer with nobody left to read or stop it."""
        if self._capture is None and self._pcapture is None:
            return                      # lock-free fast path
        with self._lock:
            cap = self._capture
            if cap is not None and not cap["armed"]:
                self.tracer.clear()
                self.tracer.enable()
                cap["armed"] = True
            pc = self._pcapture
            if pc is not None and not pc["armed"] \
                    and self.cost is not None:
                # profile window base: the accounting as of this step
                # boundary — the returned table is exactly the next
                # ``steps`` steps' worth of cost
                pc["base"] = self._profile_snapshot()
                pc["armed"] = True

    def _tick_capture(self):
        """Driver-side capture countdown: called after every completed
        supervised step. When the requested window closes, recording
        stops (unless tracing is persistent) so the capture holds
        exactly the asked-for steps, and the waiting handler wakes.
        Locked for the same reason as :meth:`_arm_capture`; the
        no-capture fast path stays one attribute check."""
        if self._capture is None and self._pcapture is None:
            return                      # lock-free fast path
        with self._lock:
            cap = self._capture
            if cap is not None and cap["armed"]:
                cap["remaining"] -= 1
                if cap["remaining"] <= 0:
                    if not self.trace_persistent:
                        self.tracer.disable()
                    cap["done"].set()
            pc = self._pcapture
            if pc is not None and pc["armed"]:
                pc["remaining"] -= 1
                if pc["remaining"] <= 0 and pc["end"] is None:
                    # freeze the window's END at this exact step
                    # boundary: the driver keeps stepping while the
                    # waiting handler wakes, and those later steps
                    # must not leak into the N-step document
                    pc["end"] = self._profile_snapshot()
                    pc["done"].set()

    def capture_trace(self, steps=32, timeout_s=30.0):
        """Capture ``steps`` engine steps of trace and return the
        Chrome trace document (the ``GET /debug/trace`` body).

        ``steps <= 0`` snapshots the current buffer without touching
        recording state — the natural read when tracing is persistent
        (``trace=True`` / ``--trace``). Otherwise the buffer is
        cleared, recording turns on, and the call blocks until the
        driver completes ``steps`` steps or ``timeout_s`` elapses (an
        idle engine steps nothing — the timeout returns whatever was
        captured, e.g. only gateway events). Captures serialize:
        a second concurrent capture raises :class:`TraceBusyError`.
        Safe from any thread; the driver's arming/countdown and this
        teardown all run under the gateway lock."""
        tr = self.tracer
        if steps <= 0:
            return tr.export()
        # clamp: Event.wait overflows on absurd timeouts, and a capture
        # that outlives any plausible debugging session is a leak
        timeout_s = min(max(float(timeout_s), 0.0), 3600.0)
        with self._lock:
            if self._capture is not None:
                raise TraceBusyError(
                    "a trace capture is already in progress")
            done = threading.Event()
            self._capture = {"remaining": int(steps), "done": done,
                             "armed": False}
        try:
            self._wake.set()
            done.wait(timeout_s)
        finally:
            # unconditional teardown: an exception here must not leave
            # an orphaned window 409-ing every later capture (or the
            # tracer recording with nobody left to stop it)
            with self._lock:
                cap, self._capture = self._capture, None
                if cap is not None and cap["armed"] \
                        and not self.trace_persistent:
                    tr.disable()
        return tr.export()

    # ------------------------------------------------------ cost profile
    def _profile_snapshot(self) -> dict:
        """One consistent reading of the accounting + token count (the
        base or frozen end of a step-bounded window)."""
        return {"cost": self.cost.snapshot_full(),
                "tokens": self._stat("tokens_generated")}

    def profile_doc(self, base=None, window_steps=None, at=None) -> dict:
        """The cost-attribution document (the ``GET /debug/profile``
        body): per-program calls / transfer bytes / compile events /
        wall EWMA / share of the window's wall, phase attribution, and
        the per-decoded-token rates the mega-kernel work is gated on.
        ``base``/``at`` bound the window (prior
        :meth:`_profile_snapshot` readings; None = gateway start /
        now)."""
        co = self.cost
        if co is None:
            raise RuntimeError(
                "cost observatory disabled (gateway built with "
                "cost=False)")
        doc = co.export(base=(base or {}).get("cost"),
                        at=(at or {}).get("cost"))
        tokens = ((at["tokens"] if at is not None
                   else self._stat("tokens_generated"))
                  - (base or {}).get("tokens", 0))
        t = doc["totals"]
        t["decoded_tokens"] = tokens
        t["dispatches_per_decoded_token"] = round(
            t["dispatches"] / max(tokens, 1), 6)
        t["h2d_bytes_per_decoded_token"] = round(
            t["h2d_bytes"] / max(tokens, 1), 3)
        t["d2h_bytes_per_decoded_token"] = round(
            t["d2h_bytes"] / max(tokens, 1), 3)
        doc["window_steps"] = window_steps
        eng = self.engine
        if getattr(eng, "_paged", False):
            # KV columns in BYTES, not blocks (README "Quantized
            # serving"): block counts hide the density story — an int8
            # pool's block is ~4x smaller — so the profile reports the
            # dtype-aware byte footprint (live/trie split from
            # occupancy(), per-block bytes from the pool) alongside
            # the storage dtype and per-token rate.
            # ONE occupancy walk: every byte field below derives from
            # this reading plus the pool's per-block constants
            occ = eng.cache.occupancy()
            kv_b = eng.cache.pool.block_nbytes
            sc_b = eng.cache.pool.scale_block_nbytes
            per_block = kv_b + sc_b
            used = occ["live"] + occ["trie"]
            doc["kv_pool"] = {
                "kv_dtype": eng.kv_dtype,
                # the other two low-precision knobs ride along so the
                # whole "Quantized serving" posture reads off one block
                "quantize_weights": getattr(eng, "quantize_weights",
                                            False),
                "quantize_activations": getattr(
                    eng, "quantize_activations", False),
                "live_bytes": occ["live"] * per_block,
                "trie_bytes": occ["trie"] * per_block,
                "free_bytes": occ["free"] * per_block,
                "used_kv_bytes": used * kv_b,
                "used_scale_bytes": used * sc_b,
                "capacity_bytes": eng.cache.pool.num_blocks * per_block,
                "bytes_per_token": eng.cache.bytes_per_token(),
            }
        if getattr(eng, "tp", 1) > 1:
            # per-layer collective-bytes column (README "Tensor-
            # parallel serving"): annotate the window's all-reduce
            # wire traffic (already delta'd by co.export) per layer
            # and per decoded token, so the EQuARX int8 win reads
            # directly off the profile
            L = max(int(eng.config.num_hidden_layers), 1)
            doc["collectives"] = {
                "tp": eng.tp,
                "per_dtype": {
                    dtype: dict(
                        rec,
                        bytes_per_layer=round(rec["bytes"] / L, 3),
                        bytes_per_decoded_token=round(
                            rec["bytes"] / max(tokens, 1), 3))
                    for dtype, rec in doc.get("collectives", {}).items()
                },
            }
        pc = getattr(eng, "prefix_cache", None)
        if pc is not None and pc.tier is not None:
            # tier columns (README "Tiered KV prefix cache"): the
            # window's spill/readmit/peer traffic (already delta'd by
            # co.export) annotated per decoded token, plus the tier's
            # current occupancy — so tier pressure reads directly off
            # the profile without touching the per-program baselines
            doc["tiers"] = {
                "host_tier_bytes": pc.host_tier_bytes,
                "tier_blocks": pc.tier.num_blocks,
                "tier_bytes": pc.tier.bytes_used,
                "per_direction": {
                    d: dict(
                        rec,
                        bytes_per_decoded_token=round(
                            rec["bytes"] / max(tokens, 1), 3))
                    for d, rec in doc.get("tiers", {}).items()
                },
            }
        return doc

    def capture_profile(self, steps=0, timeout_s=30.0) -> dict:
        """Aggregate cost attribution (``steps <= 0``: everything since
        gateway start), or a STEP-BOUNDED window: block until the
        driver completes ``steps`` engine steps and return only that
        window's costs — the same arm-at-a-step-boundary /
        count-completed-steps machinery as :meth:`capture_trace`, and
        the same serialization rule (a second concurrent window raises
        :class:`TraceBusyError` → HTTP 409)."""
        if self.cost is None:
            raise RuntimeError(
                "cost observatory disabled (gateway built with "
                "cost=False)")
        if steps <= 0:
            return self.profile_doc()
        timeout_s = min(max(float(timeout_s), 0.0), 3600.0)
        with self._lock:
            if self._pcapture is not None:
                raise TraceBusyError(
                    "a profile capture is already in progress")
            done = threading.Event()
            self._pcapture = {"remaining": int(steps), "done": done,
                              "armed": False, "base": None,
                              "end": None, "steps": int(steps)}
        try:
            self._wake.set()
            done.wait(timeout_s)
        finally:
            with self._lock:
                pc, self._pcapture = self._pcapture, None
                if pc["end"] is None:
                    # timed out mid-window: freeze the end NOW, under
                    # the lock, so it is consistent with `remaining`
                    pc["end"] = self._profile_snapshot()
        # report the steps the window actually captured, not the ask: a
        # timed-out capture (slow engine, or a window that never armed
        # because the driver is idle/dead) must not label lifetime or
        # partial totals as an N-step window — per-step rates derived
        # from the document would be silently off. A never-armed window
        # captured NOTHING: its base is its end (empty deltas), never
        # the lifetime aggregate with a 0-step label.
        armed = pc["base"] is not None
        completed = (min(pc["steps"] - max(pc["remaining"], 0),
                         pc["steps"]) if armed else 0)
        doc = self.profile_doc(base=pc["base"] if armed else pc["end"],
                               window_steps=completed, at=pc["end"])
        doc["window_steps_requested"] = pc["steps"]
        doc["window_truncated"] = completed < pc["steps"]
        return doc

    # ------------------------------------------------------ debug surface
    def request_table(self) -> list:
        """Live request table (the ``GET /debug/requests`` body): one
        row per in-flight request — state, slot, token progress,
        queue-wait, TTFT, TPOT-so-far and KV footprint. Reads host
        bookkeeping the driver thread writes (ints/short lists under
        the GIL — same discipline as the scrape-time gauges)."""
        eng = self.engine
        now = eng._clock()
        with self._lock:
            pending = list(self._intake)
            live = list(self._live.values())
        parked_ids = {id(p) for p in self._parked}
        rows = []
        wall = time.monotonic()
        for st in pending:
            # class + TTFT-deadline slack (README "Multi-tenant SLO
            # serving"): pending requests resolve against the live
            # class table (they passed validate at submit, so this
            # cannot raise); slack counts down on the same wall wait
            # the row's queue_wait_s shows
            pclass = eng.classes.resolve(st.request.priority_class)
            slack = (None if pclass.ttft_slo_s is None else
                     round(pclass.ttft_slo_s - (wall - st.submit_time), 6))
            rows.append({"id": st.id, "state": "pending", "slot": None,
                         "class": pclass.name,
                         "prompt_tokens": len(st.request.prompt),
                         "generated_tokens": 0,
                         "max_new_tokens": int(st.request.max_new_tokens),
                         # wait-so-far on the gateway wall clock (the
                         # engine has not seen this request yet, so no
                         # engine-clock stamp exists) — the longest
                         # waiters are exactly the rows an operator
                         # inspecting a saturated server looks for
                         "queue_wait_s": round(wall - st.submit_time, 6),
                         "ttft_s": None,
                         "tpot_s": None, "kv_tokens": 0,
                         "kv_blocks": None,
                         "launches": 0, "kv_bytes": 0,
                         "slo_slack_s": slack})
        for st in live:
            seq = st.seq
            slot = seq.slot
            qw = seq.queue_wait_s
            if qw is None and seq.t_submit is not None:
                qw = now - seq.t_submit          # still waiting: so far
            tpot = seq.tpot_s
            if tpot is None and seq.t_first_token is not None \
                    and len(seq.tokens) > 1 \
                    and seq.t_last_token is not None:
                # TPOT-so-far from the LAST ACCEPTED token's stamp, not
                # the live clock: mid-step the token count is frozen at
                # the previous host-accept while `now` keeps advancing,
                # so a clock-based numerator inflates for the whole
                # step — n ticks of it under multi-tick decode — then
                # snaps back. Stamp-over-stamp stays consistent however
                # long the device runs between syncs.
                tpot = (seq.t_last_token - seq.t_first_token) \
                    / (len(seq.tokens) - 1)
            kv_tokens, kv_blocks, kv_bytes = 0, None, 0
            if slot is not None:
                kv_tokens = int(eng.cache.lengths[slot])
                kv_bytes = eng.cache.slot_kv_bytes(slot)
                if getattr(eng, "_paged", False):
                    kv_blocks = len(eng.cache.slot_block_ids(slot))
            # TTFT-deadline slack on the engine clock: settled once the
            # first token landed (negative = the miss already counted),
            # counting down from the wait-so-far while still queued
            pclass = seq.pclass
            slack = None
            if pclass is not None and pclass.ttft_slo_s is not None:
                waited = seq.ttft_s
                if waited is None and seq.t_submit is not None:
                    waited = now - seq.t_submit
                if waited is not None:
                    slack = round(pclass.ttft_slo_s - waited, 6)
            rows.append({
                "id": st.id,
                "state": ("parked" if id(seq) in parked_ids
                          else seq.status),
                "slot": slot,
                "class": (pclass.name if pclass is not None
                          else eng.classes.default),
                "prompt_tokens": seq.prompt_len,
                "generated_tokens": len(seq.tokens),
                "max_new_tokens": int(seq.request.max_new_tokens),
                "queue_wait_s": None if qw is None else round(qw, 6),
                "ttft_s": (None if seq.ttft_s is None
                           else round(seq.ttft_s, 6)),
                "tpot_s": None if tpot is None else round(tpot, 6),
                "kv_tokens": kv_tokens,
                "kv_blocks": kv_blocks,
                # cost columns (README "Cost attribution &
                # /debug/profile"): device launches this request has
                # ridden so far, and the HBM bytes its KV currently
                # holds (paged: blocks x block bytes; dense: rows x
                # row bytes)
                "launches": seq.launches,
                "kv_bytes": kv_bytes,
                "slo_slack_s": slack,
            })
        return rows

    # ------------------------------------------------------ health surface
    @property
    def running_slots(self) -> int:
        """Slots actively decoding (the ``/healthz`` saturation view)."""
        return sum(1 for s in self.engine._slots
                   if s is not None and s.status == "running")

    @property
    def prefilling_slots(self) -> int:
        """Slots held by mid-chunked-prefill sequences."""
        return sum(1 for s in self.engine._slots
                   if s is not None and s.status == "prefilling")

    @property
    def restarts(self) -> int:
        return self._restarts

    def last_step_age(self) -> float:
        """Seconds since the last completed engine step (the watchdog's
        external visibility — grows without bound while a step is hung)."""
        return max(0.0, self._clock() - self._last_step_done)

    @property
    def health_state(self) -> str:
        """``ok`` | ``degraded`` | ``recovering`` | ``draining`` — the
        ``/healthz`` status. ``recovering``: an engine rebuild or a
        poison bisection is in progress (parked requests exist or a
        suspect half is live). ``degraded``: serving, but the last
        recovery's readmissions have not all finished yet (probation)
        or a transient-retry streak is active."""
        if self._closed:
            return "draining"
        if self._recovering or self._parked or self._suspect_ids:
            return "recovering"
        if self._probation or self._transient_streak:
            return "degraded"
        return "ok"

    # ------------------------------------------------------------ shutdown
    def shutdown(self, drain=True, timeout=None):
        """Close the front door; ``drain=True`` lets in-flight and
        queued work finish, ``drain=False`` cancels it. Blocks until the
        driver exits (or ``timeout``). Returns True if it did."""
        with self._lock:
            self._closed = True
            streams = ([] if drain else
                       list(self._intake) + list(self._live.values()))
        for s in streams:
            s._cancel = True
        self._wake.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
        atexit.unregister(self._atexit_hook)
        return not self._thread.is_alive()
