"""OpenAI-style HTTP front-end over :class:`ServingGateway`.

Stdlib only (``http.server`` on a thread-per-connection
``ThreadingHTTPServer``) — no new dependencies; the heavy lifting is
the gateway's single engine-driver thread, so handler threads only
parse JSON, block on token queues, and write bytes.

Endpoints:

- ``POST /v1/completions`` — body ``{"prompt": [token ids], ...}``.
  Blocking by default (one JSON response), per-token SSE with
  ``"stream": true`` (``data: {...}`` chunks, then ``data: [DONE]``).
  This framework ships no tokenizer, so prompts and completions are
  token-id arrays — the ``choices[].token_ids`` field stands in for
  OpenAI's ``text``.
- ``GET /healthz`` — liveness + drain state + slot/queue occupancy,
  including the saturation view (running/prefilling slot counts and
  waiting-room occupancy vs capacity) so an orchestrator can make
  scale-out decisions without parsing ``/metrics``.
- ``GET /metrics`` — Prometheus text exposition
  (``profiler.metrics.MetricsRegistry``).
- ``GET /debug/trace?steps=N`` — capture ``N`` engine steps of
  request-lifecycle/step-phase trace and return Chrome trace-event
  JSON (load in Perfetto; README "Tracing & debugging").
  ``steps=0`` snapshots the current buffer (the persistent ``--trace``
  mode's read); a concurrent capture gets 409.
- ``GET /debug/requests`` — live request table: per-request state,
  slot, token progress, queue-wait/TTFT/TPOT-so-far, KV footprint plus
  the cost columns (device launches ridden, KV bytes held).
- ``GET /debug/profile`` — the cost observatory's aggregated
  cost-attribution table (per-program dispatches, host<->device bytes,
  compile events, wall EWMA / share of wall, per-decoded-token rates;
  README "Cost attribution & /debug/profile"). ``steps=N`` bounds the
  window to the next N engine steps like ``/debug/trace``; a
  concurrent window gets 409.

Load shedding maps gateway signals onto status codes: full waiting
room → 429 (with Retry-After), draining gateway → 503, validation →
400. A client that disconnects mid-SSE cancels its request — the
broken-pipe write error reaches ``TokenStream.cancel()``, the engine
frees the KV slot at the next step boundary, and the remaining
streams are untouched.
"""
from __future__ import annotations

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from ..request import GenerationRequest
from .gateway import (GatewayClosedError, QueueFullError, ServingGateway,
                      TraceBusyError)

SSE_HEADERS = (("Content-Type", "text/event-stream"),
               ("Cache-Control", "no-cache"),
               ("Connection", "close"))


def _completion_body(stream, token_ids, finish_reason, model_name,
                     prompt_tokens):
    return {
        "id": stream.id,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model_name,
        "choices": [{
            "index": 0,
            "token_ids": [int(t) for t in token_ids],
            "finish_reason": finish_reason,
        }],
        "usage": {
            "prompt_tokens": prompt_tokens,
            "completion_tokens": len(token_ids),
            "total_tokens": prompt_tokens + len(token_ids),
        },
    }


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "paddle-tpu-serving/1.0"

    # ------------------------------------------------------------- helpers
    @property
    def gateway(self) -> ServingGateway:
        return self.server.gateway

    @property
    def fleet(self):
        """The engine fleet when this server fronts one (README
        "Engine fleet"), else None — single-engine servers keep the
        exact pre-fleet surface."""
        return getattr(self.server, "fleet", None)

    def log_message(self, fmt, *args):  # route through the server hook
        if self.server.log_fn is not None:
            self.server.log_fn(fmt % args)

    def _send_json(self, code, obj, extra_headers=()):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code, message, etype, extra_headers=()):
        self._send_json(code, {"error": {"message": message,
                                         "type": etype}}, extra_headers)

    # ----------------------------------------------------------------- GET
    def do_GET(self):
        path, _, query = self.path.partition("?")
        if self.fleet is not None:
            self._do_get_fleet(path, query)
            return
        if path == "/healthz":
            gw = self.gateway
            st = gw.health_state    # ok|degraded|recovering|draining
            self._send_json(503 if st == "draining" else 200, {
                "status": st,
                "active_slots": gw.engine.num_active,
                "num_slots": gw.engine.num_slots,
                # saturation view: how the held slots split between
                # decode and chunked prefill, and how full the bounded
                # waiting room is — enough for an orchestrator to see
                # "at capacity and queueing" without scraping /metrics
                "running_slots": gw.running_slots,
                "prefilling_slots": gw.prefilling_slots,
                "queue_depth": gw.queue_depth,
                "waiting_room_occupancy": gw.queue_depth,
                "waiting_room_capacity": gw.max_queue,
                # the supervisor's watchdog, externally visible: a step
                # that never returns can only be seen from out here
                "last_step_age_s": round(gw.last_step_age(), 3),
                "engine_restarts": gw.restarts,
            })
        elif path == "/debug/trace":
            qs = parse_qs(query)
            # persistent (--trace) servers default to a SNAPSHOT: a
            # parameterless probe must never clear hours of recorded
            # history — opening a fresh window there takes an explicit
            # steps=N
            default_steps = "0" if self.gateway.trace_persistent \
                else "32"
            try:
                steps = int(qs.get("steps", [default_steps])[0])
                timeout_s = float(qs.get("timeout_s", ["30"])[0])
            except ValueError as e:
                self._error(400, f"bad query parameter: {e}",
                            "invalid_request")
                return
            try:
                doc = self.gateway.capture_trace(steps=steps,
                                                 timeout_s=timeout_s)
            except TraceBusyError as e:
                self._error(409, str(e), "conflict")
                return
            self._send_json(200, doc)
        elif path == "/debug/profile":
            qs = parse_qs(query)
            try:
                steps = int(qs.get("steps", ["0"])[0])
                timeout_s = float(qs.get("timeout_s", ["30"])[0])
            except ValueError as e:
                self._error(400, f"bad query parameter: {e}",
                            "invalid_request")
                return
            try:
                doc = self.gateway.capture_profile(steps=steps,
                                                   timeout_s=timeout_s)
            except TraceBusyError as e:
                self._error(409, str(e), "conflict")
                return
            except RuntimeError as e:   # cost observatory disabled
                self._error(404, str(e), "unavailable")
                return
            self._send_json(200, doc)
        elif path == "/debug/requests":
            gw = self.gateway
            self._send_json(200, {
                "requests": gw.request_table(),
                "num_slots": gw.engine.num_slots,
                "queue_depth": gw.queue_depth,
                "tracing": gw.tracer.enabled,
            })
        elif path == "/metrics":
            body = self.gateway.registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._error(404, f"no route for GET {path}", "invalid_request")

    # ----------------------------------------------------------- GET/fleet
    def _do_get_fleet(self, path, query):
        """The fleet server's GET surface (README "Engine fleet"):
        ``/healthz`` aggregates replica states, ``/metrics`` renders
        the ONE shared registry (every series ``replica``-labeled),
        ``/debug/fleet`` is the per-replica operations table,
        ``/debug/requests`` merges the replica tables with a
        ``replica`` column, ``/debug/trace`` snapshots the merged
        fleet+replica timeline (step-bounded windows are a
        single-engine feature — the N drivers share no step counter),
        ``/debug/profile`` returns per-replica cost attribution plus
        fleet totals, and ``/fleet/cacheplane`` is the distributed
        prefix-cache surface (per-replica tier occupancy/digests plus
        host-to-host transfer totals)."""
        fl = self.fleet
        if path == "/healthz":
            st = fl.health_state
            self._send_json(503 if st == "draining" else 200, {
                "status": st,
                "replicas": [{
                    "replica": r.index, "state": r.state,
                    "active_slots": r.gateway.engine.num_active,
                    "num_slots": r.gateway.engine.num_slots,
                    "queue_depth": r.gateway.queue_depth,
                    "last_step_age_s":
                        round(r.gateway.last_step_age(), 3),
                    "engine_restarts": r.gateway.restarts,
                } for r in fl.replicas],
                "routable_replicas": len(fl._routable()),
                "num_replicas": len(fl.replicas),
                "router": fl.router.name,
            })
        elif path == "/metrics":
            body = fl.registry.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif path == "/debug/fleet":
            self._send_json(200, {"replicas": fl.fleet_table(),
                                  "router": fl.router.name,
                                  "health": fl.health_state})
        elif path == "/debug/requests":
            rows = []
            for rep in fl.replicas:
                for row in rep.gateway.request_table():
                    rows.append({**row, "replica": rep.index})
            self._send_json(200, {
                "requests": rows,
                "num_replicas": len(fl.replicas),
                "queue_depth": sum(r.gateway.queue_depth
                                   for r in fl.replicas)})
        elif path == "/debug/trace":
            self._send_json(200, fl.trace_doc())
        elif path == "/debug/profile":
            self._send_json(200, fl.profile_doc())
        elif path == "/fleet/cacheplane":
            self._send_json(200, fl.cache_plane_doc())
        else:
            self._error(404, f"no route for GET {path}",
                        "invalid_request")

    # ---------------------------------------------------------------- POST
    def do_POST(self):
        path = self.path.split("?", 1)[0]
        if self.fleet is not None and path in ("/fleet/drain",
                                               "/fleet/rebalance"):
            self._do_post_fleet(path)
            return
        if path != "/v1/completions":
            self._error(404, f"no route for POST {path}", "invalid_request")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            self._error(400, f"invalid JSON body: {e}", "invalid_request")
            return
        try:
            request = self._build_request(payload)
            # the fleet front door routes (least-loaded / affinity /
            # round-robin) and sheds sideways on a full replica; the
            # single-engine path is untouched
            front = self.fleet if self.fleet is not None else self.gateway
            stream = front.submit(request)
        except QueueFullError as e:
            self._error(429, str(e), "rate_limit",
                        extra_headers=(("Retry-After", "1"),))
            return
        except GatewayClosedError as e:
            self._error(503, str(e), "unavailable")
            return
        except (TypeError, ValueError) as e:
            self._error(400, str(e), "invalid_request")
            return
        prompt_tokens = len(request.prompt)
        if payload.get("stream", False):
            self._stream_response(stream, prompt_tokens)
            return
        # blocking path. A client that disconnects mid-generation is only
        # detectable at write time (no socket monitoring while blocked in
        # result()), so the sequence runs to completion either way — use
        # "stream": true (or timeout_s) when abandonment must free the
        # slot early.
        try:
            ids, reason = stream.result()
        except RuntimeError as e:
            # request failed engine-side (poisoned request isolated by
            # the recovery bisection, or the driver died): a PROPER
            # terminal response, never a stranded connection — the 500
            # body carries finish_reason="error" plus whatever tokens
            # streamed before the fault
            try:
                self._send_json(500, {
                    "id": stream.id,
                    "object": "text_completion",
                    "model": self.server.model_name,
                    "error": {"message": str(e), "type": "server_error"},
                    "choices": [{
                        "index": 0,
                        "token_ids": [int(t) for t in stream.tokens()],
                        "finish_reason": "error",
                    }]})
            except OSError:
                pass
            return
        try:
            self._send_json(200, _completion_body(
                stream, ids, reason, self.server.model_name, prompt_tokens))
        except (BrokenPipeError, ConnectionResetError):
            self.close_connection = True  # client gone; work already done

    def _do_post_fleet(self, path):
        """Fleet operations endpoints: ``POST /fleet/drain`` body
        ``{"replica": i}`` (add ``"undrain": true`` to return it to
        rotation) migrates a replica's live work to siblings and takes
        it out of routing; ``POST /fleet/rebalance`` (optional body
        ``{"max_moves": n}``) sheds the hottest replica's youngest
        requests to the coolest."""
        fl = self.fleet
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            self._error(400, f"invalid JSON body: {e}", "invalid_request")
            return
        try:
            if path == "/fleet/drain":
                idx = int(payload["replica"])
                if not 0 <= idx < len(fl.replicas):
                    raise ValueError(f"no replica {idx}")
                if payload.get("undrain"):
                    fl.undrain_replica(idx)
                    self._send_json(200, {"replica": idx,
                                          "state": "accepting"})
                    return
                moved = fl.drain_replica(idx)
                self._send_json(200, {"replica": idx,
                                      "state": "draining",
                                      "migrations_requested": moved})
            else:
                moved = fl.rebalance(
                    max_moves=int(payload.get("max_moves", 8)))
                self._send_json(200, {"migrations_requested": moved})
        except (KeyError, TypeError, ValueError) as e:
            self._error(400, str(e), "invalid_request")

    def _build_request(self, p):
        prompt = p.get("prompt")
        if not isinstance(prompt, (list, tuple)) or \
                not all(isinstance(t, int) for t in prompt):
            raise ValueError(
                "'prompt' must be a list of token ids (this server is "
                "tokenizer-free); got "
                f"{type(prompt).__name__}")
        kw = {}
        if p.get("timeout_s") is not None:
            kw["timeout_s"] = float(p["timeout_s"])
        # priority class (README "Multi-tenant SLO serving"): body field
        # wins, the X-Priority-Class header covers clients whose SDK
        # cannot add body fields (a proxy can inject the header). An
        # unknown name raises ValueError inside gateway.submit's
        # validate — the 400 path below — never a driver crash.
        pclass = p.get("priority_class")
        if pclass is None:
            pclass = self.headers.get("X-Priority-Class")
        if pclass is not None:
            kw["priority_class"] = str(pclass)
        eos = p.get("eos_token_id", p.get("stop_token_id"))
        return GenerationRequest(
            prompt=list(prompt),
            max_new_tokens=int(p.get("max_tokens", 16)),
            temperature=float(p.get("temperature", 0.0)),
            top_k=int(p.get("top_k", 0)),
            eos_token_id=None if eos is None else int(eos),
            seed=None if p.get("seed") is None else int(p["seed"]),
            **kw)

    def _stream_response(self, stream, prompt_tokens):
        self.send_response(200)
        for k, v in SSE_HEADERS:
            self.send_header(k, v)
        self.end_headers()

        def event(obj):
            data = obj if isinstance(obj, str) else json.dumps(obj)
            self.wfile.write(f"data: {data}\n\n".encode())
            self.wfile.flush()

        try:
            for token in stream:
                event({"id": stream.id, "object": "text_completion.chunk",
                       "model": self.server.model_name,
                       "choices": [{"index": 0, "token_id": int(token),
                                    "finish_reason": None}]})
            event({"id": stream.id, "object": "text_completion.chunk",
                   "model": self.server.model_name,
                   "choices": [{"index": 0, "token_id": None,
                                "finish_reason": stream.finish_reason}],
                   "usage": {"prompt_tokens": prompt_tokens,
                             "completion_tokens": len(stream.tokens()),
                             "total_tokens":
                                 prompt_tokens + len(stream.tokens())}})
            event("[DONE]")
        except (BrokenPipeError, ConnectionResetError, socket.timeout):
            # client went away mid-stream: free the KV slot, leave the
            # rest of the batch untouched
            stream.cancel()
        except RuntimeError as e:
            # engine-side failure: a FINAL terminal error event (with
            # finish_reason="error") so the client sees a proper end of
            # stream, never a silently dropped connection
            try:
                event({"id": stream.id, "object": "text_completion.chunk",
                       "model": self.server.model_name,
                       "choices": [{"index": 0, "token_id": None,
                                    "finish_reason": "error"}],
                       "error": {"message": str(e),
                                 "type": "server_error"}})
                event("[DONE]")
            except OSError:
                pass
        finally:
            self.close_connection = True


class ServingHTTPServer:
    """Owns the ThreadingHTTPServer + its accept-loop thread.

    ``port=0`` binds an ephemeral port (tests); read it back from
    ``.port``. ``shutdown(drain=True)`` closes the gateway's front door,
    waits for in-flight sequences, then stops accepting.
    """

    def __init__(self, gateway, host="127.0.0.1", port=8000,
                 model_name="paddle-tpu-llama", log_fn=None, fleet=None):
        if (gateway is None) == (fleet is None):
            raise ValueError(
                "pass exactly one of gateway (single engine) or fleet")
        self.gateway = gateway
        self.fleet = fleet
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.gateway = gateway
        self._httpd.fleet = fleet
        self._httpd.model_name = model_name
        self._httpd.log_fn = log_fn
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.05},
            name="http-accept", daemon=True)

    @property
    def host(self):
        return self._httpd.server_address[0]

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"

    def start(self):
        self._thread.start()
        return self

    def shutdown(self, drain=True, timeout=None):
        """Graceful stop: close the front door (new completions 503),
        drain (or cancel) in-flight work, then stop the accept loop."""
        front = self.fleet if self.fleet is not None else self.gateway
        front.shutdown(drain=drain, timeout=timeout)
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread.is_alive():
            self._thread.join(timeout)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False


def serve(model, host="127.0.0.1", port=8000, num_slots=8,
          max_seq_len=None, decode_chunk=1, max_queue=64,
          model_name=None, registry=None, log_fn=None, start=True,
          prefix_cache=False, prefix_blocks=None, prefix_block_size=32,
          paged_attn=True, prefill_chunk=512, ragged_step=True,
          headroom_mult=2.0, watchdog_deadline_s=30.0, max_restarts=8,
          fault_hook=None, clock=None, spec_decode=False, spec_k=4,
          drafter=None, trace=False, trace_buffer=65536, cost=True,
          decode_ticks=1, kv_dtype=None, quantize_weights=False,
          quantize_activations=False,
          tp=1, collective_dtype="fp", host_tier_bytes=0,
          classes=None, slo_ttft_ms=None, slo_tpot_ms=None,
          fused_tick=False, collective_overlap=False):
    """Build engine → gateway → HTTP server and start listening.

    ``decode_chunk=1`` is the serving default: chunk fusion trades
    per-token latency for dispatch amortization, the wrong trade when
    tokens stream to a client (and it keeps the compiled decode
    step-size set at exactly one program). ``prefix_cache=True`` turns
    on automatic prefix caching (README "Automatic prefix caching");
    its hit/miss/eviction counters and the ``kv_prefix_blocks`` gauge
    land on ``GET /metrics``. ``paged_attn=True`` (the default) serves
    from the block-table paged KV cache (README "Paged attention") —
    prefix hits install zero-copy and ``/metrics`` grows the
    ``kv_blocks_shared`` and ``kv_block_table_fill`` gauges; pass
    ``paged_attn=False`` for the legacy dense per-slot cache.
    ``prefill_chunk`` (default 512 tokens, paged only; ``0``/``None``
    disables) interleaves long cold-prompt prefills with decode steps
    so one long prompt can't stall every streaming client — the
    ``serving_ttft_seconds`` histogram and
    ``serving_prefill_chunks_total`` counter on ``/metrics`` watch it
    (README "Chunked prefill"). ``ragged_step=True`` (the default on
    the paged engine) runs decode rows and prefill chunks through ONE
    unified ragged program per step, with the per-step chunk grant
    adapted from the measured throughput EWMA scaled by
    ``headroom_mult`` (README "Unified ragged attention";
    ``headroom_mult=None`` pins fixed-cap pacing) — the
    ``serving_step_duration_seconds`` histogram,
    ``serving_step_tokens`` and ``serving_prefill_headroom_tokens``
    gauges on ``/metrics`` watch exactly the signals the budget reads.

    The driver is SUPERVISED (README "Fault tolerance & chaos
    testing"): a step fault is classified transient/fatal/hung, and a
    fatal one rebuilds the engine through the factory below — same
    config, same shared jit cache, so recovery re-traces nothing — and
    recovers every in-flight request by recompute.
    ``watchdog_deadline_s`` bounds a step's duration before it is
    classified hung (``0``/``None`` disables); ``max_restarts`` bounds
    the rebuild budget; ``fault_hook`` threads a
    :class:`~..faults.FaultPlan` through every engine incarnation (the
    chaos-testing entry point — pass the plan's
    :class:`~..faults.VirtualClock` as ``clock`` too when it carries
    ``hung`` faults, since the watchdog measures step durations on this
    clock). ``/healthz`` reports
    ``ok|degraded|recovering|draining`` plus ``last_step_age_s``, and
    ``/metrics`` grows ``serving_faults_total{kind}``,
    ``serving_engine_restarts_total``, ``serving_preemptions_total``
    and ``serving_recovered_requests_total``.

    ``spec_decode=True`` (paged only, default OFF) turns on
    speculative multi-token decode (README "Speculative decoding"):
    ``spec_k`` bounds the draft length, ``drafter`` overrides the
    default prompt-lookup :class:`~..drafter.NgramDrafter` (the one
    instance is shared by every engine rebuild — drafters are
    stateless policy). Token streams are byte-identical to
    speculation off; ``/metrics`` grows
    ``serving_spec_proposed_total`` / ``serving_spec_accepted_total``,
    the ``serving_spec_accept_length`` histogram and the
    ``serving_spec_launches_per_accepted_token`` gauge.

    Tracing (README "Tracing & debugging"): the gateway always carries
    a :class:`~paddle_tpu.profiler.tracing.SpanTracer` with a
    ``trace_buffer``-event ring; ``trace=True`` records from startup
    (request-lifecycle spans, engine step phases, supervisor fault/
    rebuild instants), otherwise the tracer sits disabled at zero cost
    until ``GET /debug/trace?steps=N`` opens a capture window.
    ``GET /debug/requests`` serves the live request table either way,
    and the per-request TTFT/TPOT/queue-wait decomposition lands on
    ``/metrics`` as ``serving_tpot_seconds`` /
    ``serving_queue_wait_seconds``.

    ``decode_ticks > 1`` (unified ragged engine only, default 1 so
    every banked baseline stays an A/B away) turns on multi-tick
    decode (README "Multi-tick decode"): when every running slot is in
    pure decode the engine fuses up to ``decode_ticks`` on-device
    ticks behind ONE host sync, with EOS/budget retirement masked
    inside the program — streams stay byte-identical, the host
    round-trip is amortized n-fold, and mixed traffic clamps back to
    single-tick so TTFT never regresses. ``/metrics`` grows the
    ``serving_decode_ticks_per_sync`` gauge; the
    ``serving_dispatches_per_decoded_token`` headline drops
    proportionally (DISPATCH_BENCH.json banks the ladder). Note the
    trade: a streaming client sees tokens in bursts of up to
    ``decode_ticks``.

    ``kv_dtype="int8"`` (unified ragged paged engine only, default
    None so every banked baseline stays byte-identical) serves from
    the int8 block-quantized KV pool (README "Quantized serving"):
    appends quantize on write with per-row-per-head fp32 scale planes
    riding the same physical blocks, the attention kernels upcast
    in-register after the table-indirect DMA, and pool HBM drops ~4x
    vs fp32 — the density win DENSITY_BENCH.json banks.
    ``kv_dtype="fp8"`` stores ``float8_e4m3fn`` instead with
    per-BLOCK scale planes (constant 1.0 — e4m3's exponent is the
    per-value scale), cutting scale bytes per cached token
    ``block_size``-fold vs int8's per-row planes and making the
    append path a saturating cast. ``/metrics`` grows
    ``kv_pool_bytes{kind="kv|scales"}`` and
    ``serving_kv_bytes_per_token``; ``/debug/profile`` reports the
    pool in bytes. ``quantize_weights=True`` additionally routes the
    decode-path projection matmuls through int8 weight-only storage
    (converted once per model — rebuilds and fleet replicas share the
    converted arrays and the jit cache, so
    ``decode_compilations()==1`` holds across restarts).
    ``quantize_activations=True`` (requires ``quantize_weights``)
    upgrades those projections to int8xint8: each projection input is
    quantized per-row at runtime and contracted against the int8
    weights with int32 accumulate, so the per-layer weight dequant
    disappears from the decode step entirely (greedy divergence
    measured in DENSITY_BENCH.json, not assumed).

    ``tp=N`` (unified ragged paged engine only, default 1) serves
    tensor-parallel over an N-device heads-sharded mesh (README
    "Tensor-parallel serving"): every serving program runs under
    shard_map with the paged KV pool partitioned per shard, one
    all-reduce pair per layer is the only cross-chip traffic, and
    ``collective_dtype="int8"`` runs that pair EQuARX-style
    block-quantized (~3.5x fewer wire bytes, divergence measured in
    TP_BENCH.json). ``/metrics`` grows
    ``serving_collective_bytes_total{dtype}``; ``/debug/profile``
    gains the per-layer collective-bytes section. On CPU develop with
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N``.

    ``host_tier_bytes=N`` (prefix-cache engines only, default 0 so
    every banked baseline stays byte-identical) backs the prefix trie
    with a host-RAM spill tier (README "Tiered KV prefix cache"):
    evicted chains spill device→host under this byte budget with
    their own LRU, and a later lookup that lands on a spilled chain
    streams it back h2d and readmits through the normal allocation
    path — streams byte-identical to the tier off, no new jit keys.
    ``/metrics`` grows the ``serving_prefix_*`` tier counters/gauges
    and ``serving_tier_bytes_total{direction}``; ``/debug/profile``
    gains the tiers section.

    ``classes`` (default None — single neutral class, every banked
    baseline byte-identical) turns on multi-tenant SLO policy (README
    "Multi-tenant SLO serving"): a comma list of
    ``name[*][:reserved_slots]`` entries, highest priority first, with
    ``slo_ttft_ms`` / ``slo_tpot_ms`` aligned per-class target lists
    (0 = no target). Requests pick a tier via the ``priority_class``
    body field or ``X-Priority-Class`` header (unknown name = 400);
    admission orders by (class rank, TTFT slack), reserved headroom is
    honored, and an urgent latency-class request preempts
    strictly-lower-class running work by recompute — streams stay
    byte-identical. ``/metrics`` grows the ``class`` label on the
    latency histograms plus ``serving_slo_misses_total{class,slo}``
    and ``serving_policy_preemptions_total{victim_class}``.
    """
    from ..engine import ContinuousBatchingEngine
    from ..policy import ClassTable
    priority_classes = None if classes is None else ClassTable.parse(
        classes, slo_ttft_ms=slo_ttft_ms, slo_tpot_ms=slo_tpot_ms)

    def engine_factory():
        # one factory builds the first engine AND every recovery
        # rebuild: identical config, and the model-level jit cache is
        # shared, so a rebuilt engine re-traces nothing
        # (decode_compilations() continuity across restarts)
        return ContinuousBatchingEngine(
            model, num_slots=num_slots, max_seq_len=max_seq_len,
            decode_chunk=decode_chunk, prefix_cache=prefix_cache,
            prefix_blocks=prefix_blocks,
            prefix_block_size=prefix_block_size,
            paged_attn=paged_attn, prefill_chunk=prefill_chunk,
            ragged_step=ragged_step, headroom_mult=headroom_mult,
            spec_decode=spec_decode, spec_k=spec_k, drafter=drafter,
            decode_ticks=decode_ticks, kv_dtype=kv_dtype,
            quantize_weights=quantize_weights,
            quantize_activations=quantize_activations,
            tp=tp, collective_dtype=collective_dtype,
            host_tier_bytes=host_tier_bytes,
            priority_classes=priority_classes,
            fused_tick=fused_tick,
            collective_overlap=collective_overlap,
            jit_cache=model.__dict__.setdefault("_serving_jit", {}))

    gateway = ServingGateway(
        engine_factory(), max_queue=max_queue, registry=registry,
        engine_factory=engine_factory,
        watchdog_deadline_s=watchdog_deadline_s,
        max_restarts=max_restarts, fault_hook=fault_hook, clock=clock,
        trace=trace, trace_buffer=trace_buffer, cost=cost)
    server = ServingHTTPServer(
        gateway, host=host, port=port,
        model_name=model_name or type(model).__name__, log_fn=log_fn)
    return server.start() if start else server


def serve_fleet(model, replicas=2, router="affinity", host="127.0.0.1",
                port=8000, num_slots=8, max_seq_len=None, decode_chunk=1,
                max_queue=64, model_name=None, registry=None, log_fn=None,
                start=True, prefix_cache=True, prefix_blocks=None,
                prefix_block_size=32, paged_attn=True, prefill_chunk=512,
                ragged_step=True, headroom_mult=2.0,
                watchdog_deadline_s=30.0, max_restarts=8,
                fault_hooks=None, clock=None, spec_decode=False,
                spec_k=4, drafter=None, trace=False, trace_buffer=65536,
                cost=True, affinity_band=16, decode_ticks=1,
                kv_dtype=None, quantize_weights=False,
                quantize_activations=False, tp=1,
                collective_dtype="fp", host_tier_bytes=0,
                classes=None, slo_ttft_ms=None, slo_tpot_ms=None,
                fused_tick=False, collective_overlap=False):
    """Build an engine fleet → HTTP server and start listening (README
    "Engine fleet"): ``replicas`` supervised engines — each its own
    paged pool, prefix trie and scheduler, sharing compiled programs
    per pool geometry — behind one routed front door.

    ``router`` picks the admission policy: ``round-robin`` (the
    baseline), ``least-loaded`` (live KV blocks + queue depth), or
    ``affinity`` (the default: longest cached-prefix match wins within
    ``affinity_band`` load units of the least-loaded replica, so
    prefix-cache hits survive fan-out — FLEET_BENCH.json banks the
    three-way comparison). ``num_slots`` / ``prefill_chunk`` /
    ``max_seq_len`` / ``max_queue`` / ``prefix_blocks`` accept a
    scalar or one value per replica (mixed pool geometries isolate
    their jit caches automatically; ``decode_compilations() == 1``
    holds per geometry across the whole fleet).

    On top of the single-engine surface, the handler grows
    ``GET /debug/fleet`` (the per-replica operations table),
    ``POST /fleet/drain`` and ``POST /fleet/rebalance`` (live request
    migration), ``/healthz`` aggregates replica states, and every
    ``/metrics`` series carries a ``replica`` label (monotonic across
    any single replica's rebuild). A replica that dies past its
    restart budget fails over: its live requests re-admit on siblings
    by ``restore()`` recompute and the streams continue
    byte-identically — zero requests lost (the fleet chaos matrix,
    tests/test_fleet.py).

    ``host_tier_bytes=N`` (scalar or per-replica, default 0) gives
    each replica a host-RAM spill tier AND turns on the fleet cache
    plane (README "Tiered KV prefix cache"): before a routed request
    submits, any spilled prefix chain it needs moves host-to-host
    from the sibling tier that holds it (content-digest addressed),
    so prefix affinity becomes a distributed prefix cache.
    ``GET /fleet/cacheplane`` is the debug surface; ``/metrics``
    grows ``serving_fleet_tier_transfers_total`` and
    ``serving_fleet_tier_transfer_bytes_total``.

    ``classes`` / ``slo_ttft_ms`` / ``slo_tpot_ms`` configure the
    multi-tenant class table fleet-wide (same grammar as
    :func:`serve`; every replica shares ONE parsed table). The
    ``class-headroom`` router routes each request by per-replica class
    pressure — the load that COULD NOT be displaced for it — so a
    latency request never lands on a replica saturated with equal-or-
    higher-rank work while a sibling has displaceable batch load;
    ``/debug/fleet`` rows grow per-class occupancy columns.
    """
    from ..fleet import EngineFleet, PrefixAffinityRouter
    from ..policy import ClassTable
    priority_classes = None if classes is None else ClassTable.parse(
        classes, slo_ttft_ms=slo_ttft_ms, slo_tpot_ms=slo_tpot_ms)
    if router == "affinity":
        router = PrefixAffinityRouter(band=affinity_band)
    fleet = EngineFleet(
        model, replicas=replicas, router=router, num_slots=num_slots,
        max_seq_len=max_seq_len, decode_chunk=decode_chunk,
        max_queue=max_queue, prefix_cache=prefix_cache,
        prefix_blocks=prefix_blocks,
        prefix_block_size=prefix_block_size, paged_attn=paged_attn,
        prefill_chunk=prefill_chunk, ragged_step=ragged_step,
        headroom_mult=headroom_mult, spec_decode=spec_decode,
        spec_k=spec_k, drafter=drafter, decode_ticks=decode_ticks,
        kv_dtype=kv_dtype, quantize_weights=quantize_weights,
        quantize_activations=quantize_activations,
        tp=tp, collective_dtype=collective_dtype,
        host_tier_bytes=host_tier_bytes,
        priority_classes=priority_classes,
        fused_tick=fused_tick, collective_overlap=collective_overlap,
        registry=registry, clock=clock,
        watchdog_deadline_s=watchdog_deadline_s,
        max_restarts=max_restarts, fault_hooks=fault_hooks,
        trace=trace, trace_buffer=trace_buffer, cost=cost, start=True)
    server = ServingHTTPServer(
        None, host=host, port=port,
        model_name=model_name or type(model).__name__, log_fn=log_fn,
        fleet=fleet)
    return server.start() if start else server
