"""paddle.signal — STFT/ISTFT (reference: ``python/paddle/signal.py`` over
the frame/overlap_add ops). TPU-native: framing is a gather, the FFT is
XLA's native HLO; everything fuses under jit."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops._op import tensor_op

__all__ = ["stft", "istft", "frame", "overlap_add"]


def _frame_raw(x, frame_length, hop_length):
    """[..., n] -> [..., num_frames, frame_length] (shared gather core)."""
    n = x.shape[-1]
    if n < frame_length:
        raise ValueError(
            f"signal length {n} is shorter than frame_length {frame_length}")
    num = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]
    return x[..., idx]


def _ola_raw(frames, hop_length):
    """[..., num_frames, frame_length] -> [..., out_len] (shared
    overlap-add core)."""
    fl, num = frames.shape[-1], frames.shape[-2]
    out_len = (num - 1) * hop_length + fl

    def body(i, acc):
        cur = jax.lax.dynamic_slice_in_dim(acc, i * hop_length, fl, -1)
        return jax.lax.dynamic_update_slice_in_dim(
            acc, cur + frames[..., i, :], i * hop_length, -1)

    acc = jnp.zeros(frames.shape[:-2] + (out_len,), frames.dtype)
    return jax.lax.fori_loop(0, num, body, acc)


@tensor_op
def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice overlapping frames (reference paddle.signal.frame):
    axis=-1 -> [..., frame_length, num_frames];
    axis=0  -> [num_frames, frame_length, ...]."""
    last = axis in (-1, x.ndim - 1)
    if not last:
        if axis not in (0,):
            raise ValueError("frame: axis must be 0 or -1 (paddle contract)")
        x = jnp.moveaxis(x, 0, -1)
    out = _frame_raw(x, frame_length, hop_length)
    if last:
        return jnp.swapaxes(out, -1, -2)  # [..., frame_length, num]
    return jnp.moveaxis(out, (-2, -1), (0, 1))  # [num, frame_length, ...]


@tensor_op
def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame (reference paddle.signal.overlap_add):
    axis=-1: [..., frame_length, num_frames] -> [..., out_len];
    axis=0:  [num_frames, frame_length, ...] -> [out_len, ...]."""
    last = axis in (-1, x.ndim - 1)
    if not last:
        if axis != 0:
            raise ValueError("overlap_add: axis must be 0 or -1")
        x = jnp.moveaxis(x, (0, 1), (-1, -2))  # -> [..., fl, num]
    out = _ola_raw(jnp.swapaxes(x, -1, -2), hop_length)
    return out if last else jnp.moveaxis(out, -1, 0)


def _window_arr(window, n_fft, dtype):
    if window is None:
        return jnp.ones((n_fft,), dtype)
    from .core.tensor import Tensor
    w = window.value if isinstance(window, Tensor) else jnp.asarray(window)
    return w.astype(dtype)


@tensor_op
def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (reference paddle.signal.stft):
    returns [..., n_fft//2+1 (or n_fft), num_frames] complex."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    w = _window_arr(window, wl, jnp.float32)
    if wl < n_fft:  # center-pad the window to n_fft
        pad = (n_fft - wl) // 2
        w = jnp.pad(w, (pad, n_fft - wl - pad))
    if center:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)],
                    mode=pad_mode)
    frames = _frame_raw(x, n_fft, hop) * w  # [..., num, n_fft]
    spec = (jnp.fft.rfft(frames, axis=-1) if onesided
            else jnp.fft.fft(frames, axis=-1))
    if normalized:
        spec = spec / jnp.sqrt(jnp.float32(n_fft))
    return jnp.swapaxes(spec, -1, -2)  # [..., freq, frames]


@tensor_op
def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with windowed overlap-add and COLA normalization."""
    hop = hop_length or n_fft // 4
    wl = win_length or n_fft
    w = _window_arr(window, wl, jnp.float32)
    if wl < n_fft:
        pad = (n_fft - wl) // 2
        w = jnp.pad(w, (pad, n_fft - wl - pad))
    if return_complex and onesided:
        raise ValueError("istft: return_complex=True requires "
                         "onesided=False (a complex signal has no "
                         "conjugate-symmetric spectrum)")
    spec = jnp.swapaxes(x, -1, -2)  # [..., frames, freq]
    if normalized:
        spec = spec * jnp.sqrt(jnp.float32(n_fft))
    if onesided:
        frames = jnp.fft.irfft(spec, n=n_fft, axis=-1)
    else:
        frames = jnp.fft.ifft(spec, axis=-1)
        if not return_complex:
            frames = frames.real
    frames = frames * w
    num = frames.shape[-2]
    out_len = (num - 1) * hop + n_fft
    sig = _ola_raw(frames, hop)
    # COLA normalization: divide by the summed squared window envelope
    wsq = jnp.broadcast_to(w * w, (num, n_fft))
    env = _ola_raw(wsq.reshape((1,) * (frames.ndim - 2) + (num, n_fft))
                   if frames.ndim > 2 else wsq, hop)
    sig = sig / jnp.maximum(env, 1e-8)
    if center:
        sig = sig[..., n_fft // 2: out_len - n_fft // 2]
    if length is not None:
        sig = sig[..., :length]
    return sig
