"""paddle.sparse — COO/CSR sparse tensors and ops (reference:
``python/paddle/sparse/`` — SparseCooTensor/SparseCsrTensor creation,
unary/binary ops, matmul, masked_matmul, nn.ReLU).

TPU-native: backed by ``jax.experimental.sparse.BCOO`` — static-nnz batched
COO, the formulation XLA can compile (gather/scatter/segment-sum on the
MXU-adjacent VPU) — rather than the reference's cuSPARSE handles. CSR
creation converts to BCOO internally; ``crows/cols/values`` views are
recomputed on demand.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor
from ..ops._op import tensor_op

__all__ = [
    "sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
    "SparseCsrTensor", "is_same_shape", "add", "subtract", "multiply",
    "divide", "matmul", "masked_matmul", "relu", "abs", "sin", "tanh",
    "sqrt", "pow", "neg", "cast", "transpose", "sum", "nn",
]


def _val(x):
    return x.value if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor over a BCOO core. ``indices`` is [ndim, nnz]
    (paddle layout), ``values`` [nnz, ...]."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # ------------------------------------------------------------ properties
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    @property
    def nnz(self):
        return int(self._bcoo.nse)

    def indices(self):
        return Tensor(self._bcoo.indices.T)  # [ndim, nnz]

    def values(self):
        return Tensor(self._bcoo.data)

    # ------------------------------------------------------------ conversion
    def to_dense(self):
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        return SparseCsrTensor(self._bcoo)

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    # ------------------------------------------------------------ arithmetic
    def __add__(self, other):
        return add(self, other)

    def __mul__(self, other):
        return multiply(self, other)

    def __neg__(self):
        return neg(self)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


class SparseCsrTensor(SparseCooTensor):
    """CSR view (reference SparseCsrTensor): same BCOO core, crows/cols
    recomputed on demand for 2D (or batched-2D) tensors."""

    def crows(self):
        idx = self._bcoo.indices  # [nnz, 2]
        rows = idx[:, 0]
        n_rows = self.shape[-2]
        counts = jnp.bincount(rows, length=n_rows)
        return Tensor(jnp.concatenate([jnp.zeros((1,), counts.dtype),
                                       jnp.cumsum(counts)]))

    def cols(self):
        order = jnp.lexsort((self._bcoo.indices[:, 1],
                             self._bcoo.indices[:, 0]))
        return Tensor(self._bcoo.indices[order, 1])

    def values(self):
        order = jnp.lexsort((self._bcoo.indices[:, 1],
                             self._bcoo.indices[:, 0]))
        return Tensor(self._bcoo.data[order])

    def to_sparse_coo(self, sparse_dim=None):
        return SparseCooTensor(self._bcoo)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype})")


# ---------------------------------------------------------------- creation
def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True):
    idx = np.asarray(_val(indices))          # [ndim, nnz]
    vals = jnp.asarray(_val(values))
    if dtype is not None:
        from ..core import dtype as dtype_mod
        vals = vals.astype(dtype_mod.to_jax_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
    bcoo = jsparse.BCOO((vals, jnp.asarray(idx.T)), shape=tuple(shape))
    return SparseCooTensor(bcoo)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True):
    crows = np.asarray(_val(crows))
    cols = np.asarray(_val(cols))
    vals = jnp.asarray(_val(values))
    if dtype is not None:
        from ..core import dtype as dtype_mod
        vals = vals.astype(dtype_mod.to_jax_dtype(dtype))
    rows = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
    idx = jnp.stack([jnp.asarray(rows), jnp.asarray(cols)], axis=1)
    bcoo = jsparse.BCOO((vals, idx), shape=tuple(shape))
    return SparseCsrTensor(bcoo)


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def _to_sparse(t: Tensor, kind="coo"):
    bcoo = jsparse.BCOO.fromdense(_val(t))
    return SparseCooTensor(bcoo) if kind == "coo" else SparseCsrTensor(bcoo)


# patched onto dense Tensor below (paddle parity: Tensor.to_sparse_coo /
# to_sparse_csr and the module-level spellings are the SAME function)
def to_sparse_coo(t, sparse_dim=None):
    nd = _val(t).ndim
    sparse_dim = nd if sparse_dim is None else int(sparse_dim)
    if not 0 < sparse_dim <= nd:
        raise ValueError(f"sparse_dim must be in [1, {nd}], got "
                         f"{sparse_dim}")
    if sparse_dim != nd:
        # a hybrid BCOO (n_dense > 0) would flow into ops (csr layout,
        # transpose, elementwise rebuilds) that assume fully-sparse
        # indices — refuse rather than misbehave downstream
        raise NotImplementedError(
            f"to_sparse_coo with sparse_dim ({sparse_dim}) < ndim ({nd}) "
            f"(hybrid dense/sparse layout) is not supported; omit "
            f"sparse_dim for the fully-sparse form")
    return _to_sparse(t, "coo")


def to_sparse_csr(t):
    if _val(t).ndim != 2:
        raise ValueError(
            f"to_sparse_csr expects a 2-D tensor, got {_val(t).ndim}-D")
    return _to_sparse(t, "csr")


# ---------------------------------------------------------------- elementwise
def _unary(fn, keep_zero=True):
    def op(x: SparseCooTensor):
        b = x._bcoo
        return type(x)(jsparse.BCOO((fn(b.data), b.indices), shape=b.shape))
    return op


relu = _unary(lambda v: jnp.maximum(v, 0))
abs = _unary(jnp.abs)  # noqa: A001 — paddle name
sin = _unary(jnp.sin)
tanh = _unary(jnp.tanh)
sqrt = _unary(jnp.sqrt)
neg = _unary(jnp.negative)


def pow(x, factor):  # noqa: A001
    return _unary(lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None):
    from ..core import dtype as dtype_mod
    b = x._bcoo
    data = b.data if value_dtype is None else \
        b.data.astype(dtype_mod.to_jax_dtype(value_dtype))
    idx = b.indices if index_dtype is None else \
        b.indices.astype(dtype_mod.to_jax_dtype(index_dtype))
    return type(x)(jsparse.BCOO((data, idx), shape=b.shape))


def _binary(fn):
    def op(x, y):
        if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
            out = fn(x._bcoo.todense(), y._bcoo.todense())
            return type(x)(jsparse.BCOO.fromdense(out))
        if isinstance(x, SparseCooTensor):
            return Tensor(fn(x._bcoo.todense(), _val(y)))
        return Tensor(fn(_val(x), y._bcoo.todense()))
    return op


add = _binary(jnp.add)
subtract = _binary(jnp.subtract)
multiply = _binary(jnp.multiply)
divide = _binary(jnp.divide)


def sum(x, axis=None, dtype=None, keepdim=False):  # noqa: A001
    out = jnp.sum(x._bcoo.todense(), axis=axis, keepdims=keepdim)
    return Tensor(out)


def transpose(x, perm):
    b = x._bcoo
    # BCOO transpose: permute index columns + shape
    idx = b.indices[:, jnp.asarray(perm)]
    shape = tuple(b.shape[p] for p in perm)
    return type(x)(jsparse.BCOO((b.data, idx), shape=shape))


# ------------------------------------------------------------------ matmul
def matmul(x, y):
    """sparse @ dense (and dense @ sparse) — lowers to XLA gather/
    segment-sum via bcoo_dot_general (the TPU answer to cuSPARSE spmm)."""
    if isinstance(x, SparseCooTensor) and not isinstance(y, SparseCooTensor):
        out = jsparse.bcoo_dot_general(
            x._bcoo, _val(y),
            dimension_numbers=(((x._bcoo.ndim - 1,), (0,)), ((), ())))
        return Tensor(out)
    if isinstance(y, SparseCooTensor) and not isinstance(x, SparseCooTensor):
        # dense @ sparse = (sparse.T @ dense.T).T
        yt = transpose(y, list(range(y._bcoo.ndim - 2)) +
                       [y._bcoo.ndim - 1, y._bcoo.ndim - 2])
        xt = jnp.swapaxes(_val(x), -1, -2)
        out = jsparse.bcoo_dot_general(
            yt._bcoo, xt,
            dimension_numbers=(((yt._bcoo.ndim - 1,), (0,)), ((), ())))
        return Tensor(jnp.swapaxes(out, -1, -2))
    # sparse @ sparse: densify the smaller operand
    return Tensor(x._bcoo.todense() @ y._bcoo.todense())


def masked_matmul(x, y, mask: SparseCooTensor):
    """(x @ y) sampled at mask's sparsity pattern (reference SDDMM)."""
    xv, yv = _val(x), _val(y)
    idx = mask._bcoo.indices  # [nnz, 2]
    rows = xv[idx[:, 0]]          # [nnz, K]
    cols = yv[:, idx[:, 1]].T     # [nnz, K]
    vals = jnp.sum(rows * cols, axis=-1)
    return type(mask)(jsparse.BCOO((vals, idx), shape=mask._bcoo.shape))


class _SparseReLU:
    def __call__(self, x):
        return relu(x)


class _nn:
    ReLU = _SparseReLU


nn = _nn()


# ------------------------------------------------ Tensor method spellings
# (reference: Tensor.to_sparse_coo / to_sparse_csr patched in
# python/paddle/tensor/__init__.py †) — the module-level functions above,
# bound as methods so both spellings share one validation path
Tensor.to_sparse_coo = to_sparse_coo
Tensor.to_sparse_csr = to_sparse_csr
