"""Static-graph introspection surface (reference: ``python/paddle/static/``).

XLA is the static engine: a "Program" here is a traced jaxpr + lowered/
compiled HLO. This module provides the introspection half of the reference's
static API — tracing a callable to a Program you can print, inspect for ops,
and compile — not a separate execution engine (jit IS the executor).
Compile-only tests (SURVEY.md §4) use ``Program.hlo_text`` to assert
collective/fusion properties.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax

from ..core.tensor import Tensor


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        from ..core import dtype as dtype_mod
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = dtype_mod.to_jax_dtype(dtype)
        self.name = name

    def to_struct(self, batch_size=1):
        shape = tuple(batch_size if s == -1 else s for s in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype)


class Program:
    """A traced computation: jaxpr + (lazily) lowered HLO."""

    def __init__(self, fn: Callable, example_args: Sequence[Any]):
        self._fn = fn
        self._args = example_args
        self._jaxpr = None
        self._lowered = None

    @property
    def jaxpr(self):
        if self._jaxpr is None:
            self._jaxpr = jax.make_jaxpr(self._fn)(*self._args)
        return self._jaxpr

    @property
    def lowered(self):
        if self._lowered is None:
            self._lowered = jax.jit(self._fn).lower(*self._args)
        return self._lowered

    @property
    def hlo_text(self) -> str:
        return self.lowered.as_text()

    def compile(self):
        return self.lowered.compile()

    def ops(self):
        """List of primitive op names (the reference's program op list)."""
        return [str(eqn.primitive) for eqn in self.jaxpr.eqns]

    def count_op(self, name: str) -> int:
        import re
        return len(re.findall(rf"\b{re.escape(name)}\b", self.hlo_text))

    def flops(self):
        try:
            return self.compile().cost_analysis()["flops"]
        except Exception:
            return None

    def __str__(self):
        return str(self.jaxpr)


def trace_layer(layer, example_inputs) -> Program:
    """Trace a Layer's forward into a Program (dy2static's role, done by
    jax tracing)."""
    from ..jit.functional import call_functional, split_state
    params, buffers = split_state(layer)
    vals = [x.value if isinstance(x, Tensor) else x for x in example_inputs]

    def fn(p, b, *a):
        out, new_b = call_functional(layer, p, b, tuple(a))
        return out

    return Program(fn, (params, buffers, *vals))


from .capture import (Executor, StaticProgram, data,  # noqa: E402
                      program_guard)

_default_main = StaticProgram()


def default_main_program():
    return _default_main


def default_startup_program():
    # parameter init happens eagerly at Layer construction (no separate
    # startup graph under XLA); an empty program keeps the API total
    return StaticProgram()


def name_scope(name):
    return jax.named_scope(name)
