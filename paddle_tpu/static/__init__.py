"""Static-graph introspection surface (reference: ``python/paddle/static/``).

XLA is the static engine: a "Program" here is a traced jaxpr + lowered/
compiled HLO. This module provides the introspection half of the reference's
static API — tracing a callable to a Program you can print, inspect for ops,
and compile — not a separate execution engine (jit IS the executor).
Compile-only tests (SURVEY.md §4) use ``Program.hlo_text`` to assert
collective/fusion properties.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax

from ..core.tensor import Tensor


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        from ..core import dtype as dtype_mod
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = dtype_mod.to_jax_dtype(dtype)
        self.name = name

    def to_struct(self, batch_size=1):
        shape = tuple(batch_size if s == -1 else s for s in self.shape)
        return jax.ShapeDtypeStruct(shape, self.dtype)


class Program:
    """A traced computation: jaxpr + (lazily) lowered HLO."""

    def __init__(self, fn: Callable, example_args: Sequence[Any]):
        self._fn = fn
        self._args = example_args
        self._jaxpr = None
        self._lowered = None

    @property
    def jaxpr(self):
        if self._jaxpr is None:
            self._jaxpr = jax.make_jaxpr(self._fn)(*self._args)
        return self._jaxpr

    @property
    def lowered(self):
        if self._lowered is None:
            self._lowered = jax.jit(self._fn).lower(*self._args)
        return self._lowered

    @property
    def hlo_text(self) -> str:
        return self.lowered.as_text()

    def compile(self):
        return self.lowered.compile()

    def ops(self):
        """List of primitive op names (the reference's program op list)."""
        return [str(eqn.primitive) for eqn in self.jaxpr.eqns]

    def count_op(self, name: str) -> int:
        import re
        return len(re.findall(rf"\b{re.escape(name)}\b", self.hlo_text))

    def flops(self):
        try:
            return self.compile().cost_analysis()["flops"]
        except Exception:
            return None

    def __str__(self):
        return str(self.jaxpr)


def trace_layer(layer, example_inputs) -> Program:
    """Trace a Layer's forward into a Program (dy2static's role, done by
    jax tracing)."""
    from ..jit.functional import call_functional, split_state
    params, buffers = split_state(layer)
    vals = [x.value if isinstance(x, Tensor) else x for x in example_inputs]

    def fn(p, b, *a):
        out, new_b = call_functional(layer, p, b, tuple(a))
        return out

    return Program(fn, (params, buffers, *vals))


from .capture import (Executor, StaticProgram, data,  # noqa: E402
                      program_guard)

_default_main = StaticProgram()


def default_main_program():
    return _default_main


def default_startup_program():
    # parameter init happens eagerly at Layer construction (no separate
    # startup graph under XLA); an empty program keeps the API total
    return StaticProgram()


def name_scope(name):
    return jax.named_scope(name)


class _LoadedInference:
    """Deserialized inference program returned by load_inference_model —
    runnable via ``Executor.run(program=..., feed=..., fetch_list=...)``
    exactly like a live StaticProgram (reference contract)."""

    def __init__(self, exported, feed_names, fetch_count):
        self._exported = exported
        self.feed_names = list(feed_names)
        self.fetch_count = int(fetch_count)

    def run(self, feed_vals):
        import jax.numpy as jnp
        args = [jnp.asarray(feed_vals[n]) for n in self.feed_names]
        out = self._exported.call(*args)
        return out if isinstance(out, (list, tuple)) else (out,)


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Reference ``paddle.static.save_inference_model`` †: persist the
    captured program as a deployable artifact. TPU-native form: the
    program's pure replay (feeds -> fetches, weights baked as constants)
    is serialized as StableHLO via jax.export into ``<prefix>.pdmodel``,
    with feed/fetch metadata in a ``<prefix>.pdmeta`` sidecar. Dynamic
    (-1) feed dims export as symbolic shapes.

    Sidecar format (``.pdmeta``): a ``framework.io.save`` pickle of
    ``{"feed_names": [str, ...], "fetch_count": int}`` — NOT serialized
    parameters (weights are baked into the StableHLO program). Earlier
    versions wrote this metadata under the reference's ``.pdiparams``
    extension, whose real-paddle format IS serialized parameters; that
    implied a compatibility the file never had (ADVICE r5), so the
    sidecar now has its own name. ``load_inference_model`` still reads
    a legacy ``.pdiparams`` metadata sidecar when no ``.pdmeta`` exists.
    """
    import jax as _jax
    from jax import export as jexport

    from ..framework import io as fio
    prog = program or default_main_program()
    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    feed_names = [prog.feed_names[id(t)] for t in feed_vars]
    fetch_ids = tuple(id(t) for t in fetch_vars)
    # the export prunes to what the fetches reach (training-only feeds
    # like labels drop out), but every feed the pruned graph DOES need
    # must be in feed_vars
    required = set(prog.required_feed_names(fetch_ids))
    missing = required - set(feed_names)
    if missing:
        raise ValueError(
            f"save_inference_model: the fetch targets depend on feeds "
            f"{sorted(missing)} not listed in feed_vars")
    feed_names = [n for n in feed_names if n in required]

    def pure(*arrs):
        fv = dict(zip(feed_names, arrs))
        return prog._replay_pruned(fv, fetch_ids)

    from ..jit import _struct_from_shape
    scope = jexport.SymbolicScope()
    structs = [
        _struct_from_shape(list(prog._feed_shapes[name][0]),
                           prog._feed_shapes[name][1], i, scope)
        for i, name in enumerate(feed_names)]
    exp = jexport.export(_jax.jit(pure))(*structs)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(exp.serialize())
    fio.save({"feed_names": feed_names, "fetch_count": len(fetch_ids)},
             path_prefix + ".pdmeta")


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Returns ``[program, feed_target_names, fetch_targets]`` (reference
    signature); run with ``exe.run(program, feed={name: arr},
    fetch_list=fetch_targets)``. Reads the ``.pdmeta`` feed/fetch
    sidecar (see :func:`save_inference_model` for the format), falling
    back to the legacy ``.pdiparams``-named metadata sidecar for
    artifacts saved before the rename."""
    import os as _os

    from jax import export as jexport

    from ..framework import io as fio
    meta_path = path_prefix + ".pdmeta"
    if not _os.path.exists(meta_path):  # pre-rename artifact
        meta_path = path_prefix + ".pdiparams"
    meta = fio.load(meta_path)
    with open(path_prefix + ".pdmodel", "rb") as f:
        exported = jexport.deserialize(f.read())
    prog = _LoadedInference(exported, meta["feed_names"],
                            meta["fetch_count"])
    fetch_targets = list(range(prog.fetch_count))
    return [prog, list(prog.feed_names), fetch_targets]
