"""Define-and-run static graphs (reference: ``ProgramDesc`` + ``Executor``,
``paddle/fluid/framework/program_desc.cc`` / ``executor.cc`` †).

The op dispatch point (``ops._op.apply``) doubles as the reference's
op-desc recorder: under ``program_guard``, every framework op appends
(raw fn, input var ids, output var ids) to the current ``StaticProgram``.
``Executor.run`` replays the op list as a PURE function of the feed
arrays — and jits that replay, so a captured program compiles to exactly
one XLA executable like any other step (XLA is the executor; the replay
is the "graph").

Same contract as the reference's static mode: Python control flow is
frozen at build time, and ops execute in recorded order.
"""
from __future__ import annotations

import threading

import jax

from ..core.tensor import Tensor

_tls = threading.local()


def current_program():
    return getattr(_tls, "program", None)


class StaticProgram:
    """An op-list program: feed placeholders -> recorded ops -> fetches."""

    def __init__(self):
        self.ops = []          # (fn, name, arg_slots, treedef, out_ids)
        self.feed_names = {}   # placeholder Tensor id -> feed name
        self._feed_shapes = {}
        self._known = set()    # Tensor ids produced so far (or fed)
        self._const = {}       # Tensor id -> captured literal value
        self._compiled = {}
        # ids index the graph, so every recorded Tensor must stay alive
        # for the program's lifetime — otherwise CPython reuses a freed
        # intermediate's id for a new object and the graph silently
        # cross-wires
        self._keepalive = []
        self._build_ctime = None  # Tensor creation-counter at guard entry

    # ----------------------------------------------------------- building
    def add_feed(self, name, tensor, spec_shape=None):
        self.feed_names[id(tensor)] = name
        self._feed_shapes[name] = (tuple(spec_shape if spec_shape is not None
                                         else tensor.shape), tensor.dtype)
        self._known.add(id(tensor))
        self._keepalive.append(tensor)

    def record(self, fn, name, flat, treedef, out_tree):
        slots = []
        for x in flat:
            if isinstance(x, Tensor):
                xid = id(x)
                if xid not in self._known and xid not in self._const:
                    from ..core.tensor import Parameter
                    if (self._build_ctime is not None
                            and not isinstance(x, Parameter)
                            and getattr(x, "_ctime", 0) >= self._build_ctime):
                        # created DURING capture but not by a recorded op
                        # and not a Parameter: raw Tensor construction
                        # bypassed the dispatch. If its value derives from
                        # a placeholder it will be FROZEN at build-time
                        # values — warn loudly (layers legitimately build
                        # constant tensors in __init__, so this cannot be
                        # a hard error by default; FLAGS_static_strict
                        # promotes it to one for capture-audit runs).
                        msg = (
                            f"static capture: input of op '{name}' was "
                            f"created inside program_guard without going "
                            f"through the op dispatch; it is captured as a "
                            f"BUILD-TIME CONSTANT. If it derives from a "
                            f"data() placeholder, the program will ignore "
                            f"that feed.")
                        from ..utils.flags import get_flag
                        if get_flag("FLAGS_static_strict", False):
                            raise RuntimeError(
                                msg + " (FLAGS_static_strict promotes "
                                "this warning to an error)")
                        import warnings
                        warnings.warn(msg)
                    # a tensor from OUTSIDE the program (weights, eager
                    # constants): captured by value, like the reference's
                    # persistable vars
                    self._const[xid] = x.value
                    self._keepalive.append(x)
                slots.append(("var", xid))
            else:
                slots.append(("lit", x))
        out_ids = []
        for o in jax.tree.leaves(out_tree, is_leaf=lambda t: isinstance(t, Tensor)):
            if isinstance(o, Tensor):
                oid = id(o)
                out_ids.append(oid)
                self._known.add(oid)
                self._keepalive.append(o)
        self.ops.append((fn, name, slots, treedef, out_ids))

    # ------------------------------------------------------------ running
    def _replay(self, feed_vals, fetch_ids):
        """Pure function: feed dict (name->array) -> fetched values."""
        env = dict(self._const)
        for tid, fname in self.feed_names.items():
            env[tid] = feed_vals[fname]
        for fn, name, slots, treedef, out_ids in self.ops:
            vals = [env[s[1]] if s[0] == "var" else s[1] for s in slots]
            a, k = jax.tree.unflatten(treedef, vals)
            out = fn(*a, **k)
            leaves = jax.tree.leaves(out)
            for oid, leaf in zip(out_ids, leaves):
                env[oid] = leaf
        return tuple(env[fid] for fid in fetch_ids)

    def _prune(self, fetch_ids):
        """Backward reachability from the fetches: (ops_used, needed_ids).
        The reference's inference-model export prunes the graph to what
        the fetch targets require, so feeds only the training half uses
        (labels) drop out."""
        needed = set(fetch_ids)
        ops_used = []
        for op in reversed(self.ops):
            _fn, _name, slots, _treedef, out_ids = op
            if any(o in needed for o in out_ids):
                ops_used.append(op)
                needed.update(s[1] for s in slots if s[0] == "var")
        ops_used.reverse()
        return ops_used, needed

    def _replay_pruned(self, feed_vals, fetch_ids):
        """Pure replay over only the ops the fetches need; feeds not in
        the pruned graph are never touched."""
        ops_used, needed = self._prune(fetch_ids)
        env = dict(self._const)
        for tid, fname in self.feed_names.items():
            if tid in needed:
                env[tid] = feed_vals[fname]
        for fn, _name, slots, treedef, out_ids in ops_used:
            vals = [env[s[1]] if s[0] == "var" else s[1] for s in slots]
            a, k = jax.tree.unflatten(treedef, vals)
            out = fn(*a, **k)
            for oid, leaf in zip(out_ids, jax.tree.leaves(out)):
                env[oid] = leaf
        return tuple(env[fid] for fid in fetch_ids)

    def required_feed_names(self, fetch_ids):
        _ops, needed = self._prune(fetch_ids)
        return [fname for tid, fname in self.feed_names.items()
                if tid in needed]

    def run(self, feed, fetch_ids, jit=True):
        key = (tuple(sorted(feed)), tuple(fetch_ids), jit)
        fn = self._compiled.get(key)
        if fn is None:
            fn = (jax.jit(lambda fv: self._replay(fv, fetch_ids)) if jit
                  else (lambda fv: self._replay(fv, fetch_ids)))
            self._compiled[key] = fn
        return fn(feed)

    def op_names(self):
        return [name for _, name, _, _, _ in self.ops]

    def __str__(self):
        lines = [f"StaticProgram({len(self.ops)} ops, "
                 f"feeds={sorted(self._feed_shapes)})"]
        lines += [f"  {i}: {n}" for i, n in enumerate(self.op_names())]
        return "\n".join(lines)


class program_guard:
    """Capture ops built inside the ``with`` into ``main_program``."""

    def __init__(self, main_program, startup_program=None):
        self.program = main_program
        self._prev = None

    def __enter__(self):
        self._prev = current_program()
        if self.program._build_ctime is None:
            self.program._build_ctime = Tensor._creation_counter
        _tls.program = self.program
        return self.program

    def __exit__(self, *exc):
        _tls.program = self._prev
        return False


def data(name, shape, dtype="float32", lod_level=0):
    """Feed placeholder (reference ``paddle.static.data``): a zero tensor
    whose id is bound to ``name`` in the current program; Executor.run
    substitutes the fed array at that slot."""
    import jax.numpy as jnp

    from ..core import dtype as dtype_mod
    prog = current_program()
    spec = tuple(-1 if s in (-1, None) else int(s) for s in shape)
    # -1 dims materialize as 1 for the zero placeholder VALUE, but the
    # placeholder's `.shape` reads back the declared spec (-1 stays -1, as
    # in the reference's static mode) so a reshape/arange size computed
    # from it at build time cannot silently bake batch=1. reshape(-1, ...)
    # then infers correctly at replay for any fed batch.
    shape = tuple(1 if s == -1 else s for s in spec)
    t = Tensor(jnp.zeros(shape, dtype_mod.to_jax_dtype(dtype)), name=name)
    if any(s == -1 for s in spec):
        t._static_spec = spec
    if prog is not None:
        prog.add_feed(name, t, spec_shape=spec)
    return t


class Executor:
    """Replays captured programs (reference ``paddle.static.Executor``);
    ``place`` is accepted for API parity (XLA owns placement)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, jit=True):
        feed_vals = {k: (v.value if isinstance(v, Tensor) else v)
                     for k, v in (feed or {}).items()}
        # a deserialized inference program (load_inference_model) runs its
        # exported StableHLO directly
        if program is not None and hasattr(program, "_exported"):
            import numpy as np
            outs = program.run(feed_vals)
            sel = fetch_list if fetch_list else range(len(outs))
            return [np.asarray(outs[i]) for i in sel]
        if program is None or not isinstance(program, StaticProgram):
            raise ValueError("Executor.run needs the StaticProgram that "
                             "captured the graph (program_guard target)")
        fetch_list = fetch_list or []
        missing = set(program.feed_names.values()) - set(feed_vals)
        if missing:
            raise ValueError(f"missing feeds: {sorted(missing)}")
        for fname, (spec, _dt) in program._feed_shapes.items():
            if fname not in feed_vals:
                continue
            got = tuple(getattr(feed_vals[fname], "shape", ()))
            if len(got) != len(spec) or any(
                    s != -1 and s != g for s, g in zip(spec, got)):
                raise ValueError(
                    f"feed '{fname}' has shape {got}, expected {spec} "
                    f"(-1 = any)")
        fetch_ids = tuple(id(t) for t in fetch_list)
        outs = program.run(feed_vals, fetch_ids, jit=jit)
        import numpy as np
        return [np.asarray(o) for o in outs]
