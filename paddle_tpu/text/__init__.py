"""paddle.text (reference: ``python/paddle/text/datasets/`` † — Conll05,
Imdb, Imikolov, Movielens, UCIHousing, WMT14/16). Every dataset downloads
from a hosted archive; with zero egress here, constructors raise with the
manual-placement recipe instead of hanging on DNS. The Dataset machinery
itself (paddle.io) is fully functional — point it at local files."""
from ..io import Dataset

__all__ = ["Conll05st", "Imdb", "Imikolov", "Movielens", "UCIHousing",
           "WMT14", "WMT16", "ViterbiDecoder", "viterbi_decode"]


def _offline(name, url_hint):
    raise RuntimeError(
        f"paddle.text.{name} downloads its corpus on first use "
        f"({url_hint}); this environment has no network egress. Download "
        f"the archive elsewhere and build a paddle.io.Dataset over the "
        f"local files.")


def _make_dataset(name, url_hint):
    class _D(Dataset):
        def __init__(self, *a, **k):
            _offline(name, url_hint)

    _D.__name__ = name
    _D.__qualname__ = name
    return _D


Conll05st = _make_dataset("Conll05st", "conll05st-tests.tar.gz")
Imdb = _make_dataset("Imdb", "aclImdb_v1.tar.gz")
Imikolov = _make_dataset("Imikolov", "simple-examples.tgz")
Movielens = _make_dataset("Movielens", "ml-1m.zip")
UCIHousing = _make_dataset("UCIHousing", "housing.data")
WMT14 = _make_dataset("WMT14", "wmt14.tgz")
WMT16 = _make_dataset("WMT16", "wmt16.tar.gz")


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=False):
    """CRF Viterbi decode (reference paddle.text.viterbi_decode): returns
    (scores, best paths). Pure lax.scan over time — jit/batch friendly.

    ``lengths`` masks padded timesteps (padded path entries are 0 and
    contribute nothing to the score). ``include_bos_eos_tag`` applies the
    reference's boundary convention: the LAST tag index is BOS (its
    transition row scores the first step) and the second-to-last is EOS
    (its transition column scores each sequence's final step).
    """
    import jax
    import jax.numpy as jnp

    from ..core.tensor import Tensor
    from ..ops._op import unwrap
    pot = unwrap(potentials)        # [B, T, N]
    trans = unwrap(transition_params)  # [N, N]
    B, T, N = pot.shape
    if lengths is None:
        lens = jnp.full((B,), T, jnp.int32)
    else:
        lens = unwrap(lengths).astype(jnp.int32)

    init = pot[:, 0]
    if include_bos_eos_tag:
        init = init + trans[N - 1][None, :]

    def body(carry, xs):
        score, t = carry
        emit = xs  # [B, N]
        cand = score[:, :, None] + trans[None]  # [B, N, N]
        best_prev = jnp.argmax(cand, axis=1)    # [B, N]
        new = jnp.max(cand, axis=1) + emit
        valid = (t < lens)[:, None]             # padded step: freeze
        score = jnp.where(valid, new, score)
        bp = jnp.where(valid, best_prev,
                       jnp.broadcast_to(jnp.arange(N)[None], (B, N)))
        return (score, t + 1), bp

    (score, _), backptrs = jax.lax.scan(
        body, (init, jnp.int32(1)), jnp.swapaxes(pot[:, 1:], 0, 1))
    if include_bos_eos_tag:
        score = score + trans[:, N - 2][None, :]
    last_idx = jnp.argmax(score, axis=1)  # [B]
    best_score = jnp.max(score, axis=1)

    def walk(carry, bp):  # reverse walk through backpointers
        # carry = state at time k+1; bp maps it to the best state at k
        # (identity on padded steps, so the walk passes through them).
        # Output prev (state k): stacked outputs are times 0..T-2, with
        # last_idx appended as time T-1.
        idx = carry
        prev = jnp.take_along_axis(bp, idx[:, None], axis=1)[:, 0]
        return prev, prev

    _, path_rev = jax.lax.scan(walk, last_idx, backptrs, reverse=True)
    paths = jnp.concatenate(
        [jnp.swapaxes(path_rev, 0, 1), last_idx[:, None]], axis=1)
    paths = jnp.where(jnp.arange(T)[None, :] < lens[:, None], paths, 0)
    return Tensor(best_score), Tensor(paths.astype(jnp.int32))


class ViterbiDecoder:
    """Layer-shaped wrapper (reference paddle.text.ViterbiDecoder)."""

    def __init__(self, transitions, include_bos_eos_tag=False, name=None):
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def __call__(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              include_bos_eos_tag=self.include_bos_eos_tag)
