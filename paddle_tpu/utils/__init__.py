from . import flags
from .flags import get_flags, set_flags
from . import log as logger  # noqa: F401
from . import dlpack  # noqa: F401
from . import unique_name  # noqa: F401


def try_import(name):
    import importlib
    try:
        return importlib.import_module(name)
    except ImportError:
        return None


def run_check():
    """paddle.utils.run_check analog: verify the device works end-to-end."""
    import jax
    import jax.numpy as jnp
    x = jnp.ones((128, 128), jnp.bfloat16)
    y = jax.jit(lambda a: a @ a)(x)
    y.block_until_ready()
    dev = jax.devices()[0]
    print(f"paddle_tpu is installed and working on {dev.platform} ({dev.device_kind}).")
    return True


class deprecated:
    def __init__(self, update_to="", since="", reason=""):
        self.update_to = update_to

    def __call__(self, fn):
        return fn
