"""paddle.utils.cpp_extension (reference:
``python/paddle/utils/cpp_extension/`` † + the ``PD_BUILD_OP`` custom-op C
API, ``paddle/phi/api/ext/`` †).

TPU-native design: a custom C++ op cannot inject device code into XLA the
way ``PD_BUILD_OP`` injects CUDA kernels — on TPU, device-side custom
kernels are Pallas (``paddle_tpu.kernels``). What this module provides is
the reference's *out-of-tree extension* capability: compile user C++ with
the in-image g++ (plain C ABI, ctypes — no pybind11 in this environment),
and lift exported symbols into framework ops that run as **host
callbacks** (``jax.pure_callback``) — usable under jit, vmapped batch
dims excluded, with an optional custom gradient via a paired backward
symbol. This mirrors the role of the reference's CPU custom kernels;
docs steer hot-path work to Pallas.

Exported-symbol ABI (documented contract, float32 v1)::

    extern "C" void op(int n_in, const float** ins,
                       const int64_t* in_sizes, float* out,
                       int64_t out_size);
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

__all__ = ["load", "CppExtension", "CUDAExtension", "BuildExtension",
           "CustomOpLibrary"]

_ARGTYPES = [
    ctypes.c_int,
    ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
    ctypes.POINTER(ctypes.c_int64),
    ctypes.POINTER(ctypes.c_float),
    ctypes.c_int64,
]


class CustomOpLibrary:
    """A loaded extension; ``def_op`` lifts exported symbols into ops."""

    def __init__(self, name, cdll, path):
        self.name = name
        self._cdll = cdll
        self.path = path

    def def_op(self, symbol, out_shape_fn=None, backward_symbol=None):
        """Wrap C ``symbol`` as a framework op over float32 tensors.

        ``out_shape_fn(*input_shapes) -> shape`` (default: first input's
        shape). ``backward_symbol`` names a C function with the same ABI
        computing dx from (inputs..., grad_out) — without it the op is
        non-differentiable, like a reference custom op with no grad
        kernel registered.
        """
        import jax
        import jax.numpy as jnp

        from ..core.tensor import Tensor

        cfn = getattr(self._cdll, symbol)
        cfn.argtypes = _ARGTYPES
        cfn.restype = None
        bfn = None
        if backward_symbol is not None:
            bfn = getattr(self._cdll, backward_symbol)
            bfn.argtypes = _ARGTYPES
            bfn.restype = None

        def call_c(fn, arrays, out_shape):
            arrays = [np.ascontiguousarray(a, np.float32) for a in arrays]
            out = np.zeros(out_shape, np.float32)
            ins = (ctypes.POINTER(ctypes.c_float) * len(arrays))(*[
                a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
                for a in arrays])
            sizes = (ctypes.c_int64 * len(arrays))(*[a.size for a in arrays])
            fn(len(arrays), ins, sizes,
               out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), out.size)
            return out

        def fwd_raw(*vals):
            shape = (out_shape_fn(*[v.shape for v in vals])
                     if out_shape_fn else vals[0].shape)
            result = jax.ShapeDtypeStruct(tuple(shape), jnp.float32)
            return jax.pure_callback(
                lambda *a: call_c(cfn, a, tuple(shape)), result, *vals)

        if bfn is None:
            op_fn = fwd_raw
        else:
            @jax.custom_vjp
            def op_fn(*vals):
                return fwd_raw(*vals)

            def fwd_rule(*vals):
                return fwd_raw(*vals), vals

            def bwd_rule(res, g):
                # backward symbol computes cotangents for ALL inputs,
                # concatenated flat in input order
                total = sum(int(np.prod(v.shape)) for v in res)
                flat = jax.pure_callback(
                    lambda *a: call_c(bfn, a, (total,)),
                    jax.ShapeDtypeStruct((total,), jnp.float32),
                    *res, g)
                outs = []
                off = 0
                for v in res:
                    n = int(np.prod(v.shape))
                    outs.append(flat[off:off + n].reshape(v.shape))
                    off += n
                return tuple(outs)

            op_fn.defvjp(fwd_rule, bwd_rule)

        from ..ops._op import tensor_op
        wrapped = tensor_op(differentiable=bfn is not None)(
            lambda *vals: op_fn(*vals))
        wrapped.__name__ = symbol
        return wrapped

    def __getattr__(self, symbol):
        return getattr(self._cdll, symbol)


def load(name, sources, extra_cflags=None, extra_ldflags=None,
         build_directory=None, verbose=False, **kw):
    """Compile ``sources`` into a shared library and load it (reference
    ``cpp_extension.load`` JIT-build path). Rebuilds only when sources
    change (content hash in the artifact name)."""
    sources = [sources] if isinstance(sources, str) else list(sources)
    build_directory = build_directory or os.path.join(
        tempfile.gettempdir(), "paddle_tpu_extensions")
    os.makedirs(build_directory, exist_ok=True)
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    for flag in (extra_cflags or []) + (extra_ldflags or []):
        h.update(flag.encode())
    so = os.path.join(build_directory, f"{name}_{h.hexdigest()[:16]}.so")
    if not os.path.exists(so):
        # build to a temp path + atomic rename: K launcher-spawned ranks
        # calling load() concurrently must never dlopen a half-written .so
        tmp = f"{so}.tmp.{os.getpid()}"
        cmd = (["g++", "-O2", "-shared", "-fPIC", "-std=c++17"]
               + (extra_cflags or []) + sources
               + (extra_ldflags or []) + ["-o", tmp])
        if verbose:
            print("building:", " ".join(cmd))
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=300)
            os.rename(tmp, so)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"cpp_extension build failed:\n{e.stderr.decode()}") from e
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return CustomOpLibrary(name, ctypes.CDLL(so), so)


class CppExtension:
    """setuptools-Extension-shaped shim (reference ``CppExtension``)."""

    def __init__(self, sources, *args, **kwargs):
        self.sources = sources
        self.name = kwargs.get("name")
        self.kwargs = kwargs


def CUDAExtension(*args, **kwargs):
    raise RuntimeError(
        "CUDAExtension targets the reference's CUDA backend; on TPU, write "
        "device kernels with Pallas (paddle_tpu.kernels) and host-side "
        "extensions with CppExtension/load")


class BuildExtension:
    """Stand-in for the reference's setuptools build_ext command: builds
    each CppExtension with the same g++ pipeline as :func:`load`."""

    def __init__(self, *args, **kwargs):
        pass

    @classmethod
    def with_options(cls, **options):
        return cls

    def build_extensions(self, extensions, build_directory=None):
        return [load(getattr(e, "name", None) or f"ext{i}", e.sources,
                     build_directory=build_directory)
                for i, e in enumerate(extensions)]
