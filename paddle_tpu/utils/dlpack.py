"""DLPack interop (reference: python/paddle/utils/dlpack.py †).

Zero-copy tensor exchange with any DLPack-speaking framework (torch, numpy,
cupy, ...). jax arrays implement ``__dlpack__``/``__dlpack_device__``, so
``to_dlpack`` returns the standard capsule and ``from_dlpack`` accepts
either a capsule or an object implementing the protocol (the modern
``__dlpack__`` form torch/numpy produce).
"""
import jax.numpy as jnp

__all__ = ["to_dlpack", "from_dlpack"]


def to_dlpack(x):
    """Tensor -> DLPack capsule (consumable exactly once by a peer
    framework's ``from_dlpack``).

    DLPack has no TPU device type, so a TPU-resident array is copied to
    host first and the capsule describes the host buffer (no longer
    zero-copy — the interop contract survives, the aliasing does not)."""
    import jax

    from ..core.tensor import Tensor
    v = x.value if isinstance(x, Tensor) else jnp.asarray(x)
    try:
        return v.__dlpack__()
    except (TypeError, ValueError, RuntimeError):
        import numpy as np
        return np.asarray(jax.device_get(v)).__dlpack__()


class _CapsuleShim:
    """Adapter for legacy PyCapsule input: modern jax only consumes objects
    implementing ``__dlpack__``/``__dlpack_device__``, while the reference's
    ``to_dlpack`` (and torch's) hand out bare capsules. The device tuple is
    read from the DLManagedTensor struct the capsule carries."""

    def __init__(self, capsule):
        self._capsule = capsule
        self._device = _capsule_device(capsule)

    def __dlpack__(self, **_kw):
        return self._capsule

    def __dlpack_device__(self):
        return self._device


def _capsule_device(capsule):
    """(device_type, device_id) from a 'dltensor' capsule via the stable
    DLPack ABI: DLTensor starts with {void* data; int32 device_type;
    int32 device_id; ...}."""
    import ctypes
    get = ctypes.pythonapi.PyCapsule_GetPointer
    get.restype = ctypes.c_void_p
    get.argtypes = [ctypes.py_object, ctypes.c_char_p]
    ptr = get(capsule, b"dltensor")
    base = ctypes.cast(ptr, ctypes.POINTER(ctypes.c_int32))
    ptr_words = ctypes.sizeof(ctypes.c_void_p) // 4
    return int(base[ptr_words]), int(base[ptr_words + 1])


def from_dlpack(dlpack):
    """DLPack capsule or ``__dlpack__``-implementing object -> Tensor."""
    from ..core.tensor import Tensor
    if not hasattr(dlpack, "__dlpack__"):  # legacy capsule
        dlpack = _CapsuleShim(dlpack)
    return Tensor(jnp.from_dlpack(dlpack))
