"""Unified flag/config system (reference: gflags-style ``FLAGS_*`` in
``paddle/phi/core/flags.cc`` + ``paddle.set_flags``).

One dataclass-free registry serving the reference's three config planes:
C++-style FLAGS (env-overridable), runtime set_flags, and introspection.
"""
from __future__ import annotations

import os
from typing import Any, Dict

_REGISTRY: Dict[str, Any] = {}


def define_flag(name: str, default, help_str: str = ""):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    env = os.environ.get(name)
    value = default
    if env is not None:
        if isinstance(default, bool):
            value = env.lower() in ("1", "true", "yes")
        elif isinstance(default, int):
            value = int(env)
        elif isinstance(default, float):
            value = float(env)
        else:
            value = env
    _REGISTRY[name] = value
    return value


def get_flags(flags=None):
    if flags is None:
        return dict(_REGISTRY)
    if isinstance(flags, str):
        flags = [flags]
    return {f: _REGISTRY[f] for f in flags}


def set_flags(flags: Dict[str, Any]):
    for k, v in flags.items():
        if not k.startswith("FLAGS_"):
            k = "FLAGS_" + k
        _REGISTRY[k] = v


def get_flag(name, default=None):
    if not name.startswith("FLAGS_"):
        name = "FLAGS_" + name
    if name not in _REGISTRY and default is not None:
        return define_flag(name, default)
    return _REGISTRY.get(name, default)


# Core flags mirroring the reference's most-used ones
define_flag("FLAGS_check_nan_inf", False,
            "instrument jitted steps with NaN/Inf checks (debug_nans)")
define_flag("FLAGS_embedding_deterministic", True, "always true on TPU/XLA")
define_flag("FLAGS_cudnn_deterministic", True, "always true on TPU/XLA")
define_flag("FLAGS_allocator_strategy", "xla",
            "allocator is XLA's (BFC on host, HBM arena on device)")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 1.0, "XLA-managed")
define_flag("FLAGS_use_pallas_kernels", True,
            "use Pallas fused kernels (flash attention etc.) when on TPU")
define_flag("FLAGS_static_strict", False,
            "promote the static-capture constant-hazard warning (a tensor "
            "created inside program_guard without going through the op "
            "dispatch is frozen as a build-time constant) to an error")
