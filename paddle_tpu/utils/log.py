"""Rank-aware logging (reference: fleet ``log_util.py`` + launcher logs)."""
from __future__ import annotations

import logging
import os
import sys

_LOGGERS = {}


def get_logger(name="paddle_tpu", level=None):
    if name in _LOGGERS:
        return _LOGGERS[name]
    logger = logging.getLogger(name)
    rank = os.environ.get("PADDLE_TRAINER_ID", "0")
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(logging.Formatter(
        f"[%(asctime)s] [rank {rank}] %(levelname)s %(name)s: %(message)s"))
    logger.addHandler(handler)
    logger.setLevel(level or os.environ.get("PADDLE_LOG_LEVEL", "INFO").upper())
    logger.propagate = False
    _LOGGERS[name] = logger
    return logger


logger = get_logger()


def log_rank0(msg):
    if os.environ.get("PADDLE_TRAINER_ID", "0") == "0":
        logger.info(msg)
