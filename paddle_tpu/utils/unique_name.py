"""Unique name generation (reference: python/paddle/utils/unique_name.py †
— the name mint behind auto-assigned parameter/op names).

``generate("fc")`` -> "fc_0", "fc_1", ...; ``guard()`` scopes a fresh
generator (optionally prefixed) so names inside the with-block restart
from zero; ``switch`` swaps the active generator and returns the old one.
"""
import contextlib

__all__ = ["generate", "guard", "switch"]


class _NameGenerator:
    def __init__(self, prefix=""):
        self.prefix = prefix
        self._counters = {}

    def generate(self, key):
        n = self._counters.get(key, 0)
        self._counters[key] = n + 1
        return f"{self.prefix}{key}_{n}"


_generator = _NameGenerator()


def generate(key):
    return _generator.generate(key)


def switch(new_generator=None):
    global _generator
    old = _generator
    _generator = new_generator if new_generator is not None \
        else _NameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    if isinstance(new_generator, str):
        new_generator = _NameGenerator(new_generator)
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
