full_version = "0.1.0"
major = 0
minor = 1
patch = 0
commit = "unknown"


def show():
    print(f"paddle_tpu {full_version} (TPU-native, JAX/XLA/Pallas core)")
