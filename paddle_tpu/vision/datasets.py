"""Vision datasets (reference: ``python/paddle/vision/datasets/``).

Zero-egress environment: no downloads. ``FakeData`` provides synthetic
ImageNet-shaped data (benchmarks / smoke tests); file-backed datasets read
local directories.
"""
from __future__ import annotations

import os

import numpy as np

from ..io.dataset import Dataset


class FakeData(Dataset):
    """Synthetic dataset with deterministic per-index samples."""

    def __init__(self, size=1000, image_shape=(3, 224, 224), num_classes=1000,
                 transform=None, dtype=np.float32):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.dtype = dtype

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx % 2 ** 31)
        img = rng.standard_normal(self.image_shape).astype(self.dtype)
        label = np.asarray(rng.randint(0, self.num_classes), np.int32)
        if self.transform:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return self.size


class DatasetFolder(Dataset):
    """ImageFolder-style local-directory dataset (requires a local image
    decoder; npy/npz files are supported natively)."""

    def __init__(self, root, loader=None, extensions=(".npy",), transform=None):
        self.root = root
        self.transform = transform
        self.loader = loader or (lambda p: np.load(p))
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for f in sorted(os.listdir(d)):
                if f.lower().endswith(tuple(extensions)):
                    self.samples.append((os.path.join(d, f), self.class_to_idx[c]))

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, np.asarray(label, np.int32)

    def __len__(self):
        return len(self.samples)


ImageFolder = DatasetFolder


class MNIST(Dataset):
    """MNIST from local idx files (``image_path``/``label_path`` required —
    zero-egress)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download:
            raise RuntimeError("downloads unavailable (zero-egress environment)")
        if image_path is None or label_path is None:
            raise ValueError("provide local image_path/label_path idx files")
        import gzip
        op = gzip.open if image_path.endswith(".gz") else open
        with op(image_path, "rb") as f:
            f.read(16)
            self.images = np.frombuffer(f.read(), np.uint8).reshape(-1, 28, 28)
        op = gzip.open if label_path.endswith(".gz") else open
        with op(label_path, "rb") as f:
            f.read(8)
            self.labels = np.frombuffer(f.read(), np.uint8)
        self.transform = transform

    def __getitem__(self, idx):
        img = self.images[idx][:, :, None]
        if self.transform:
            img = self.transform(img)
        return img, np.asarray(self.labels[idx], np.int32)

    def __len__(self):
        return len(self.images)
