"""DenseNet (reference: ``python/paddle/vision/models/densenet.py``)."""
from ... import nn


class _DenseLayer(nn.Layer):
    """BN-ReLU-Conv1x1(bn_size*growth) -> BN-ReLU-Conv3x3(growth)."""

    def __init__(self, in_c, growth_rate, bn_size, dropout):
        super().__init__()
        self.dropout = dropout
        self.fn = nn.Sequential(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, bn_size * growth_rate, 1, bias_attr=False),
            nn.BatchNorm2D(bn_size * growth_rate), nn.ReLU(),
            nn.Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                      bias_attr=False))
        if dropout:
            self.drop = nn.Dropout(dropout)

    def forward(self, x):
        from ...ops import concat
        y = self.fn(x)
        if self.dropout:
            y = self.drop(y)
        return concat([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_c, out_c):
        super().__init__()
        self.fn = nn.Sequential(
            nn.BatchNorm2D(in_c), nn.ReLU(),
            nn.Conv2D(in_c, out_c, 1, bias_attr=False),
            nn.AvgPool2D(2, stride=2))

    def forward(self, x):
        return self.fn(x)


_ARCH = {
    121: (64, 32, (6, 12, 24, 16)),
    161: (96, 48, (6, 12, 36, 24)),
    169: (64, 32, (6, 12, 32, 32)),
    201: (64, 32, (6, 12, 48, 32)),
    264: (64, 32, (6, 12, 64, 48)),
}


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        if layers not in _ARCH:
            raise ValueError(f"supported layers: {sorted(_ARCH)}, got {layers}")
        init_c, growth, block_cfg = _ARCH[layers]
        feats = [nn.Conv2D(3, init_c, 7, stride=2, padding=3, bias_attr=False),
                 nn.BatchNorm2D(init_c), nn.ReLU(),
                 nn.MaxPool2D(3, stride=2, padding=1)]
        c = init_c
        for bi, n in enumerate(block_cfg):
            for _ in range(n):
                feats.append(_DenseLayer(c, growth, bn_size, dropout))
                c += growth
            if bi != len(block_cfg) - 1:
                feats.append(_Transition(c, c // 2))
                c //= 2
        feats += [nn.BatchNorm2D(c), nn.ReLU()]
        self.features = nn.Sequential(*feats)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Linear(c, num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops import flatten
            x = self.classifier(flatten(x, 1))
        return x


def _densenet(layers, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
