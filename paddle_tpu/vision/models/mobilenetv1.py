"""MobileNetV1 (reference: ``python/paddle/vision/models/mobilenetv1.py``)."""
from ... import nn


def _conv_bn(in_c, out_c, k, stride=1, padding=0, groups=1):
    return nn.Sequential(
        nn.Conv2D(in_c, out_c, k, stride=stride, padding=padding,
                  groups=groups, bias_attr=False),
        nn.BatchNorm2D(out_c), nn.ReLU())


class _DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c, stride):
        super().__init__()
        self.fn = nn.Sequential(
            _conv_bn(in_c, in_c, 3, stride=stride, padding=1, groups=in_c),
            _conv_bn(in_c, out_c, 1))

    def forward(self, x):
        return self.fn(x)


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def c(ch):
            return int(ch * scale)

        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        layers = [_conv_bn(3, c(32), 3, stride=2, padding=1)]
        layers += [_DepthwiseSeparable(c(i), c(o), s) for i, o, s in cfg]
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(c(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops import flatten
            x = self.fc(flatten(x, 1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return MobileNetV1(scale=scale, **kwargs)
