"""MobileNetV3 small/large (reference:
``python/paddle/vision/models/mobilenetv3.py``)."""
from ... import nn


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _act(name):
    return nn.Hardswish() if name == "HS" else nn.ReLU()


class _SqueezeExcite(nn.Layer):
    def __init__(self, ch):
        super().__init__()
        mid = _make_divisible(ch // 4)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.fc1 = nn.Conv2D(ch, mid, 1)
        self.fc2 = nn.Conv2D(mid, ch, 1)
        self.relu = nn.ReLU()
        self.hsig = nn.Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.pool(x)))))
        return x * s


class _InvertedResidualV3(nn.Layer):
    def __init__(self, in_c, exp, out_c, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if exp != in_c:
            layers += [nn.Conv2D(in_c, exp, 1, bias_attr=False),
                       nn.BatchNorm2D(exp), _act(act)]
        layers += [nn.Conv2D(exp, exp, k, stride=stride, padding=k // 2,
                             groups=exp, bias_attr=False),
                   nn.BatchNorm2D(exp), _act(act)]
        if use_se:
            layers.append(_SqueezeExcite(exp))
        layers += [nn.Conv2D(exp, out_c, 1, bias_attr=False),
                   nn.BatchNorm2D(out_c)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


# (kernel, expansion, out_channels, use_se, activation, stride)
_LARGE = [(3, 16, 16, False, "RE", 1), (3, 64, 24, False, "RE", 2),
          (3, 72, 24, False, "RE", 1), (5, 72, 40, True, "RE", 2),
          (5, 120, 40, True, "RE", 1), (5, 120, 40, True, "RE", 1),
          (3, 240, 80, False, "HS", 2), (3, 200, 80, False, "HS", 1),
          (3, 184, 80, False, "HS", 1), (3, 184, 80, False, "HS", 1),
          (3, 480, 112, True, "HS", 1), (3, 672, 112, True, "HS", 1),
          (5, 672, 160, True, "HS", 2), (5, 960, 160, True, "HS", 1),
          (5, 960, 160, True, "HS", 1)]
_SMALL = [(3, 16, 16, True, "RE", 2), (3, 72, 24, False, "RE", 2),
          (3, 88, 24, False, "RE", 1), (5, 96, 40, True, "HS", 2),
          (5, 240, 40, True, "HS", 1), (5, 240, 40, True, "HS", 1),
          (5, 120, 48, True, "HS", 1), (5, 144, 48, True, "HS", 1),
          (5, 288, 96, True, "HS", 2), (5, 576, 96, True, "HS", 1),
          (5, 576, 96, True, "HS", 1)]


class MobileNetV3(nn.Layer):
    def __init__(self, cfg, last_c, head_c, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        in_c = _make_divisible(16 * scale)
        layers = [nn.Conv2D(3, in_c, 3, stride=2, padding=1, bias_attr=False),
                  nn.BatchNorm2D(in_c), nn.Hardswish()]
        for k, exp, out_c, se, act, s in cfg:
            exp_c = _make_divisible(exp * scale)
            o = _make_divisible(out_c * scale)
            layers.append(_InvertedResidualV3(in_c, exp_c, o, k, s, se, act))
            in_c = o
        lc = _make_divisible(last_c * scale)
        layers += [nn.Conv2D(in_c, lc, 1, bias_attr=False),
                   nn.BatchNorm2D(lc), nn.Hardswish()]
        self.features = nn.Sequential(*layers)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Linear(lc, head_c), nn.Hardswish(), nn.Dropout(0.2),
                nn.Linear(head_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops import flatten
            x = self.classifier(flatten(x, 1))
        return x


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_LARGE, 960, 1280, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_SMALL, 576, 1024, scale=scale,
                         num_classes=num_classes, with_pool=with_pool)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return MobileNetV3Large(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return MobileNetV3Small(scale=scale, **kwargs)
