"""ShuffleNetV2 (reference: ``python/paddle/vision/models/shufflenetv2.py``)."""
from ... import nn
from ...nn import functional as F


def _act_layer(act):
    return nn.Swish() if act == "swish" else nn.ReLU()


def _conv_bn_act(in_c, out_c, k, stride, groups=1, act="relu"):
    layers = [nn.Conv2D(in_c, out_c, k, stride=stride, padding=k // 2,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_c)]
    if act:
        layers.append(_act_layer(act))
    return nn.Sequential(*layers)


class _ShuffleUnit(nn.Layer):
    """stride-1 unit: split channels, transform one half, shuffle."""

    def __init__(self, ch, act):
        super().__init__()
        half = ch // 2
        self.branch = nn.Sequential(
            _conv_bn_act(half, half, 1, 1, act=act),
            _conv_bn_act(half, half, 3, 1, groups=half, act=None),
            _conv_bn_act(half, half, 1, 1, act=act))

    def forward(self, x):
        from ...ops import concat, split
        x1, x2 = split(x, 2, axis=1)
        out = concat([x1, self.branch(x2)], axis=1)
        return F.channel_shuffle(out, 2)


class _ShuffleUnitDown(nn.Layer):
    """stride-2 unit: both branches downsample, concat doubles channels."""

    def __init__(self, in_c, out_c, act):
        super().__init__()
        half = out_c // 2
        self.branch1 = nn.Sequential(
            _conv_bn_act(in_c, in_c, 3, 2, groups=in_c, act=None),
            _conv_bn_act(in_c, half, 1, 1, act=act))
        self.branch2 = nn.Sequential(
            _conv_bn_act(in_c, half, 1, 1, act=act),
            _conv_bn_act(half, half, 3, 2, groups=half, act=None),
            _conv_bn_act(half, half, 1, 1, act=act))

    def forward(self, x):
        from ...ops import concat
        out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return F.channel_shuffle(out, 2)


_STAGE_OUT = {
    0.25: (24, 24, 48, 96, 512),
    0.33: (24, 32, 64, 128, 512),
    0.5: (24, 48, 96, 192, 1024),
    1.0: (24, 116, 232, 464, 1024),
    1.5: (24, 176, 352, 704, 1024),
    2.0: (24, 244, 488, 976, 2048),
}
_STAGE_REPEATS = (4, 8, 4)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        if scale not in _STAGE_OUT:
            raise ValueError(f"supported scales: {sorted(_STAGE_OUT)}")
        chs = _STAGE_OUT[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _conv_bn_act(3, chs[0], 3, 2, act=act)
        self.max_pool = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_c = chs[0]
        for si, reps in enumerate(_STAGE_REPEATS):
            out_c = chs[si + 1]
            units = [_ShuffleUnitDown(in_c, out_c, act)]
            units += [_ShuffleUnit(out_c, act) for _ in range(reps - 1)]
            stages.append(nn.Sequential(*units))
            in_c = out_c
        self.stages = nn.Sequential(*stages)
        self.conv_last = _conv_bn_act(in_c, chs[4], 1, 1, act=act)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(chs[4], num_classes)

    def forward(self, x):
        x = self.max_pool(self.conv1(x))
        x = self.stages(x)
        x = self.conv_last(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            from ...ops import flatten
            x = self.fc(flatten(x, 1))
        return x


def _shufflenet(scale, act="relu", pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, pretrained=pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, pretrained=pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, pretrained=pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, act="swish", pretrained=pretrained, **kwargs)
