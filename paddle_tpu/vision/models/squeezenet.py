"""SqueezeNet (reference: ``python/paddle/vision/models/squeezenet.py``)."""
from ... import nn


class _Fire(nn.Layer):
    def __init__(self, in_c, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Sequential(nn.Conv2D(in_c, squeeze, 1), nn.ReLU())
        self.expand1 = nn.Sequential(nn.Conv2D(squeeze, e1, 1), nn.ReLU())
        self.expand3 = nn.Sequential(nn.Conv2D(squeeze, e3, 3, padding=1),
                                     nn.ReLU())

    def forward(self, x):
        from ...ops import concat
        s = self.squeeze(x)
        return concat([self.expand1(s), self.expand3(s)], axis=1)


class SqueezeNet(nn.Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            feats = [nn.Conv2D(3, 96, 7, stride=2), nn.ReLU(),
                     nn.MaxPool2D(3, stride=2),
                     _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                     _Fire(128, 32, 128, 128), nn.MaxPool2D(3, stride=2),
                     _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                     _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                     nn.MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256)]
        elif version == "1.1":
            feats = [nn.Conv2D(3, 64, 3, stride=2), nn.ReLU(),
                     nn.MaxPool2D(3, stride=2),
                     _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                     nn.MaxPool2D(3, stride=2),
                     _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                     nn.MaxPool2D(3, stride=2),
                     _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                     _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256)]
        else:
            raise ValueError(f"version must be '1.0' or '1.1', got {version}")
        self.features = nn.Sequential(*feats)
        if num_classes > 0:
            self.classifier_conv = nn.Sequential(
                nn.Dropout(0.5), nn.Conv2D(512, num_classes, 1), nn.ReLU())
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.classifier_conv(x)
        if self.with_pool:
            x = self.pool(x)
        from ...ops import flatten
        return flatten(x, 1)


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return SqueezeNet(version="1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights unavailable offline")
    return SqueezeNet(version="1.1", **kwargs)
