"""paddle.vision.ops — detection primitives (reference:
``python/paddle/vision/ops.py`` over the CUDA nms/roi_align/box kernels).

TPU-native: static-shape formulations — NMS is an O(N^2) mask + fixed-
iteration suppression scan (no dynamic output shapes: returns keep indices
padded with -1 when ``top_k`` is given, or a boolean keep mask), roi_align
is a bilinear gather; everything compiles under jit.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..ops._op import tensor_op

__all__ = ["nms", "box_iou", "box_area", "roi_align", "roi_pool",
           "box_coder", "distribute_fpn_proposals", "prior_box",
           "yolo_box", "deform_conv2d", "psroi_pool", "matrix_nms", "generate_proposals"]


def _iou_matrix(boxes_a, boxes_b, norm=0.0):
    """Pairwise IoU; ``norm=1.0`` is the reference's un-normalized
    (integer pixel) convention where spans are end - start + 1."""
    area_a = ((boxes_a[:, 2] - boxes_a[:, 0] + norm) *
              (boxes_a[:, 3] - boxes_a[:, 1] + norm))
    area_b = ((boxes_b[:, 2] - boxes_b[:, 0] + norm) *
              (boxes_b[:, 3] - boxes_b[:, 1] + norm))
    lt = jnp.maximum(boxes_a[:, None, :2], boxes_b[None, :, :2])
    rb = jnp.minimum(boxes_a[:, None, 2:], boxes_b[None, :, 2:])
    wh = jnp.clip(rb - lt + norm, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-9)


@tensor_op(differentiable=False)
def box_iou(boxes1, boxes2, name=None):
    return _iou_matrix(boxes1, boxes2)


@tensor_op
def box_area(boxes, name=None):
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


@tensor_op(differentiable=False)
def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None, _norm=0.0):
    """Hard NMS (reference paddle.vision.ops.nms): returns kept box indices
    sorted by descending score. Static-shape: the suppression runs as a
    fixed-length scan over all N candidates; with ``top_k`` the result is
    exactly top_k indices padded with -1. ``_norm=1.0`` switches the IoU
    to the +1-pixel span convention (generate_proposals' pixel_offset)."""
    n = boxes.shape[0]
    if scores is None:
        order = jnp.arange(n)
    else:
        order = jnp.argsort(-scores)
    sorted_boxes = boxes[order]
    iou = _iou_matrix(sorted_boxes, sorted_boxes, norm=_norm)
    if category_idxs is not None:
        # multiclass: suppress only within the same category
        cats = category_idxs[order]
        same = cats[:, None] == cats[None, :]
        iou = jnp.where(same, iou, 0.0)

    def body(keep, i):
        # suppressed if any higher-ranked KEPT box overlaps > threshold
        over = (iou[i] > iou_threshold) & (jnp.arange(n) < i) & keep
        keep = keep.at[i].set(~jnp.any(over))
        return keep, None

    keep, _ = jax.lax.scan(body, jnp.ones((n,), bool), jnp.arange(n))
    kept_sorted = jnp.where(keep, jnp.arange(n), n)  # suppressed -> sentinel
    ranked = jnp.sort(kept_sorted)  # kept positions in score order
    idx = jnp.where(ranked < n, order[jnp.clip(ranked, 0, n - 1)], -1)
    if top_k is not None:
        if top_k > n:  # keep the static [top_k] contract
            idx = jnp.concatenate(
                [idx, jnp.full((top_k - n,), -1, idx.dtype)])
        return idx[:top_k]
    # dynamic count is not jit-able; outside jit trim the -1 tail
    return idx[idx >= 0] if not isinstance(idx, jax.core.Tracer) else idx


@tensor_op
def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference roi_align): x [N,C,H,W], boxes [R,4] in
    (x1,y1,x2,y2); boxes_num [N] maps rois to images. Bilinear sampling at
    output_size^2 cells x sampling_ratio^2 points."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    N, C, H, W = x.shape
    R = boxes.shape[0]
    if boxes_num is None:
        if N != 1:
            raise ValueError(
                f"roi_align: boxes_num is required when the batch has "
                f"{N} images (otherwise every roi would read image 0)")
        img_of = jnp.zeros((R,), jnp.int32)
    else:
        img_of = jnp.repeat(jnp.arange(len(boxes_num)),
                            jnp.asarray(boxes_num), total_repeat_length=R)
    offset = 0.5 if aligned else 0.0
    sr = sampling_ratio if sampling_ratio > 0 else 2

    def one_roi(box, img):
        x1, y1, x2, y2 = (box * spatial_scale) - offset
        rw = jnp.maximum(x2 - x1, 1e-3)
        rh = jnp.maximum(y2 - y1, 1e-3)
        bin_w, bin_h = rw / ow, rh / oh
        # sample grid: [oh, sr] x [ow, sr]
        gy = y1 + (jnp.arange(oh)[:, None] + (jnp.arange(sr)[None, :] + 0.5)
                   / sr) * bin_h
        gx = x1 + (jnp.arange(ow)[:, None] + (jnp.arange(sr)[None, :] + 0.5)
                   / sr) * bin_w
        gy = gy.reshape(-1)  # [oh*sr]
        gx = gx.reshape(-1)  # [ow*sr]

        def bilinear(c_map):
            y0 = jnp.clip(jnp.floor(gy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(gx), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
            y0i, x0i = y0.astype(jnp.int32), x0.astype(jnp.int32)
            ly = jnp.clip(gy - y0, 0, 1)[:, None]
            lx = jnp.clip(gx - x0, 0, 1)[None, :]
            v00 = c_map[y0i][:, x0i]
            v01 = c_map[y0i][:, x1i]
            v10 = c_map[y1i][:, x0i]
            v11 = c_map[y1i][:, x1i]
            val = (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx +
                   v10 * ly * (1 - lx) + v11 * ly * lx)  # [oh*sr, ow*sr]
            val = val.reshape(oh, sr, ow, sr)
            return val.mean(axis=(1, 3))

        return jax.vmap(bilinear)(x[img])  # [C, oh, ow]

    return jax.vmap(one_roi)(boxes, img_of)  # [R, C, oh, ow]


@tensor_op
def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors (reference box_coder, the SSD/
    Faster-RCNN transform). 2-D target_box aligned 1:1 with priors only;
    the reference's 3-D [N,M,4] + axis broadcast is not implemented."""
    if target_box.ndim != 2 or axis != 0:
        raise NotImplementedError(
            "box_coder: only 2-D target_box with axis=0 is supported")
    norm = 0.0 if box_normalized else 1.0
    pw = prior_box[:, 2] - prior_box[:, 0] + norm
    ph = prior_box[:, 3] - prior_box[:, 1] + norm
    pcx = prior_box[:, 0] + pw * 0.5
    pcy = prior_box[:, 1] + ph * 0.5
    var = prior_box_var if prior_box_var is not None else jnp.ones((4,))
    if code_type == "encode_center_size":
        tw = target_box[:, 2] - target_box[:, 0] + norm
        th = target_box[:, 3] - target_box[:, 1] + norm
        tcx = target_box[:, 0] + tw * 0.5
        tcy = target_box[:, 1] + th * 0.5
        out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=-1)
        return out / var
    return _decode_center_size(target_box, var, pw, ph, pcx, pcy, norm)


def _decode_center_size(deltas, var, pw, ph, pcx, pcy, norm, clip=None):
    """Inverse of encode_center_size (shared by box_coder's decode branch
    and generate_proposals); ``clip`` caps the w/h log-deltas (the RPN
    kernel's kBBoxClipDefault)."""
    t = deltas * var
    tw, th = t[..., 2], t[..., 3]
    if clip is not None:
        tw = jnp.minimum(tw, clip)
        th = jnp.minimum(th, clip)
    cx = t[..., 0] * pw + pcx
    cy = t[..., 1] * ph + pcy
    w = jnp.exp(tw) * pw
    h = jnp.exp(th) * ph
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)


@tensor_op(differentiable=False)
def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, name=None):
    """FPN level assignment (reference distribute_fpn_proposals): returns
    the target level per roi (static-shape variant of the scatter)."""
    off = 1.0 if pixel_offset else 0.0
    w = fpn_rois[:, 2] - fpn_rois[:, 0] + off
    h = fpn_rois[:, 3] - fpn_rois[:, 1] + off
    scale = jnp.sqrt(jnp.maximum(w * h, 1e-9))
    lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-9)) + refer_level
    return jnp.clip(lvl, min_level, max_level).astype(jnp.int32)


def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
             name=None):
    """RoIPool (reference roi_pool): max pooling over quantized roi bins —
    the pre-RoIAlign detector op. x [N,C,H,W], boxes [R,4] (x1,y1,x2,y2).

    Reference quantization: rounded roi corners, roi span end-start+1,
    per-cell [floor(i*bin), ceil((i+1)*bin)) ranges clamped to the
    feature map (cells can OVERLAP), empty cells output 0."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    if boxes_num is None and x.shape[0] != 1:
        raise ValueError(
            f"roi_pool: boxes_num is required when the batch has "
            f"{x.shape[0]} images (otherwise every roi would read image 0)")
    return _roi_pool_impl(x, boxes, boxes_num, oh, ow, float(spatial_scale))


@tensor_op
def _roi_pool_impl(x, boxes, boxes_num, oh, ow, spatial_scale):
    N, C, H, W = x.shape
    R = boxes.shape[0]
    if boxes_num is None:
        img_of = jnp.zeros((R,), jnp.int32)
    else:
        img_of = jnp.repeat(jnp.arange(boxes_num.shape[0]),
                            boxes_num, total_repeat_length=R)
    NEG = jnp.asarray(-3.4e38, jnp.float32)

    def one_roi(args):
        box, img = args
        x1 = jnp.round(box[0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(box[1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(box[2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(box[3] * spatial_scale).astype(jnp.int32)
        roi_h = jnp.maximum(y2 - y1 + 1, 1).astype(jnp.float32)
        roi_w = jnp.maximum(x2 - x1 + 1, 1).astype(jnp.float32)
        bin_h, bin_w = roi_h / oh, roi_w / ow
        iy = jnp.arange(oh, dtype=jnp.float32)
        ix = jnp.arange(ow, dtype=jnp.float32)
        y0 = jnp.clip(y1 + jnp.floor(iy * bin_h).astype(jnp.int32), 0, H)
        ye = jnp.clip(y1 + jnp.ceil((iy + 1) * bin_h).astype(jnp.int32),
                      0, H)
        x0 = jnp.clip(x1 + jnp.floor(ix * bin_w).astype(jnp.int32), 0, W)
        xe = jnp.clip(x1 + jnp.ceil((ix + 1) * bin_w).astype(jnp.int32),
                      0, W)
        ys, xs = jnp.arange(H), jnp.arange(W)
        my = (ys[:, None] >= y0[None]) & (ys[:, None] < ye[None])  # [H,oh]
        mx = (xs[:, None] >= x0[None]) & (xs[:, None] < xe[None])  # [W,ow]
        feat = x[img].astype(jnp.float32)                          # [C,H,W]
        # separable masked max: rows first ([C,oh,W]), then cols
        rows = jnp.max(jnp.where(my.T[None, :, :, None],
                                 feat[:, None, :, :], NEG), axis=2)
        out = jnp.max(jnp.where(mx.T[None, None, :, :],
                                rows[:, :, None, :], NEG), axis=3)
        return jnp.where(out <= NEG / 2, 0.0, out).astype(x.dtype)

    # lax.map (sequential over rois) bounds live memory at one roi's
    # [C, oh, H, W] mask product instead of R of them
    return jax.lax.map(one_roi, (boxes, img_of))


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) boxes (reference prior_box): one box per
    (feature cell, size/aspect combo), normalized (x1,y1,x2,y2) + per-box
    variances."""
    import numpy as np

    from ..core.tensor import Tensor
    fh, fw = (int(input.shape[2]), int(input.shape[3]))
    ih, iw = (int(image.shape[2]), int(image.shape[3]))
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if all(abs(ar - a) > 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for s, ms in enumerate(min_sizes):
        whs = []
        for ar in ars:
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            big = np.sqrt(ms * max_sizes[s])
            if min_max_aspect_ratios_order:
                whs.insert(1, (big, big))   # Caffe order: [min, max, ars]
            else:
                whs.append((big, big))      # default: [min, ars..., max]
        boxes.append(whs)
    cy = (np.arange(fh) + offset) * step_h
    cx = (np.arange(fw) + offset) * step_w
    out = []
    for y in cy:
        row = []
        for x_ in cx:
            cell = []
            for whs in boxes:
                for (w, h) in whs:
                    cell.append([(x_ - w / 2) / iw, (y - h / 2) / ih,
                                 (x_ + w / 2) / iw, (y + h / 2) / ih])
            row.append(cell)
        out.append(row)
    arr = np.asarray(out, np.float32)  # [fh, fw, P, 4]
    if clip:
        arr = np.clip(arr, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          arr.shape).copy()
    return Tensor(jnp.asarray(arr)), Tensor(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """YOLOv3 head decode (reference yolo_box): raw feature map
    [N, A*(5+C), H, W] -> boxes [N, H*W*A, 4] + scores [N, H*W*A, C]."""
    if iou_aware:
        raise NotImplementedError(
            "yolo_box iou_aware=True (the [N, A*(6+C), H, W] layout) is "
            "not implemented")
    return _yolo_box_impl(x, img_size, tuple(anchors), int(class_num),
                          float(conf_thresh), int(downsample_ratio),
                          bool(clip_bbox), float(scale_x_y))


@tensor_op
def _yolo_box_impl(x, img_size, anchors, class_num, conf_thresh,
                   downsample_ratio, clip_bbox, scale_x_y):
    N, _, H, W = x.shape
    A = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(A, 2)
    v = x.reshape(N, A, 5 + class_num, H, W)
    gx = jnp.arange(W, dtype=jnp.float32)[None, None, None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[None, None, :, None]
    sig = jax.nn.sigmoid
    bx = (gx + sig(v[:, :, 0]) * scale_x_y
          - (scale_x_y - 1.0) / 2.0) / W
    by = (gy + sig(v[:, :, 1]) * scale_x_y
          - (scale_x_y - 1.0) / 2.0) / H
    bw = jnp.exp(v[:, :, 2]) * an[None, :, 0, None, None] \
        / (downsample_ratio * W)
    bh = jnp.exp(v[:, :, 3]) * an[None, :, 1, None, None] \
        / (downsample_ratio * H)
    conf = sig(v[:, :, 4])
    probs = sig(v[:, :, 5:]) * conf[:, :, None]
    # to absolute pixel corners against per-image (h, w)
    imh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    keep = conf > conf_thresh
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
    boxes = jnp.where(keep[..., None], boxes, 0.0)
    probs = jnp.where(keep[..., None], jnp.moveaxis(probs, 2, -1), 0.0)
    boxes = boxes.reshape(N, A * H * W, 4)
    scores = probs.reshape(N, A * H * W, class_num)
    return boxes, scores


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference deform_conv2d over the
    deformable_conv CUDA kernel †). TPU formulation: the per-tap bilinear
    sampling is four flat gathers (take_along_axis) and the convolution
    itself collapses to one einsum over (in-channel, tap) — gathers feed
    the MXU contraction instead of the reference's im2col+atomics.

    x [B,Cin,H,W]; offset [B, 2*dg*kh*kw, Ho, Wo] laid out (group, tap,
    (dy,dx)); mask [B, dg*kh*kw, Ho, Wo] enables the v2 modulated path."""
    sh, sw = (stride, stride) if isinstance(stride, int) else stride
    ph, pw = (padding, padding) if isinstance(padding, int) else padding
    dh, dw = (dilation, dilation) if isinstance(dilation, int) else dilation
    return _deform_conv2d_impl(x, offset, weight, bias, mask,
                               sh, sw, ph, pw, dh, dw,
                               int(deformable_groups), int(groups))


@tensor_op
def _deform_conv2d_impl(x, offset, weight, bias, mask, sh, sw, ph, pw,
                        dh, dw, dg, groups):
    B, Cin, H, W = x.shape
    Cout, Cg, kh, kw = weight.shape
    T = kh * kw
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    off = offset.reshape(B, dg, T, 2, Ho, Wo)
    # sampling positions per (batch, dgroup, tap, out-pixel)
    tap_dy = (jnp.arange(kh) * dh)[:, None].repeat(kw, 1).reshape(T)
    tap_dx = (jnp.arange(kw) * dw)[None, :].repeat(kh, 0).reshape(T)
    base_y = (jnp.arange(Ho) * sh - ph)[:, None]
    base_x = (jnp.arange(Wo) * sw - pw)[None, :]
    py = base_y[None, None, None] + tap_dy[None, None, :, None, None] \
        + off[:, :, :, 0]
    px = base_x[None, None, None] + tap_dx[None, None, :, None, None] \
        + off[:, :, :, 1]                       # [B, dg, T, Ho, Wo]

    Cd = Cin // dg
    xg = x.reshape(B, dg, Cd, H * W)
    y0 = jnp.floor(py)
    x0 = jnp.floor(px)
    out = 0.0
    for cy, wy in ((y0, 1.0 - (py - y0)), (y0 + 1, py - y0)):
        for cx, wx in ((x0, 1.0 - (px - x0)), (x0 + 1, px - x0)):
            valid = (cy >= 0) & (cy < H) & (cx >= 0) & (cx < W)
            idx = (jnp.clip(cy, 0, H - 1) * W
                   + jnp.clip(cx, 0, W - 1)).astype(jnp.int32)
            g = jnp.take_along_axis(
                xg, idx.reshape(B, dg, 1, T * Ho * Wo), axis=-1)
            w = jnp.where(valid, wy * wx, 0.0).reshape(B, dg, 1, T * Ho * Wo)
            out = out + g * w.astype(x.dtype)
    sampled = out.reshape(B, dg, Cd, T, Ho, Wo)
    if mask is not None:  # v2 modulation, one scalar per (dgroup, tap)
        sampled = sampled * mask.reshape(B, dg, 1, T, Ho, Wo).astype(x.dtype)
    sampled = sampled.reshape(B, groups, Cin // groups, T, Ho, Wo)
    wg = weight.reshape(groups, Cout // groups, Cg, T)
    res = jnp.einsum("goct,bgcthw->bgohw", wg, sampled,
                     preferred_element_type=jnp.float32)
    res = res.reshape(B, Cout, Ho, Wo).astype(x.dtype)
    if bias is not None:
        res = res + bias.reshape(1, Cout, 1, 1)
    return res


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference psroi_pool † — the R-FCN
    head): input channel (c_out, i, j) average-pools bin (i, j) of each
    roi. Same masked-mean static-shape scheme as _roi_pool_impl."""
    if isinstance(output_size, int):
        oh = ow = output_size
    else:
        oh, ow = output_size
    if x.shape[1] % (oh * ow):
        raise ValueError(
            f"psroi_pool: channels {x.shape[1]} not divisible by "
            f"output_size^2 {oh * ow}")
    return _psroi_pool_impl(x, boxes, boxes_num, oh, ow,
                            float(spatial_scale))


@tensor_op
def _psroi_pool_impl(x, boxes, boxes_num, oh, ow, spatial_scale):
    N, C, H, W = x.shape
    Co = C // (oh * ow)
    R = boxes.shape[0]
    img_of = jnp.repeat(jnp.arange(boxes_num.shape[0]), boxes_num,
                        total_repeat_length=R)

    def one_roi(args):
        box, img = args
        # reference: rounded corners, end exclusive at x2+1, min span 0.1
        x1 = jnp.round(box[0]) * spatial_scale
        y1 = jnp.round(box[1]) * spatial_scale
        x2 = jnp.round(box[2] + 1.0) * spatial_scale
        y2 = jnp.round(box[3] + 1.0) * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        bin_h, bin_w = rh / oh, rw / ow
        iy = jnp.arange(oh, dtype=jnp.float32)
        ix = jnp.arange(ow, dtype=jnp.float32)
        y0 = jnp.clip(jnp.floor(y1 + iy * bin_h), 0, H).astype(jnp.int32)
        ye = jnp.clip(jnp.ceil(y1 + (iy + 1) * bin_h), 0, H).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(x1 + ix * bin_w), 0, W).astype(jnp.int32)
        xe = jnp.clip(jnp.ceil(x1 + (ix + 1) * bin_w), 0, W).astype(jnp.int32)
        ys, xs = jnp.arange(H), jnp.arange(W)
        my = (ys[:, None] >= y0[None]) & (ys[:, None] < ye[None])  # [H,oh]
        mx = (xs[:, None] >= x0[None]) & (xs[:, None] < xe[None])  # [W,ow]
        feat = x[img].reshape(Co, oh, ow, H, W).astype(jnp.float32)
        # bin (i,j) reads channel slice (c, i, j): mask both spatial dims
        m = (my.T[None, :, None, :, None] * mx.T[None, None, :, None, :])
        s = jnp.sum(feat * m, axis=(3, 4))
        cnt = jnp.maximum(jnp.sum(m, axis=(3, 4)), 1e-9)
        return (s / cnt).astype(x.dtype)                   # [Co, oh, ow]

    return jax.lax.map(one_roi, (boxes, img_of))


@tensor_op(differentiable=False)
def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference matrix_nms †, the SOLOv2 parallel soft-NMS):
    per class, every box's score decays by min_j f(iou_ij)/f(iou_max_j)
    over higher-scored boxes j — one IoU matrix instead of the greedy
    suppression loop, which is exactly the TPU-friendly formulation.

    Static-shape contract (cf. nms above): per image the top
    ``kk = min(keep_top_k, C * nms_top_k)`` rows (all candidates when
    keep_top_k = -1) come back as [label, score, x1, y1, x2, y2] with
    label = -1 on padding rows; out [N, kk, 6], index [N, kk] (flat
    class*M+box, -1 pad), rois_num [N]."""
    N, M, _ = bboxes.shape
    C = scores.shape[1]
    k = min(int(nms_top_k), M) if nms_top_k > 0 else M
    # keep_top_k=-1 is the reference's keep-everything; also clamp to the
    # candidate pool so small inputs under the default 200 don't fault
    kk = min(int(keep_top_k), C * k) if keep_top_k > 0 else C * k
    iou_norm = 0.0 if normalized else 1.0

    def one_image(args):
        box, sc = args                         # [M,4], [C,M]
        cls_valid = jnp.arange(C) != background_label

        def one_class(s):
            vals, order = jax.lax.top_k(s, k)
            sel = box[order]
            iou = _iou_matrix(sel, sel, norm=iou_norm)
            higher = jnp.tril(jnp.ones((k, k), bool), -1)  # j above i
            iou = jnp.where(higher, iou, 0.0)
            iou_max = jnp.max(iou, axis=1)     # compensation per j
            if use_gaussian:
                # reference kernel (SOLOv2): exp(-sigma*iou^2) /
                # exp(-sigma*comp^2) — sigma MULTIPLIES the exponent
                decay = jnp.exp((iou_max[None, :] ** 2 - iou ** 2)
                                * gaussian_sigma)
            else:
                decay = (1.0 - iou) / jnp.maximum(
                    1.0 - iou_max[None, :], 1e-10)
            decay = jnp.min(jnp.where(higher, decay, 1.0), axis=1)
            new_s = jnp.where(vals > score_threshold, vals * decay, -1.0)
            new_s = jnp.where(new_s > post_threshold, new_s, -1.0)
            return new_s, order

        cs, orders = jax.vmap(one_class)(sc)    # [C,k], [C,k]
        cs = jnp.where(cls_valid[:, None], cs, -1.0)
        flat_s = cs.reshape(-1)
        top_s, top_i = jax.lax.top_k(flat_s, kk)
        cls_of = (top_i // k).astype(jnp.float32)
        box_of = jnp.take(orders.reshape(-1), top_i)
        good = top_s > 0
        out = jnp.concatenate(
            [jnp.where(good, cls_of, -1.0)[:, None], top_s[:, None],
             jnp.where(good[:, None], box[box_of], 0.0)], axis=1)
        idx = jnp.where(good, cls_of.astype(jnp.int32) * M + box_of, -1)
        return out, idx, jnp.sum(good.astype(jnp.int32))

    out, idx, num = jax.lax.map(one_image, (bboxes, scores))
    res = [out]
    if return_index:
        res.append(idx)
    if return_rois_num:
        res.append(num)
    return tuple(res) if len(res) > 1 else out


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False,
                       name=None):
    """RPN proposal generation (reference generate_proposals_v2 †):
    per image, decode anchor deltas -> clip to the image -> drop
    sub-min_size boxes -> top pre_nms_top_n by score -> hard NMS ->
    top post_nms_top_n.

    Static-shape contract: rois/roi_probs come back [N, post_nms_top_n,
    4]/[N, post_nms_top_n] zero-padded, rois_num [N] giving the valid
    count per image (the reference's LoD boundary)."""
    if eta < 1.0:
        raise NotImplementedError(
            "generate_proposals: adaptive-NMS (eta < 1) is not "
            "implemented — the static-shape schedule runs hard NMS; "
            "pass eta=1.0")
    return _generate_proposals_impl(
        scores, bbox_deltas, img_size, anchors, variances,
        int(pre_nms_top_n), int(post_nms_top_n), float(nms_thresh),
        # reference FilterBoxes floors min_size at 1 pixel
        max(float(min_size), 1.0), 1.0 if pixel_offset else 0.0,
        return_rois_num)


@tensor_op(differentiable=False)
def _generate_proposals_impl(scores, bbox_deltas, img_size, anchors,
                             variances, pre_n, post_n, nms_thresh, min_size,
                             offset, return_rois_num):
    N, A, H, W = scores.shape
    M = A * H * W
    anc = anchors.reshape(M, 4)
    var = variances.reshape(M, 4)
    pre_n = min(pre_n, M)

    def one_image(args):
        sc, deltas, imsz = args
        s = sc.reshape(A, H, W).transpose(1, 2, 0).reshape(M)
        d = deltas.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(M, 4)
        top_s, top_i = jax.lax.top_k(s, pre_n)
        a = anc[top_i]
        v = var[top_i]
        dd = d[top_i]
        aw = a[:, 2] - a[:, 0] + offset
        ah = a[:, 3] - a[:, 1] + offset
        acx = a[:, 0] + aw * 0.5
        acy = a[:, 1] + ah * 0.5
        # shared center-size decode; w/h log-deltas capped at the RPN
        # kernel's kBBoxClipDefault = log(1000/16)
        dec = _decode_center_size(dd, v, aw, ah, acx, acy, offset,
                                  clip=math.log(1000.0 / 16.0))
        ih, iw = imsz[0], imsz[1]
        x1 = jnp.clip(dec[:, 0], 0, iw - offset)
        y1 = jnp.clip(dec[:, 1], 0, ih - offset)
        x2 = jnp.clip(dec[:, 2], 0, iw - offset)
        y2 = jnp.clip(dec[:, 3], 0, ih - offset)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
        ok = ((x2 - x1 + offset) >= min_size) & \
             ((y2 - y1 + offset) >= min_size)
        top_s = jnp.where(ok, top_s, -jnp.inf)
        keep = nms.raw_fn(boxes, nms_thresh, scores=top_s, top_k=post_n,
                          _norm=offset)
        good = (keep >= 0) & (jnp.take(top_s, jnp.clip(keep, 0, pre_n - 1))
                              > -jnp.inf)
        ki = jnp.clip(keep, 0, pre_n - 1)
        out_b = jnp.where(good[:, None], boxes[ki], 0.0)
        out_s = jnp.where(good, top_s[ki], 0.0)
        return out_b, out_s, jnp.sum(good.astype(jnp.int32))

    rois, probs, num = jax.lax.map(one_image, (scores, bbox_deltas,
                                               img_size))
    if return_rois_num:
        return rois, probs, num
    return rois, probs
