"""Vision transforms (reference: ``python/paddle/vision/transforms/``).

Numpy/host-side preprocessing (HWC uint8/float images), composed in the
DataLoader workers; device-side augmentation belongs in the jitted step.
"""
from __future__ import annotations

import numbers
import random

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1] numpy (Tensor conversion happens
    at collate)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            return (img - self.mean[:, None, None]) / self.std[:, None, None]
        return (img - self.mean) / self.std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = self.size
        # nearest/bilinear resize without PIL: use jax.image on host numpy
        import jax.image
        chw = img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[2] not in (1, 3)
        if chw:
            out_shape = (img.shape[0], h, w)
        elif img.ndim == 3:
            out_shape = (h, w, img.shape[2])
        else:
            out_shape = (h, w)
        out = jax.image.resize(img.astype(np.float32), out_shape, "linear")
        return np.asarray(out).astype(img.dtype)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = np.asarray(img)
        hwc = not (img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[2] not in (1, 3))
        H, W = (img.shape[0], img.shape[1]) if hwc else (img.shape[1], img.shape[2])
        th, tw = self.size
        i = max((H - th) // 2, 0)
        j = max((W - tw) // 2, 0)
        if hwc:
            return img[i:i + th, j:j + tw]
        return img[:, i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = np.asarray(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, int) else self.padding[0]
            pads = [(p, p), (p, p)] + [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pads)
        H, W = img.shape[0], img.shape[1]
        th, tw = self.size
        i = random.randint(0, max(H - th, 0))
        j = random.randint(0, max(W - tw, 0))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            img = np.asarray(img)
            return img[:, ::-1].copy() if img.ndim >= 2 else img
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            img = np.asarray(img)
            return img[::-1].copy()
        return np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        img = np.asarray(img)
        H, W = img.shape[0], img.shape[1]
        area = H * W
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= W and 0 < h <= H:
                i = random.randint(0, H - h)
                j = random.randint(0, W - w)
                return self._resize(img[i:i + h, j:j + w])
        return self._resize(CenterCrop(min(H, W))(img))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(np.asarray(img), self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        factor = 1.0 + random.uniform(-self.value, self.value)
        return np.clip(np.asarray(img, np.float32) * factor, 0,
                       255 if np.asarray(img).dtype == np.uint8 else None)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


# ---------------------------------------------------------- r4 parity batch
# (reference: remaining python/paddle/vision/transforms/transforms.py †;
# all HWC-numpy host-side like the rest of this module)
def _as_float(img):
    a = np.asarray(img)
    return a.astype(np.float32), a.dtype


def _clip_back(out, dtype):
    if dtype == np.uint8:
        return np.clip(out, 0, 255).astype(np.uint8)
    return out


def adjust_brightness(img, brightness_factor):
    a, dt = _as_float(img)
    return _clip_back(a * brightness_factor, dt)


def adjust_contrast(img, contrast_factor):
    a, dt = _as_float(img)
    mean = _rgb_to_gray(a).mean()
    return _clip_back((a - mean) * contrast_factor + mean, dt)


def adjust_saturation(img, saturation_factor):
    a, dt = _as_float(img)
    gray = _rgb_to_gray(a)[..., None]
    return _clip_back(gray + (a - gray) * saturation_factor, dt)


def _rgb_to_gray(a):
    if a.ndim == 2:
        return a
    return (0.299 * a[..., 0] + 0.587 * a[..., 1] + 0.114 * a[..., 2])


def adjust_hue(img, hue_factor):
    """Rotate hue by hue_factor (in [-0.5, 0.5] turns) via HSV roundtrip."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    a, dt = _as_float(img)
    scale = 255.0 if dt == np.uint8 else 1.0
    x = a / scale
    mx, mn = x.max(-1), x.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    h = np.where(mx == r, ((g - b) / diff) % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4))
    h = (h / 6.0 + hue_factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    v = mx
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    i = (i.astype(np.int32) % 6)[..., None]
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return _clip_back(out * scale, dt)


def to_grayscale(img, num_output_channels=1):
    a, dt = _as_float(img)
    gray = _rgb_to_gray(a)
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return _clip_back(out, dt)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate counter-clockwise by `angle` degrees about the center
    (inverse-map nearest/bilinear sampling, constant fill). ``expand``
    enlarges the canvas to hold the whole rotated image (PIL contract)."""
    a, dt = _as_float(img)
    h, w = a.shape[:2]
    theta = np.deg2rad(angle)
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None \
        else (center[1], center[0])
    c, s = np.cos(theta), np.sin(theta)
    if expand:
        # epsilon before ceil: cos(90deg) is ~6e-17, not 0, and without it
        # a right-angle rotation grows the canvas by a phantom pixel
        oh = int(np.ceil(abs(h * c) + abs(w * s) - 1e-9))
        ow = int(np.ceil(abs(w * c) + abs(h * s) - 1e-9))
        ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
    else:
        oh, ow, ocy, ocx = h, w, cy, cx
    yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
    # inverse rotation of output coords into the source image
    sx = (xx - ocx) * c - (yy - ocy) * s + cx
    sy = (xx - ocx) * s + (yy - ocy) * c + cy
    if interpolation == "bilinear":
        x0, y0 = np.floor(sx).astype(int), np.floor(sy).astype(int)
        wx, wy = sx - x0, sy - y0

        def fetch(xi, yi):
            inside = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
            v = a[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)]
            m = inside if a.ndim == 2 else inside[..., None]
            return np.where(m, v, fill)

        out = (fetch(x0, y0) * ((1 - wx) * (1 - wy))[..., None]
               + fetch(x0 + 1, y0) * (wx * (1 - wy))[..., None]
               + fetch(x0, y0 + 1) * ((1 - wx) * wy)[..., None]
               + fetch(x0 + 1, y0 + 1) * (wx * wy)[..., None]) \
            if a.ndim == 3 else \
            (fetch(x0, y0) * (1 - wx) * (1 - wy)
             + fetch(x0 + 1, y0) * wx * (1 - wy)
             + fetch(x0, y0 + 1) * (1 - wx) * wy
             + fetch(x0 + 1, y0 + 1) * wx * wy)
    else:
        xi, yi = np.round(sx).astype(int), np.round(sy).astype(int)
        inside = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        v = a[np.clip(yi, 0, h - 1), np.clip(xi, 0, w - 1)]
        m = inside if a.ndim == 2 else inside[..., None]
        out = np.where(m, v, fill)
    return _clip_back(out, dt)


class ContrastTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        return adjust_contrast(img, 1.0 + random.uniform(-self.value,
                                                         self.value))


class SaturationTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        return adjust_saturation(img, 1.0 + random.uniform(-self.value,
                                                           self.value))


class HueTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        return adjust_hue(img, random.uniform(-self.value, self.value))


class AdjustBrightness(BaseTransform):
    def __init__(self, brightness_factor):
        self.brightness_factor = brightness_factor

    def _apply_image(self, img):
        return adjust_brightness(img, self.brightness_factor)


class AdjustContrast(BaseTransform):
    def __init__(self, contrast_factor):
        self.contrast_factor = contrast_factor

    def _apply_image(self, img):
        return adjust_contrast(img, self.contrast_factor)


class AdjustHue(BaseTransform):
    def __init__(self, hue_factor):
        self.hue_factor = hue_factor

    def _apply_image(self, img):
        return adjust_hue(img, self.hue_factor)


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    @staticmethod
    def _range(value, center=1.0, lo_floor=0.0):
        """number v -> (max(floor, center-v), center+v); (lo, hi) passes
        through (the reference accepts both forms)."""
        if isinstance(value, (list, tuple)):
            return (float(value[0]), float(value[1]))
        return (max(lo_floor, center - value), center + value)

    def _apply_image(self, img):
        ops = []
        if self.brightness:
            f = random.uniform(*self._range(self.brightness))
            ops.append(lambda im, f=f: adjust_brightness(im, f))
        if self.contrast:
            f = random.uniform(*self._range(self.contrast))
            ops.append(lambda im, f=f: adjust_contrast(im, f))
        if self.saturation:
            f = random.uniform(*self._range(self.saturation))
            ops.append(lambda im, f=f: adjust_saturation(im, f))
        if self.hue:
            f = random.uniform(*self._range(self.hue, center=0.0,
                                            lo_floor=-0.5))
            ops.append(lambda im, f=f: adjust_hue(im, f))
        random.shuffle(ops)
        for op in ops:
            img = op(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, numbers.Number):
            padding = (padding,) * 4
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding  # (left, top, right, bottom)
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        a = np.asarray(img)
        l, t, r, b = self.padding
        pad_width = [(t, b), (l, r)] + [(0, 0)] * (a.ndim - 2)
        if self.padding_mode == "constant":
            return np.pad(a, pad_width, mode="constant",
                          constant_values=self.fill)
        mode = {"edge": "edge", "reflect": "reflect",
                "symmetric": "symmetric"}[self.padding_mode]
        return np.pad(a, pad_width, mode=mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.interpolation = interpolation
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, self.interpolation, expand=self.expand,
                      center=self.center, fill=self.fill)


class RandomErasing(BaseTransform):
    """Erase a random rectangle (reference RandomErasing: area scale,
    aspect ratio, constant or random fill)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        a = np.array(img, copy=True)
        h, w = a.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            eh, ew = int(round(np.sqrt(target * ar))), \
                int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                y = random.randint(0, h - eh)
                x = random.randint(0, w - ew)
                if self.value == "random":
                    a[y:y + eh, x:x + ew] = np.random.rand(
                        eh, ew, *a.shape[2:]) * (
                        255 if a.dtype == np.uint8 else 1.0)
                else:
                    a[y:y + eh, x:x + ew] = self.value
                return a
        return a


class GaussianBlur(BaseTransform):
    def __init__(self, kernel_size=3, sigma=(0.1, 2.0)):
        self.kernel_size = (kernel_size, kernel_size) \
            if isinstance(kernel_size, numbers.Number) else tuple(kernel_size)
        for k in self.kernel_size:  # even taps would shift by half a pixel
            if k <= 0 or k % 2 == 0:
                raise ValueError(
                    f"GaussianBlur kernel_size must be positive odd, got "
                    f"{self.kernel_size}")
        self.sigma = (sigma, sigma) if isinstance(sigma, numbers.Number) \
            else tuple(sigma)

    def _apply_image(self, img):
        a, dt = _as_float(img)
        sigma = random.uniform(*self.sigma)

        def kernel1d(k):
            r = np.arange(k) - (k - 1) / 2.0
            g = np.exp(-(r ** 2) / (2 * sigma ** 2))
            return g / g.sum()

        kh, kw = self.kernel_size
        gy, gx = kernel1d(kh), kernel1d(kw)
        # separable blur with edge padding (torch/paddle use reflect; edge
        # is visually equivalent at these kernel sizes)
        ph, pw = kh // 2, kw // 2
        pad_width = [(ph, ph), (0, 0)] + [(0, 0)] * (a.ndim - 2)
        out = np.pad(a, pad_width, mode="reflect")
        out = sum(gy[i] * out[i:i + a.shape[0]] for i in range(kh))
        pad_width = [(0, 0), (pw, pw)] + [(0, 0)] * (a.ndim - 2)
        out = np.pad(out, pad_width, mode="reflect")
        out = sum(gx[j] * out[:, j:j + a.shape[1]] for j in range(kw))
        return _clip_back(out, dt)
