"""Vision transforms (reference: ``python/paddle/vision/transforms/``).

Numpy/host-side preprocessing (HWC uint8/float images), composed in the
DataLoader workers; device-side augmentation belongs in the jitted step.
"""
from __future__ import annotations

import numbers
import random

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1] numpy (Tensor conversion happens
    at collate)."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = np.transpose(arr, (2, 0, 1))
        return arr


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, np.float32)
        if self.data_format == "CHW":
            return (img - self.mean[:, None, None]) / self.std[:, None, None]
        return (img - self.mean) / self.std


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = self.size
        # nearest/bilinear resize without PIL: use jax.image on host numpy
        import jax.image
        chw = img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[2] not in (1, 3)
        if chw:
            out_shape = (img.shape[0], h, w)
        elif img.ndim == 3:
            out_shape = (h, w, img.shape[2])
        else:
            out_shape = (h, w)
        out = jax.image.resize(img.astype(np.float32), out_shape, "linear")
        return np.asarray(out).astype(img.dtype)


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = np.asarray(img)
        hwc = not (img.ndim == 3 and img.shape[0] in (1, 3) and img.shape[2] not in (1, 3))
        H, W = (img.shape[0], img.shape[1]) if hwc else (img.shape[1], img.shape[2])
        th, tw = self.size
        i = max((H - th) // 2, 0)
        j = max((W - tw) // 2, 0)
        if hwc:
            return img[i:i + th, j:j + tw]
        return img[:, i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = np.asarray(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, int) else self.padding[0]
            pads = [(p, p), (p, p)] + [(0, 0)] * (img.ndim - 2)
            img = np.pad(img, pads)
        H, W = img.shape[0], img.shape[1]
        th, tw = self.size
        i = random.randint(0, max(H - th, 0))
        j = random.randint(0, max(W - tw, 0))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            img = np.asarray(img)
            return img[:, ::-1].copy() if img.ndim >= 2 else img
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            img = np.asarray(img)
            return img[::-1].copy()
        return np.asarray(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        img = np.asarray(img)
        H, W = img.shape[0], img.shape[1]
        area = H * W
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= W and 0 < h <= H:
                i = random.randint(0, H - h)
                j = random.randint(0, W - w)
                return self._resize(img[i:i + h, j:j + w])
        return self._resize(CenterCrop(min(H, W))(img))


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(np.asarray(img), self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value):
        self.value = value

    def _apply_image(self, img):
        factor = 1.0 + random.uniform(-self.value, self.value)
        return np.clip(np.asarray(img, np.float32) * factor, 0,
                       255 if np.asarray(img).dtype == np.uint8 else None)


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.asarray(img)[:, ::-1].copy()


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)
