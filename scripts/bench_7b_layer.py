"""On-chip microbench: ONE decoder layer at exact 7B dimensions
(hidden 4096, ffn 11008, 32 heads, bf16, remat) through the same scan body
bench.py uses — the 7B-shaped perf evidence VERDICT r3 item 3 asks for.

Run standalone (prints a JSON line) or import `measure()` from bench.py.
"""
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

import numpy as np


def measure(iters=8, B=4, S=2048, L=2):
    """Train-step (fwd+bwd) over L stacked 7B-dim layers; returns dict with
    tok/s and layer-MFU using the per-layer 6N formula (N = params/layer)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models import llama as llama_mod
    from paddle_tpu.profiler.metrics import peak_flops_per_chip

    H, I, nh, hd = 4096, 11008, 32, 128
    rng = np.random.RandomState(0)

    def mk(*shape):
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * 0.02,
                           jnp.bfloat16)

    stack = dict(
        wq=mk(L, H, nh * hd), wk=mk(L, H, nh * hd), wv=mk(L, H, nh * hd),
        wo=mk(L, nh * hd, H), w_gate=mk(L, H, I), w_up=mk(L, H, I),
        w_down=mk(L, I, H),
        input_ln=jnp.ones((L, H), jnp.bfloat16),
        post_ln=jnp.ones((L, H), jnp.bfloat16))
    x0 = jnp.asarray(rng.randn(B, S, H).astype(np.float32), jnp.bfloat16)
    sin, cos = llama_mod._rope_tables(S, hd, 10000.0)

    def body(h, lp):
        lwq, lwk, lwv, lwo, lg, lu, ld, lin, lpost = lp
        resid = h
        hn = llama_mod._rms(h, lin, 1e-5)
        q = jnp.einsum("bsh,hnd->bnsd", hn, lwq.reshape(H, nh, hd))
        k = jnp.einsum("bsh,hnd->bnsd", hn, lwk.reshape(H, nh, hd))
        v = jnp.einsum("bsh,hnd->bnsd", hn, lwv.reshape(H, nh, hd))
        q = llama_mod._apply_rope_bhsd(q, sin, cos)
        k = llama_mod._apply_rope_bhsd(k, sin, cos)
        attn = llama_mod._attention_bhsd(q, k, v, nh)
        h = resid + jnp.einsum("bnsd,ndh->bsh", attn, lwo.reshape(nh, hd, H))
        resid = h
        hn = llama_mod._rms(h, lpost, 1e-5)
        ff = jax.nn.silu(jnp.einsum("bsh,hi->bsi", hn, lg)) * \
            jnp.einsum("bsh,hi->bsi", hn, lu)
        return resid + jnp.einsum("bsi,ih->bsh", ff, ld), None

    order = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
             "input_ln", "post_ln")

    def loss_fn(stack, x0):
        xs = tuple(stack[k] for k in order)
        out, _ = jax.lax.scan(jax.checkpoint(body), x0, xs)
        return jnp.sum(out.astype(jnp.float32) ** 2) * 1e-6

    step = jax.jit(jax.grad(loss_fn))

    g = step(stack, x0)
    jax.block_until_ready(g)
    float(jax.tree.leaves(g)[0].sum().astype(jnp.float32))  # fence
    t0 = time.perf_counter()
    for _ in range(iters):
        g = step(stack, x0)
    float(jax.tree.leaves(g)[0].sum().astype(jnp.float32))
    dt = time.perf_counter() - t0

    n_params_layer = (3 * H * nh * hd + nh * hd * H + 3 * H * I + 2 * H)
    tokens = iters * B * S
    tok_s = tokens / dt
    flops = tok_s * 6.0 * n_params_layer * L
    mfu = flops / peak_flops_per_chip()
    return {"layer7b_tok_s": round(tok_s), "layer7b_mfu": round(float(mfu), 4),
            "L": L, "B": B, "S": S,
            "params_per_layer_m": round(n_params_layer / 1e6, 1)}


if __name__ == "__main__":
    print(json.dumps(measure()))
