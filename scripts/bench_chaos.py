"""Chaos benchmark: serving availability under a deterministic fault
plan (README "Fault tolerance & chaos testing").

Question answered: when the supervised gateway driver takes the full
injected fault matrix — a transient step fault, KV-pool exhaustion, a
fatal crash, real NaN corruption of the KV pool, and (separately, on a
virtual clock) a hung step past the watchdog deadline — over the mixed
short/long greedy+sampled workload, does it keep its availability
contract?

- **requests lost must be 0** for every non-poison fault: each
  submitted request terminates with a real finish_reason;
- **streams byte-identical** to the fault-free baseline run — recovery
  recomputes, preemption donates-and-requeues, and neither may change
  a single token;
- **recovery latency is measured**: wall seconds from each fault to
  the first completed step on the rebuilt engine
  (``ServingGateway.restart_latencies``), banked per restart;
- **preemptions counted** (the pool-exhaustion leg repairs by
  recompute, not crash);
- the **poison leg** pins the blast radius: a request the fault is
  pinned to is the ONLY one failed (``finish_reason="error"``) while
  every bystander completes byte-identically.

Methodology: the whole workload is submitted before the driver thread
starts, so the engine's step sequence — and therefore the plan-step
indices faults fire at — is deterministic; a replay reproduces the
exact streams and fault log (spot-checked and banked as
``deterministic``). Recovery latency is the one measured (wall-clock)
column, like the calibrated per-call costs of the other serving
benches.

Usage:
  python scripts/bench_chaos.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_chunked import BLOCK_SIZE, CHUNK, _model  # noqa: E402

NUM_SLOTS = 4
POISON_LEN = 37          # unique prompt length marks the poisoned request


def _workload():
    """Mixed traffic: greedy shorts, seeded-sampled rows, two long
    prompts that chunk — enough steps for every planned fault to land
    while work is in flight."""
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(23)
    reqs = []
    for i in range(10):
        kw = {}
        if i % 4 == 3:
            kw = dict(temperature=0.8, top_k=5, seed=200 + i)
        reqs.append(GenerationRequest(
            prompt=rng.randint(0, 2048, (12,)).astype(np.int32),
            max_new_tokens=12, **kw))
    for j in range(2):
        reqs.append(GenerationRequest(
            prompt=rng.randint(0, 2048, (160,)).astype(np.int32),
            max_new_tokens=6))
    return reqs


def _clone(r):
    from paddle_tpu.serving import GenerationRequest
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             seed=r.seed)


def _factory(model, s_max, spec=False):
    from paddle_tpu.serving import ContinuousBatchingEngine

    def factory():
        return ContinuousBatchingEngine(
            model, num_slots=NUM_SLOTS, max_seq_len=s_max, decode_chunk=1,
            prefix_cache=True, prefix_block_size=BLOCK_SIZE,
            prefill_chunk=CHUNK, spec_decode=spec,
            jit_cache=model.__dict__.setdefault("_serving_jit", {}))
    return factory


def _run_gateway(model, s_max, reqs, plan=None, clock=None,
                 watchdog_deadline_s=None, spec=False):
    """Submit the whole workload, then start the supervised driver and
    drain. Returns (streams, finish_reasons, gateway)."""
    from paddle_tpu.serving.server import ServingGateway
    factory = _factory(model, s_max, spec=spec)
    gw = ServingGateway(factory(), engine_factory=factory,
                        max_queue=len(reqs) + 4, fault_hook=plan,
                        clock=clock, watchdog_deadline_s=watchdog_deadline_s,
                        max_restarts=32, retry_backoff_s=0.0,
                        start=False)
    streams = [gw.submit(_clone(r)) for r in reqs]
    t0 = time.perf_counter()
    gw.start()
    outs = []
    for st in streams:
        try:
            ids, reason = st.result()
            outs.append((list(ids), reason))
        except RuntimeError:
            outs.append((st.tokens(), st.finish_reason))
    wall = time.perf_counter() - t0
    gw.shutdown(drain=True, timeout=60)
    return ([o[0] for o in outs], [o[1] for o in outs], gw, wall)


def _chaos_plan():
    from paddle_tpu.serving import FaultPlan
    return (FaultPlan()
            .at_step(3, "transient")
            .at_step(6, "pool")
            .at_step(10, "fatal")
            .at_step(15, "nan"))


def measure_chaos(quick=True):
    from paddle_tpu.serving import FaultPlan, VirtualClock
    s_max = 1024 if quick else 2048
    model = _model(quick)
    reqs = _workload()
    # warm every program shape once so recovery latency measures
    # recovery, not first-compile
    _run_gateway(model, s_max, reqs)
    # ---------------------------------------------------------- baseline
    base_streams, base_reasons, base_gw, base_wall = _run_gateway(
        model, s_max, reqs)
    # ------------------------------------------------------------- chaos
    plan = _chaos_plan()
    streams, reasons, gw, wall = _run_gateway(model, s_max, reqs, plan=plan)
    lost = sum(1 for r in reasons if r not in
               ("stop", "length", "cancelled", "timeout"))
    preemptions = gw._stat("preemptions")
    lat = list(gw.restart_latencies)
    chaos = {
        "requests_lost": lost,
        "streams_identical": streams == base_streams,
        "finish_reasons_ok": reasons == base_reasons,
        "engine_restarts": gw.restarts,
        "preemptions": preemptions,
        "faults_fired": [list(x) for x in plan.log],
        "recovery_latency_s": {
            # one sample per FAULT EVENT (transient retries included):
            # wall seconds from the fault to the next completed step
            "per_fault": [round(x, 4) for x in lat],
            "mean": round(float(np.mean(lat)), 4) if lat else None,
            "max": round(float(np.max(lat)), 4) if lat else None,
        },
        "wall_s": round(wall, 3),
        "baseline_wall_s": round(base_wall, 3),
    }
    # determinism spot-check: same plan, same workload -> same streams
    # and the same fault log
    plan2 = _chaos_plan()
    streams2, _, gw2, _ = _run_gateway(model, s_max, reqs, plan=plan2)
    deterministic = streams2 == streams and plan2.log == plan.log \
        and gw2.restarts == gw.restarts
    # -------------------------------------------------- hung-step leg
    # virtual clock: the stall and the watchdog classification cost no
    # real time; restarts prove the hung path end-to-end
    clk = VirtualClock()
    hplan = FaultPlan(clock=clk).at_step(5, "hung", stall_s=60.0)
    hstreams, hreasons, hgw, _ = _run_gateway(
        model, s_max, reqs, plan=hplan, clock=clk, watchdog_deadline_s=5.0)
    hung = {
        "requests_lost": sum(1 for r in hreasons if r not in
                             ("stop", "length")),
        "streams_identical": hstreams == base_streams,
        "engine_restarts": hgw.restarts,
    }
    # ---------------------------------------------- spec-enabled leg
    # the same fault matrix with speculative decode ON: a fatal fault
    # lands mid-speculation (unverified draft K/V in the pool) and
    # recovery must still be byte-identical — restore() recomputes from
    # ACCEPTED tokens only, and the rebuilt engine's fresh pool never
    # sees the dead engine's draft rows
    _run_gateway(model, s_max, reqs, spec=True)   # warm spec programs
    splan = _chaos_plan()
    sstreams, sreasons, sgw, _ = _run_gateway(
        model, s_max, reqs, plan=splan, spec=True)
    spec_res = {
        "requests_lost": sum(1 for r in sreasons if r not in
                             ("stop", "length", "cancelled", "timeout")),
        "streams_identical": sstreams == base_streams,
        "engine_restarts": sgw.restarts,
        # the final engine incarnation's count (stats reset on rebuild)
        "spec_accepted": sgw.engine.stats["spec_accepted"],
        "faults_fired": [list(x) for x in splan.log],
    }
    # ------------------------------------------------------ poison leg
    from paddle_tpu.serving import GenerationRequest
    rngp = np.random.RandomState(99)
    poison = GenerationRequest(
        prompt=rngp.randint(0, 2048, (POISON_LEN,)).astype(np.int32),
        max_new_tokens=24)
    pplan = FaultPlan().poison(lambda s: s.prompt_len == POISON_LEN)
    pstreams, preasons, pgw, _ = _run_gateway(
        model, s_max, reqs + [poison], plan=pplan)
    poison_res = {
        "poisoned_failed":
            sum(1 for r in preasons if r == "error"),
        "poisoned_is_last": preasons[-1] == "error",
        "bystanders_lost": sum(1 for r in preasons[:-1] if r not in
                               ("stop", "length")),
        "bystander_streams_identical": pstreams[:-1] == base_streams,
        "engine_restarts": pgw.restarts,
    }
    accepted = bool(
        chaos["requests_lost"] == 0 and chaos["streams_identical"]
        and deterministic
        and hung["requests_lost"] == 0 and hung["streams_identical"]
        and spec_res["requests_lost"] == 0
        and spec_res["streams_identical"]
        and poison_res["poisoned_failed"] == 1
        and poison_res["poisoned_is_last"]
        and poison_res["bystanders_lost"] == 0
        and poison_res["bystander_streams_identical"])
    return {
        "chaos": chaos, "hung": hung, "spec": spec_res,
        "poison": poison_res,
        "deterministic": bool(deterministic),
        "requests": len(reqs),
        "accepted": accepted,
        "num_slots": NUM_SLOTS, "prefill_chunk": CHUNK,
        "block_size": BLOCK_SIZE,
        "fault_plan": "transient@3, pool@6, fatal@10, nan@15 over the "
                      "mixed trace; hung@5 (virtual clock), the same "
                      "matrix with spec_decode=True (fatal lands mid-"
                      "speculation, recovery recomputes from accepted "
                      "tokens only), and a request-pinned poison as "
                      "separate legs",
        "clock_model": "streams/counters are deterministic (workload "
                       "submitted before the driver starts, plan-step "
                       "indexed faults); recovery_latency_s is the one "
                       "measured wall-clock column (fault -> first "
                       "completed step on the rebuilt engine).",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "chaos": measure_chaos(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0 if res["chaos"]["accepted"] else 1


if __name__ == "__main__":
    sys.exit(main())
