"""Chunked-prefill benchmark: bounded TTFT under mixed traffic.

Question answered: when one long cold prompt lands amid steady
short-request decoding traffic, what does splitting its prefill into
``prefill_chunk``-token chunks interleaved with decode steps
(``serving/engine.py``, README "Chunked prefill") buy the SHORT
requests' time-to-first-token — and are the token streams still
byte-identical?

Both legs run the SAME paged engine, model, kernel, scheduling
(``decode_chunk=1``) and the same arrival trace — the only difference
is ``prefill_chunk``:

- **unchunked** — a long cold prompt monopolizes an entire engine step:
  every short request that arrives while its prefill runs (or sits
  queued behind it) eats the whole prefill latency before its own first
  token;
- **chunked** — the long prefill advances at most ``prefill_chunk``
  tokens per step, so decode slots keep emitting and a newly arrived
  short prompt prefills within ~one chunk's latency.

Methodology: a calibrated discrete-event replay (same ethos as
bench_paged.py — deterministic composition, measured scalars). The
four device-call costs a step can be built from (plain fused decode
tick, short cold prefill, long cold prefill, one chunk call) are each
measured warm on the real engine, best-of-N; the replay then drives
the actual engine over a fixed virtual-time arrival schedule, charging
every step the sum of its measured parts (the engine is instrumented
to count which calls each step ran). A request's TTFT is the step-END
clock of its first token minus its arrival instant — a token is only
visible when the step that computed it returns, so a monopolizing
prefill step is charged to everyone who waited behind it. Given the
calibration table, both legs are fully DETERMINISTIC: a shared-CPU
box's scheduling jitter moves the four calibrated scalars slightly,
never the traffic pattern (time-based replays drift their operating
point with machine load — measured failure mode of the first cut of
this bench). The headline p95 (and the acceptance gate) is the EXACT
order statistic over the raw TTFT samples; the same samples also run
through a ``profiler.metrics.Histogram`` over the TTFT bucket ladder
and its ``quantile(0.95)`` is banked alongside
(``hist_p95_ttft_short_s``) as a scrape-parity column — the
``serving_ttft_seconds`` path reports through buckets, so the pair
shows the estimator's granularity without letting bucket-edge
interpolation move the gate.

Headline metric: ``p95_ttft_ratio`` = short-request p95 TTFT unchunked
/ chunked. The acceptance bar (ISSUE 5) is >= 2x; ``accepted`` in the
banked JSON records the gate.

Usage:
  python scripts/bench_chunked.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BLOCK_SIZE = 16
CHUNK = 32          # quick-leg chunk: 24 chunks for the long prompt
LONG_LEN = 768      # long cold prompt (vs 1024 max_position_embeddings)
SHORT_LEN = 12
SHORT_NEW = 12
ACCEPT_RATIO = 2.0  # ISSUE 5 acceptance bar: >= 2x lower p95 TTFT


def _model(quick=True):
    """Bench model sized so the mixed-traffic asymmetry is REAL on the
    quick (CPU) leg: a 768-token cold prefill costs ~19 warm decode
    steps (measured; the other serving legs' 384-wide model has a
    flatter ratio on CPU, which would understate the very stall this
    bench exists to show), while a single chunk step stays ~2 decode
    steps. The full-size leg reuses the 350M bench config."""
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    kw = (dict(vocab_size=2048, hidden_size=128, intermediate_size=352,
               num_hidden_layers=4, num_attention_heads=8,
               num_key_value_heads=4, max_position_embeddings=1024)
          if quick else
          dict(vocab_size=32000, hidden_size=1024, intermediate_size=2816,
               num_hidden_layers=24, num_attention_heads=16,
               num_key_value_heads=16, max_position_embeddings=2048,
               dtype="bfloat16"))
    paddle.seed(7)
    return LlamaForCausalLM(LlamaConfig(decode_attention="jnp", **kw))


def _trace(short_every_s, n_short=30, long_at=(6, 16, 26)):
    """Mixed traffic: ``n_short`` short decode requests arriving on a
    steady virtual-time clock (``short_every_s`` is calibrated to the
    measured decode-step time so the short traffic alone is
    SUSTAINABLE — otherwise every TTFT is queue-bound and the prefill
    policy is invisible), plus long cold prompts arriving with short
    traffic already decoding (after the ``long_at``-th shorts).
    Returns [(arrival_s, kind, GenerationRequest)] sorted by arrival."""
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(17)
    sched = []
    for i in range(n_short):
        t = i * short_every_s
        if i in long_at:
            sched.append((t + short_every_s / 2, "long", GenerationRequest(
                prompt=rng.randint(0, 2048, (LONG_LEN,)).astype(np.int32),
                max_new_tokens=8)))
        kw = {}
        if i % 5 == 4:  # a few seeded-sampled rows keep the pin strong
            kw = dict(temperature=0.8, top_k=5, seed=100 + i)
        sched.append((t, "short", GenerationRequest(
            prompt=rng.randint(0, 2048, (SHORT_LEN,)).astype(np.int32),
            max_new_tokens=SHORT_NEW, **kw)))
    sched.sort(key=lambda x: x[0])
    return sched


def _clone(r):
    from paddle_tpu.serving import GenerationRequest
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             seed=r.seed)


def _mk_engine(model, num_slots, s_max, prefill_chunk):
    from paddle_tpu.serving import ContinuousBatchingEngine
    # ragged_step=False: THIS leg is the two-program baseline the
    # banked CHUNKED_BENCH numbers (and bench_ragged's comparison) are
    # defined on; the unified default must not drift it
    return ContinuousBatchingEngine(
        model, num_slots=num_slots, max_seq_len=s_max, decode_chunk=1,
        prefix_block_size=BLOCK_SIZE, prefill_chunk=prefill_chunk,
        ragged_step=False, spec_decode=False,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}))


def _instrument(eng):
    """Count the device calls each step runs (one cold-prefill call per
    prompt bucket, one chunk call per chunk bucket, decode via stats),
    so the replay can charge the step the sum of its measured parts."""
    calls = {"short": 0, "long": 0, "chunk": 0}
    orig_cold = eng._admit_cold

    def cold(seqs, finished):
        for b in {eng._bucket(s.prompt_len) for s in seqs}:
            calls["long" if b > 4 * CHUNK else "short"] += 1
        return orig_cold(seqs, finished)

    orig_chunks = eng._run_prefill_chunks

    def chunks(plan, finished):
        calls["chunk"] += len({eng._bucket(n) for _, n in plan})
        return orig_chunks(plan, finished)

    eng._admit_cold = cold
    eng._run_prefill_chunks = chunks
    return calls


def _replay(model, sched, num_slots, s_max, prefill_chunk, costs):
    """Drive one engine through the arrival schedule on the calibrated
    virtual clock; returns (per-kind TTFT lists, streams keyed by
    submit order, engine)."""
    eng = _mk_engine(model, num_slots, s_max, prefill_chunk)
    calls = _instrument(eng)
    clock = 0.0
    ttft = {"short": [], "long": []}
    seen = set()
    newly_first = []       # first tokens surfaced by the current step
    arrivals = {}          # request_id -> (arrival_s, kind)

    def on_token(seq, tok):
        # a token becomes VISIBLE when the step that produced it
        # returns, so its timestamp is the step-END clock — charging
        # the whole monopolizing step (the thing this bench measures)
        # to every request that waited behind it
        if seq.request_id not in seen:
            seen.add(seq.request_id)
            newly_first.append(seq.request_id)

    eng.on_token = on_token
    pending = list(sched)
    seqs = []
    while pending or eng.has_work():
        while pending and pending[0][0] <= clock:
            t0, kind, req = pending.pop(0)
            seq = eng.submit(_clone(req))
            arrivals[seq.request_id] = (t0, kind)
            seqs.append(seq)
        if not eng.has_work():
            clock = pending[0][0]  # idle-skip to the next arrival
            continue
        before = dict(calls)
        dec0 = eng.stats["decode_calls"]
        eng.step()
        clock += sum((calls[k] - before[k]) * costs[k] for k in calls) \
            + (eng.stats["decode_calls"] - dec0) * costs["decode"]
        for rid in newly_first:
            t0, kind = arrivals[rid]
            ttft[kind].append(clock - t0)
        newly_first.clear()
    streams = [s.tokens for s in seqs]
    return ttft, streams, eng


def _p95(values):
    """Exact p95 order statistic — the headline and the acceptance
    gate (bucket-edge interpolation must never move a pass/fail)."""
    return float(np.percentile(values, 95))


def _hist_p95(values):
    """The same samples through the Histogram bucket-quantile
    estimator — the path a serving_ttft_seconds scrape uses; banked
    next to the exact column as a granularity/parity check."""
    from paddle_tpu.profiler.metrics import Histogram, TTFT_BUCKETS
    h = Histogram("ttft", buckets=TTFT_BUCKETS)
    for v in values:
        h.observe(v)
    return h.quantile(0.95)


def _calibrate_costs(model, num_slots, s_max):
    """Measure the four warm per-call costs the replay's clock is built
    from, each best-of-N so scheduler jitter only ever inflates a
    sample it then discards:

    - ``decode``: one fused decode tick over all slots;
    - ``short`` / ``long``: one cold-prefill call of the short / long
      prompt bucket (a max_new_tokens=1 request retires at install, so
      its admission step runs no decode — the step IS the call);
    - ``chunk``: one ``CHUNK``-token suffix call (a mid-prefill step
      runs nothing else).
    """
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(3)

    def _req(n, new=4):
        return GenerationRequest(
            prompt=rng.randint(0, 2048, (n,)).astype(np.int32),
            max_new_tokens=new)

    eng = _mk_engine(model, num_slots, s_max, None)
    for _ in range(num_slots):
        eng.submit(_req(SHORT_LEN, new=40))
    eng.step()
    eng.step()
    # best-of-9 floors throughout (the bench_dispatch/bench_trace
    # repeat discipline, ISSUE 13): best-of-5 flakes ~4% on a loaded
    # box, and these calibrated costs drive every replay clock both
    # bench_chunked and bench_ragged bank
    t_dec = min(_timed(eng.step) for _ in range(9))
    for s in list(eng._slots):
        if s is not None:
            eng.cancel(s)

    def admit_cost(plen):
        best = None
        for _ in range(9):
            eng.submit(_req(plen, new=1))  # retires at install: slot back
            t = _timed(eng.step)
            best = t if best is None else min(best, t)
        return best

    t_short = admit_cost(SHORT_LEN)
    t_long = admit_cost(LONG_LEN)

    eng = _mk_engine(model, num_slots, s_max, CHUNK)
    ts = []
    for _ in range(2):
        seq = eng.submit(_req(LONG_LEN))
        while seq.status != "running":
            ts.append(_timed(eng.step))  # chunk-only steps (no decode)
        eng.cancel(seq)
    ts.sort()
    return {"decode": t_dec, "short": t_short, "long": t_long,
            "chunk": ts[0]}


def _timed(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def measure_chunked_prefill(quick=True, num_slots=4):
    s_max = 1024 if quick else 2048
    model = _model(quick)
    # warm every program both legs touch (cold prefill buckets for the
    # short and long prompts, chunk suffix buckets, group-size pow2
    # pads, paged decode) before any timed calibration: a saturated
    # mini-schedule hits the full (group, bucket) grid cheaply
    zero = {"decode": 0.0, "short": 0.0, "long": 0.0, "chunk": 0.0}
    warm = _trace(0.0, n_short=8, long_at=(2,))
    _replay(model, warm, num_slots, s_max, None, zero)
    _replay(model, warm, num_slots, s_max, CHUNK, zero)
    costs = _calibrate_costs(model, num_slots, s_max)
    # arrival clock: ~30% slot utilization from the shorts alone
    # (SHORT_NEW decode steps held per short / (interval * num_slots)),
    # so the only congestion events in the trace are the long prefills
    # — and the chunk is kept small enough that a chunk-carrying step
    # stays within ~2x a plain decode step (a chunk that dwarfs the
    # decode batch would stretch every slot's residency and just move
    # the stall, the measured failure mode of chunk=128 on this config)
    sched = _trace(short_every_s=costs["decode"] * 10.0)
    legs = {}
    for name, chunk in (("unchunked", None), ("chunked", CHUNK)):
        ttft, streams, eng = _replay(model, sched, num_slots, s_max,
                                     chunk, costs)
        legs[name] = {"p95_ttft_short_s": _p95(ttft["short"]),
                      "hist_p95_ttft_short_s": _hist_p95(ttft["short"]),
                      "mean_ttft_short_s": float(np.mean(ttft["short"])),
                      "max_ttft_short_s": float(np.max(ttft["short"])),
                      "ttft_long_s": float(np.mean(ttft["long"])),
                      "prefill_chunks": eng.stats["prefill_chunks"],
                      "decode_compilations": eng.decode_compilations(),
                      "streams": streams}
    # determinism spot-check: a replay depends only on the schedule and
    # the calibration table, so a re-run must reproduce exactly
    ttft2, streams2, _ = _replay(model, sched, num_slots, s_max, CHUNK,
                                 costs)
    deterministic = streams2 == legs["chunked"]["streams"] and \
        _p95(ttft2["short"]) == legs["chunked"]["p95_ttft_short_s"]
    tokens_equal = legs["unchunked"].pop("streams") == \
        legs["chunked"].pop("streams")
    un, ch = legs["unchunked"], legs["chunked"]
    ratio = un["p95_ttft_short_s"] / max(ch["p95_ttft_short_s"], 1e-9)
    return {
        "unchunked": un, "chunked": ch,
        "tokens_equal": tokens_equal,
        "deterministic": bool(deterministic),
        "p95_ttft_ratio": ratio,
        "accept_ratio": ACCEPT_RATIO,
        "accepted": bool(tokens_equal and ratio >= ACCEPT_RATIO),
        "prefill_chunk": CHUNK, "block_size": BLOCK_SIZE,
        "num_slots": num_slots,
        "call_costs_ms": {k: round(v * 1e3, 2) for k, v in costs.items()},
        "trace": f"three {LONG_LEN}-token cold prompts amid 30 "
                 f"{SHORT_LEN}-token/{SHORT_NEW}-new short requests "
                 f"arriving every 10 decode-steps, calibrated "
                 f"virtual-clock replay",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "chunked_prefill": measure_chunked_prefill(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0 if res["chunked_prefill"]["accepted"] else 1


if __name__ == "__main__":
    sys.exit(main())
