"""Decode-throughput benchmark: the serving story's two numbers.

Leg A — kernel: tok/s through ``model.generate`` with the ragged Pallas
decode kernel vs the jnp masked-attention path (token equality checked).

Leg B — scheduling: continuous batching vs restart-per-batch on ONE
staggered request trace. Both legs run the SAME engine machinery with
single-step dispatch, so per-step cost is identical and the measured
ratio isolates scheduling: the baseline emulates the fixed
``generate()`` contract (admit a whole batch, pad everyone to the batch
max, run to completion, only then look at the queue again), while
continuous batching admits into slots the moment they free. Effective
tok/s counts only the tokens each request asked for — the padded tail a
restart batch decodes for its short members is pure waste and scores
zero.

Usage:
  python scripts/bench_decode.py --quick [--json PATH]   # CPU-sized
  python scripts/bench_decode.py                          # bench-350M
"""
import argparse
import json
import os
import sys
import time
from collections import deque
from dataclasses import replace

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _models(quick, attns=("jnp",)):
    import paddle_tpu as paddle
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    # sized so one decode step's compute dominates host dispatch noise
    # (a 256-wide 4-layer model left the ~1 s legs at the mercy of
    # scheduler jitter; the step-count ratio is the signal being measured)
    kw = (dict(vocab_size=2048, hidden_size=384, intermediate_size=1056,
               num_hidden_layers=6, num_attention_heads=8,
               num_key_value_heads=4, max_position_embeddings=256)
          if quick else
          dict(vocab_size=32000, hidden_size=1024, intermediate_size=2816,
               num_hidden_layers=24, num_attention_heads=16,
               num_key_value_heads=16, max_position_embeddings=2048,
               dtype="bfloat16"))
    out = {}
    for attn in attns:
        paddle.seed(7)  # same weights for every decode path
        out[attn] = LlamaForCausalLM(LlamaConfig(decode_attention=attn, **kw))
    return out


# ------------------------------------------------------------- leg A: kernel
def measure_decode_paths(quick=True, B=4, prompt=32, max_new=32):
    """tok/s via model.generate for pallas vs jnp decode attention."""
    import paddle_tpu as paddle
    models = _models(quick, attns=("pallas", "jnp"))
    rng = np.random.RandomState(0)
    V = models["jnp"].config.vocab_size
    ids = paddle.to_tensor(rng.randint(0, V, (B, prompt)).astype(np.int32))
    res, toks = {}, {}
    for attn, m in models.items():
        m.generate(ids, max_new_tokens=max_new, seed=0)  # compile + warm
        t0 = time.perf_counter()
        out = m.generate(ids, max_new_tokens=max_new, seed=0).numpy()
        dt = time.perf_counter() - t0
        toks[attn] = out
        res[attn] = {"tok_s": B * max_new / dt, "wall_s": dt}
    res["tokens_equal"] = bool((toks["pallas"] == toks["jnp"]).all())
    # model.generate rides the engine default (paged since PR 5):
    # record the operating point so rebanks against the dense-era
    # DECODE_BENCH.json baseline can't silently mix engine kinds
    res["paged_attn"] = True
    return res


# --------------------------------------------------------- leg B: scheduling
def _trace(quick=True):
    """Staggered arrivals, heterogeneous budgets: (arrival_step, request).

    Two waves of 4 onto 4 slots; budgets alternate 64/8 so a restart
    batch pads its short members to 64 while continuous batching refills
    their slots at step 8."""
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(1)
    n_long, n_short = (64, 8) if quick else (128, 16)
    reqs = []
    for i in range(8):
        arrival = 0 if i < 4 else 12
        reqs.append((arrival, GenerationRequest(
            prompt=rng.randint(0, 2048, (16,)).astype(np.int32),
            max_new_tokens=n_long if i % 2 == 0 else n_short)))
    return reqs


def _mk_engine(model, num_slots, s_max):
    from paddle_tpu.serving import ContinuousBatchingEngine
    # ragged_step=False pins the two-program step this leg's banked
    # baselines (DECODE_BENCH.json) were measured on — the unified
    # ragged default must not silently drift the comparison
    return ContinuousBatchingEngine(
        model, num_slots=num_slots, max_seq_len=s_max, decode_chunk=1,
        ragged_step=False, spec_decode=False,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}))


def _run_continuous(model, trace, num_slots, s_max):
    eng = _mk_engine(model, num_slots, s_max)
    pending = deque(trace)
    seqs = []
    t0 = time.perf_counter()
    while pending or eng.has_work():
        while pending and eng.stats["steps"] >= pending[0][0]:
            seqs.append(eng.submit(pending.popleft()[1]))
        if eng.has_work():
            eng.step()
        else:
            eng.stats["steps"] += 1  # idle tick: nothing arrived yet
    dt = time.perf_counter() - t0
    useful = sum(len(s.tokens) for s in seqs)
    return {"wall_s": dt, "useful_tokens": useful,
            "tok_s": useful / dt, "decode_steps": eng.stats["decode_steps"],
            "occupancy": (eng.stats["active_slot_steps"]
                          / max(eng.stats["slot_steps"], 1))}


def _run_restart(model, trace, num_slots, s_max):
    """generate()-style baseline: batch the arrived requests, pad all to
    the batch max budget, run to completion, repeat."""
    eng = _mk_engine(model, num_slots, s_max)
    pending = deque(trace)
    arrived, useful, steps = [], 0, 0
    t0 = time.perf_counter()
    while pending or arrived:
        while pending and steps >= pending[0][0]:
            arrived.append(pending.popleft()[1])
        if not arrived:
            steps += 1  # waiting for the next arrival, batch idle
            continue
        batch = arrived[:num_slots]
        arrived = arrived[num_slots:]
        mx = max(r.max_new_tokens for r in batch)
        before = eng.stats["steps"]
        for r in batch:
            eng.submit(replace(r, max_new_tokens=mx))  # batch-wide padding
        while eng.has_work():
            eng.step()
        steps += eng.stats["steps"] - before
        useful += sum(r.max_new_tokens for r in batch)  # wanted, not padded
    dt = time.perf_counter() - t0
    return {"wall_s": dt, "useful_tokens": useful, "tok_s": useful / dt,
            "decode_steps": eng.stats["decode_steps"]}


def measure_continuous_batching(quick=True, repeats=5):
    num_slots, s_max = 4, 128 if quick else 256
    model = _models(quick)["jnp"]  # same kernel both legs: pure scheduling
    # warm every jitted program on a throwaway trace, then time each leg
    # `repeats` times interleaved and keep each leg's best wall — a ~1 s
    # leg on a shared CPU box sees 2-3x scheduler noise otherwise
    _run_continuous(model, _trace(quick), num_slots, s_max)
    _run_restart(model, _trace(quick), num_slots, s_max)
    cb = rs = None
    for _ in range(repeats):
        c = _run_continuous(model, _trace(quick), num_slots, s_max)
        r = _run_restart(model, _trace(quick), num_slots, s_max)
        cb = c if cb is None or c["wall_s"] < cb["wall_s"] else cb
        rs = r if rs is None or r["wall_s"] < rs["wall_s"] else rs
    return {"continuous": cb, "restart": rs, "repeats": repeats,
            "speedup": cb["tok_s"] / rs["tok_s"],
            # both legs share one engine kind (the paged default since
            # PR 5), so the CB-vs-restart ratio stays like-vs-like;
            # recorded so absolute tok/s drift vs the dense-era bank
            # is attributable
            "paged_attn": True,
            "num_slots": num_slots, "s_max": s_max,
            "trace": "2 waves of 4 (arrive @0/@12), budgets 64/8 alternating"
                     if quick else
                     "2 waves of 4 (arrive @0/@12), budgets 128/16"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short trace")
    ap.add_argument("--json", default=None, help="also write result here")
    ap.add_argument("--leg", choices=["paths", "cb", "both"], default="both")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(),
           "quick": bool(args.quick)}
    if args.leg in ("paths", "both"):
        res["decode_paths"] = measure_decode_paths(quick=args.quick)
    if args.leg in ("cb", "both"):
        res["continuous_batching"] = measure_continuous_batching(
            quick=args.quick)
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
