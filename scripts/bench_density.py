"""Quantized serving density benchmark: int8/fp8 block-quantized KV at
a FIXED pool-byte budget (README "Quantized serving").

Question answered: holding the KV pool's HBM budget constant, how many
MORE concurrent slots does ``kv_dtype="int8"`` serve than the fp32
baseline — and what does quality actually pay (measured, not assumed)?
The fp8 leg asks the follow-up: with per-BLOCK scale planes instead of
int8's per-row planes, how many FEWER bytes does a cached token cost —
and the int8xint8 leg (``quantize_activations``) measures what the
dequant-free decode projections pay in stream divergence.

Legs (all deterministic — exact byte accounting + token comparison, no
wall-clock in the gates):

- **capacity**: the baseline engine's pool capacity in bytes (exact,
  from ``PagedKVCache.occupancy_bytes()`` — dtype-aware: int8 data
  PLUS its fp32 scale planes) becomes the budget; the int8 leg takes
  the largest slot count whose pool fits the SAME budget, then
  actually serves that many requests CONCURRENTLY (peak
  ``num_active`` is measured, not inferred). Acceptance:
  ``slot_capacity_ratio >= 1.8``.
- **quality**: greedy-stream divergence rate of int8 vs the fp32
  baseline on the mixed shared-prefix trace — fraction of streams
  that diverge anywhere, plus the mean matched-prefix fraction.
  Reported as measured; nothing assumes zero.
- **determinism**: the int8 engine replays byte-identically, and
  ``decode_compilations() == 1`` on the quantized geometry.
- **default unchanged**: the default (``kv_dtype`` unset) engine's
  streams are byte-identical before and after quantized engines ran
  against the same shared jit cache — the banked baselines cannot
  have drifted.
- **weights**: int8 weight-only decode rides along — projection-weight
  bytes fp vs int8 and stream determinism.
- **fp8**: bytes per cached token strictly below the int8 leg's (the
  per-block scale planes cost ``2*L*Hkv*4/block_size`` per token vs
  int8's ``2*L*Hkv*4``), greedy divergence measured against fp32 and
  gated at <= 0.02, replay-deterministic,
  ``decode_compilations() == 1`` on the kv8f geometry.
- **a8** (int8xint8 projections): divergence measured and bounded,
  deterministic, compiles once on the a8 geometry.

Usage:
  python scripts/bench_density.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_decode import _models  # noqa: E402  (same model as the other legs)

BLOCK_SIZE = 16


def _trace(n_req, quick=True):
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(17)
    sys_prompts = [rng.randint(0, 2048, (32,)).astype(np.int32)
                   for _ in range(2)]
    max_new = 8 if quick else 16
    reqs = []
    for i in range(n_req):
        tail = rng.randint(0, 2048, (12,)).astype(np.int32)
        reqs.append(GenerationRequest(
            prompt=np.concatenate([sys_prompts[i % 2], tail]),
            max_new_tokens=max_new))
    return reqs


def _clone(r):
    from paddle_tpu.serving import GenerationRequest
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens)


def _engine(model, num_slots, s_max, **kw):
    from paddle_tpu.serving import ContinuousBatchingEngine
    return ContinuousBatchingEngine(
        model, num_slots=num_slots, max_seq_len=s_max, decode_chunk=1,
        prefix_block_size=BLOCK_SIZE, prefill_chunk=None,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}), **kw)


def _pool_capacity_bytes(eng):
    ob = eng.cache.occupancy_bytes()
    return ob["capacity_kv"] + ob["capacity_scales"]


def _probe_capacity_bytes(model, num_slots, s_max, kv_dtype):
    """Pool capacity for a candidate slot count WITHOUT building the
    full serving stack: a bare PagedKVCache runs the same exact
    dtype-aware accounting the engine's pool would (occupancy_bytes),
    so the slot-count search never constructs and discards whole
    engines."""
    from paddle_tpu.serving.kv_cache import PagedKVCache
    c = model.config
    cache = PagedKVCache(
        c.num_hidden_layers, num_slots, s_max, c.num_key_value_heads,
        c.head_dim, dtype=model.embed_tokens.value.dtype,
        block_size=BLOCK_SIZE, kv_dtype=kv_dtype)
    ob = cache.occupancy_bytes()
    return ob["capacity_kv"] + ob["capacity_scales"]


def _run_concurrent(eng, reqs):
    """Generate with peak-concurrency tracking: the capacity leg's
    'slots measured' is the max simultaneously active slots, not a
    derived number. Requests are cloned per engine — the same trace
    object is reused across five engine runs and must stay pristine."""
    seqs = [eng.submit(_clone(r)) for r in reqs]
    peak = 0
    while eng.has_work():
        eng.step()
        peak = max(peak, eng.num_active)
    return [list(s.output_ids()) for s in seqs], peak


def _divergence(base, quant):
    diverged = sum(1 for a, b in zip(base, quant) if a != b)
    fracs = []
    for a, b in zip(base, quant):
        m = 0
        for t, u in zip(a, b):
            if t != u:
                break
            m += 1
        fracs.append(m / max(len(a), 1))
    return {"streams": len(base), "diverged_streams": diverged,
            "divergence_rate": diverged / max(len(base), 1),
            "matched_prefix_fraction": sum(fracs) / max(len(fracs), 1)}


def measure_density(quick=True, base_slots=4):
    s_max = 128 if quick else 256
    model = _models(quick)["jnp"]

    # ---------------------------------------------------- capacity A/B
    base = _engine(model, base_slots, s_max)
    budget = _pool_capacity_bytes(base)
    per_slot_base = budget // base_slots
    # largest int8 slot count whose pool fits the SAME byte budget —
    # probe the exact dtype-aware accounting, never a derived formula
    q_slots = base_slots
    while _probe_capacity_bytes(model, q_slots + 1, s_max,
                                "int8") <= budget:
        q_slots += 1
    quant = _engine(model, q_slots, s_max, kv_dtype="int8")
    q_bytes = _pool_capacity_bytes(quant)
    assert q_bytes <= budget

    # default-path pin, first reading: streams before quantized engines
    # share the jit cache
    reqs_small = _trace(2 * base_slots, quick)
    default_before, _ = _run_concurrent(_engine(model, base_slots, s_max),
                                        _trace(2 * base_slots, quick))

    # the int8 engine SERVES its claimed capacity: one request per slot,
    # peak concurrency measured
    outs_q, peak_q = _run_concurrent(quant, _trace(q_slots, quick))
    base_outs, peak_b = _run_concurrent(base, _trace(base_slots, quick))

    # ------------------------------------------------- quality (greedy)
    b_streams, _ = _run_concurrent(_engine(model, base_slots, s_max),
                                   reqs_small)
    q_streams, _ = _run_concurrent(
        _engine(model, base_slots, s_max, kv_dtype="int8"), reqs_small)
    q_streams2, _ = _run_concurrent(
        _engine(model, base_slots, s_max, kv_dtype="int8"), reqs_small)
    div = _divergence(b_streams, q_streams)

    # ------------------------------------------------------ weight leg
    w_eng = _engine(model, base_slots, s_max, quantize_weights=True)
    w_streams, _ = _run_concurrent(w_eng, reqs_small)
    w_streams2, _ = _run_concurrent(
        _engine(model, base_slots, s_max, quantize_weights=True),
        reqs_small)
    from paddle_tpu.serving.decode import _WEIGHT_QUANT_KEYS, \
        llama_decode_params
    raw, _tied = llama_decode_params(model)
    fp_w_bytes = sum(raw[k].size * raw[k].dtype.itemsize
                     for k in _WEIGHT_QUANT_KEYS + ("lm_head",))
    q_w_bytes = sum(q.size * q.dtype.itemsize + s.size * s.dtype.itemsize
                    for q, s in (w_eng._params[k]
                                 for k in _WEIGHT_QUANT_KEYS
                                 + ("lm_head",)))

    # ------------------------------------------------------- fp8 KV leg
    # per-BLOCK scale planes: bytes per cached token must land strictly
    # below the int8 leg's (same data bytes at head_dim >= 8, block_size
    # x fewer scale bytes), and greedy divergence vs fp32 is MEASURED
    # and gated tight — e4m3's exponent is the per-value scale, so the
    # walk should hold on this model/trace.
    f8_eng = _engine(model, base_slots, s_max, kv_dtype="fp8")
    f8_streams, _ = _run_concurrent(f8_eng, reqs_small)
    f8_streams2, _ = _run_concurrent(
        _engine(model, base_slots, s_max, kv_dtype="fp8"), reqs_small)
    f8_div = _divergence(b_streams, f8_streams)
    ob_f8 = f8_eng.cache.occupancy_bytes()

    # -------------------------------------------- int8xint8 (a8) leg
    a8_eng = _engine(model, base_slots, s_max, quantize_weights=True,
                     quantize_activations=True)
    a8_streams, _ = _run_concurrent(a8_eng, reqs_small)
    a8_streams2, _ = _run_concurrent(
        _engine(model, base_slots, s_max, quantize_weights=True,
                quantize_activations=True), reqs_small)
    a8_div = _divergence(b_streams, a8_streams)

    # default-path pin, second reading: quantized siblings in the same
    # jit cache must not have perturbed the default engine's streams
    default_after, _ = _run_concurrent(_engine(model, base_slots, s_max),
                                       _trace(2 * base_slots, quick))

    ob_b = base.cache.occupancy_bytes()
    ob_q = quant.cache.occupancy_bytes()
    ratio = q_slots / base_slots
    res = {
        "pool_budget_bytes": int(budget),
        "baseline_slots": base_slots,
        "baseline_pool_bytes": int(budget),
        "baseline_bytes_per_slot": int(per_slot_base),
        "baseline_bytes_per_token": ob_b["per_token"],
        "int8_slots": q_slots,
        "int8_pool_bytes": int(q_bytes),
        "int8_bytes_per_token": ob_q["per_token"],
        "int8_scale_plane_bytes": int(ob_q["capacity_scales"]),
        "slot_capacity_ratio": ratio,
        "peak_concurrent_slots_int8": peak_q,
        "peak_concurrent_slots_base": peak_b,
        "served_full_capacity": peak_q == q_slots,
        "greedy_divergence": div,
        "int8_deterministic": q_streams == q_streams2,
        "weights_deterministic": w_streams == w_streams2,
        "weight_bytes_fp": int(fp_w_bytes),
        "weight_bytes_int8": int(q_w_bytes),
        "weight_bytes_ratio": fp_w_bytes / q_w_bytes,
        "fp8_bytes_per_token": ob_f8["per_token"],
        "fp8_scale_plane_bytes": int(ob_f8["capacity_scales"]),
        "fp8_greedy_divergence": f8_div,
        "fp8_deterministic": f8_streams == f8_streams2,
        "a8_greedy_divergence": a8_div,
        "a8_deterministic": a8_streams == a8_streams2,
        "decode_compilations_int8": quant.decode_compilations(),
        "decode_compilations_w8": w_eng.decode_compilations(),
        "decode_compilations_fp8": f8_eng.decode_compilations(),
        "decode_compilations_a8": a8_eng.decode_compilations(),
        "default_streams_unchanged": default_before == default_after,
        "block_size": BLOCK_SIZE,
        "trace": f"{2 * base_slots} reqs round-robin over 2 shared "
                 f"32-token system prompts + unique 12-token tails",
        "accepted": bool(
            ratio >= 1.8 and peak_q == q_slots
            and q_streams == q_streams2
            and quant.decode_compilations() == 1
            # fp8 gates: strictly cheaper cached tokens than int8,
            # tight measured divergence, deterministic, compiles once
            and ob_f8["per_token"] < ob_q["per_token"]
            and f8_div["divergence_rate"] <= 0.02
            and f8_streams == f8_streams2
            and f8_eng.decode_compilations() == 1
            # a8 gates: divergence BOUNDED (reported exactly above),
            # deterministic, compiles once
            and a8_div["matched_prefix_fraction"] >= 0.75
            and a8_streams == a8_streams2
            and a8_eng.decode_compilations() == 1
            and default_before == default_after),
    }
    return res


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "density": measure_density(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0 if res["density"]["accepted"] else 1


if __name__ == "__main__":
    sys.exit(main())
