"""Dispatch-cost benchmark: device launches and boundary bytes per
decoded token, by engine configuration (README "Cost attribution &
/debug/profile").

Question answered: what does ONE decoded token cost at the host↔device
boundary — program dispatches, host→device argument bytes, device→host
result bytes — on each serving configuration, measured EXACTLY by the
cost observatory (``profiler/cost.py``)? The banked numbers are the
explicit baseline the ROADMAP's mega-kernel item must beat ("measured
dispatch count per decoded token drops ≥5×"): without this file that
claim has nothing to diff against.

Four configs drive the SAME model, jit cache and seeded request trace
(short prompts + one chunk-length cold prompt + seeded-sampled rows)
through ``engine.generate()``:

- **dense** — ``paged_attn=False``: the legacy per-slot cache,
  two-program interleave;
- **paged** — block tables, two-program interleave
  (``ragged_step=False``);
- **ragged** — the unified one-program step (the engine default; this
  row doubles as the ``decode_ticks=1`` rung of the multi-tick ladder);
- **spec**  — speculative decode over the unified path
  (``spec_decode=True``);
- **mtick4 / mtick8** — multi-tick decode (``decode_ticks`` in
  {4, 8}, README "Multi-tick decode"): one host sync per n fused
  on-device ticks. The banked
  ``dispatches_per_decoded_token_by_ticks`` ladder plus the
  ``multitick_dispatch_reduction`` ratio (ticks=1 / ticks=8; accepted
  at >= 3x) are the ISSUE 13 acceptance evidence — exact counters,
  byte-identical streams.

Exactness pin: every engine is ALSO instrumented at its program
accessors (the ``bench_ragged.py`` counters) and the observatory's
dispatch total must EQUAL the accessor count — the cost layer is an
account of what ran, not an estimate. Token streams are asserted
identical across all four configs (the standing byte-identity
contract), and fixed-cap chunk pacing (``headroom_mult=None``) keeps
every leg's plan — and therefore its counts — deterministic.

Disabled-overhead leg: the TRACE_BENCH three-way method
(``bench_trace.py``), with the COST layer in the tracer's role —
baseline (no observatory) vs installed-but-disabled (loose ≤ 1.15×
sanity bound — see ACCEPT_DISABLED_RATIO) vs enabled (reported
openly).

Usage:
  python scripts/bench_dispatch.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys
import time

import numpy as np

# the fused×tp2×overlap leg shards over a 2-device CPU mesh: force the
# virtual host devices BEFORE jax initializes (same flag conftest.py
# uses; inert for every single-chip leg — the banked counters reproduce)
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_decode import _models  # noqa: E402

from paddle_tpu.profiler.cost import PROGRAM_KINDS  # noqa: E402

NUM_SLOTS = 4
S_MAX = 256
BLOCK_SIZE = 8
CHUNK = 32
#: ISSUE 11: the cost layer is free when off. 1.01 was bankable on the
#: PR-10 box; the current container shows 2-10% best-of-9 spread
#: between the three IDENTICAL-work legs (measured on a pristine
#: pre-PR-20 checkout: disabled_overhead_ratio 1.078/1.095 on code
#: whose banked value was 1.005), so the wall gate is a loose 1.15
#: sanity bound — an accidentally unguarded record path costs well
#: beyond that. The structural zero-work claim is carried by
#: ``_co()``'s one-attribute guard plus the AST sweep
#: (test_cost_observatory.py), and the raw wall ratios
#: (disabled_vs_baseline included) are banked openly alongside
ACCEPT_DISABLED_RATIO = 1.15


def _requests(vocab, n_short=6, max_new=12):
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(11)
    reqs = []
    for i in range(n_short):
        kw = {}
        if i % 3 == 2:          # every third row seeded-sampled
            kw = dict(temperature=0.8, top_k=5, seed=500 + i)
        reqs.append(GenerationRequest(
            prompt=rng.randint(0, vocab, (8,)).astype(np.int32),
            max_new_tokens=max_new, **kw))
    # one chunk-length cold prompt so the chunked-prefill path runs
    reqs.append(GenerationRequest(
        prompt=rng.randint(0, vocab, (3 * CHUNK - 7,)).astype(np.int32),
        max_new_tokens=max_new))
    return reqs


CONFIGS = (
    ("dense", dict(paged_attn=False, ragged_step=False)),
    ("paged", dict(paged_attn=True, ragged_step=False)),
    ("ragged", dict(paged_attn=True, ragged_step=True)),
    ("spec", dict(paged_attn=True, ragged_step=True, spec_decode=True,
                  spec_k=3)),
    # multi-tick decode ladder (README "Multi-tick decode"): the
    # unified engine with decode_ticks in {4, 8} — the ragged config
    # IS the decode_ticks=1 rung, so the three rows bank
    # dispatches-per-token vs fused on-device ticks directly
    ("mtick4", dict(paged_attn=True, ragged_step=True, decode_ticks=4)),
    ("mtick8", dict(paged_attn=True, ragged_step=True, decode_ticks=8)),
)

#: ISSUE 13 acceptance bar: measured dispatches per decoded token on
#: this trace must drop >= 3x at decode_ticks=8 vs the banked ragged
#: (decode_ticks=1) baseline — exact CostObservatory counters, the
#: same counter /metrics serves as serving_dispatches_per_decoded_token
ACCEPT_MTICK_REDUCTION = 3.0


def _engine(model, cfg):
    from paddle_tpu.serving import ContinuousBatchingEngine
    return ContinuousBatchingEngine(
        model, num_slots=NUM_SLOTS, max_seq_len=S_MAX, decode_chunk=1,
        prefix_block_size=BLOCK_SIZE, prefill_chunk=CHUNK,
        headroom_mult=None,     # fixed-cap pacing: deterministic plans
        jit_cache=model.__dict__.setdefault("_serving_jit", {}), **cfg)


def _count_accessor_launches(eng):
    """The pre-observatory exact counters (bench_ragged.py's method):
    every device call site invokes its program accessor exactly once,
    so accessor calls == program launches — the independent count the
    observatory is pinned against."""
    calls = {"n": 0}

    def wrap(orig):
        def f(*a, **kw):
            calls["n"] += 1
            return orig(*a, **kw)
        return f

    for name in ("_prefill_fn", "_suffix_fn", "_decode_fn",
                 "_ragged_fn", "_mtick_fn", "_spec_fn"):
        setattr(eng, name, wrap(getattr(eng, name)))
    return calls


def _device_launches(co, eng):
    """EXACT device-side kernel-launch count for one leg: per-program
    dispatch counts (the observatory) × the program's jaxpr census,
    with the multi-tick while body billed at its PER-ITERATION census
    for the iterations that actually ran (``mtick_ticks`` −
    ``mtick_syncs`` body iterations; the census counts a while body
    once per call) and the fused program's cond'd tick 0 billed at the
    branch that ran (``mtick_pure_syncs`` pure-decode launches take
    the whole-tick kernel; mixed launches keep the packed forward,
    which is the census' cond maximum)."""
    total = 0
    for p in co.export()["programs"]:
        census = p.get("census")
        if census is None:
            continue
        body = sum(b["pallas_calls"] for b in census["loop_bodies"])
        if p["kind"] == "mtick":
            tick0_scanned = census["pallas_calls"] - body
            tick0_pure = body if eng.fused_tick else tick0_scanned
            pure = eng.stats["mtick_pure_syncs"]
            iters = eng.stats["mtick_ticks"] - eng.stats["mtick_syncs"]
            total += (pure * tick0_pure
                      + (p["calls"] - pure) * tick0_scanned
                      + iters * body)
        else:
            # scan trip counts are already multiplied in; no while
            # loops outside the mtick program
            total += p["calls"] * census["pallas_calls"]
    return total


def _run_config(model, name, cfg, reqs):
    from dataclasses import replace

    from paddle_tpu.profiler.cost import CostObservatory
    eng = _engine(model, cfg)
    co = CostObservatory()
    eng.cost = co
    accessor = _count_accessor_launches(eng)
    outs = eng.generate([replace(r) for r in reqs])
    tokens = eng.stats["tokens_generated"]
    t = co.totals
    return {
        "config": name,
        "dispatches": t["dispatches"],
        "accessor_launches": accessor["n"],
        "exact": t["dispatches"] == accessor["n"],
        "decoded_tokens": tokens,
        "dispatches_per_decoded_token": round(
            t["dispatches"] / max(tokens, 1), 4),
        "h2d_bytes_per_token": round(t["h2d_bytes"] / max(tokens, 1), 1),
        "d2h_bytes_per_token": round(t["d2h_bytes"] / max(tokens, 1), 1),
        "per_kind_dispatches": {
            kind: co.kind_calls(kind) for kind in PROGRAM_KINDS
            if co.kind_calls(kind)},
        "decode_compilations": eng.decode_compilations(),
        "decode_ticks": eng.decode_ticks,
        "decode_ticks_per_sync": round(
            eng.stats["mtick_ticks"] / max(eng.stats["mtick_syncs"], 1),
            3),
    }, [o.tolist() for o in outs], co, eng


def _overhead_leg(model, reqs, repeats=9):
    """TRACE_BENCH's interleaved three-way best-of method, with the
    cost layer in the tracer's role. ``repeats=9`` (vs bench_trace's
    5): the three legs run identical device work modulo one attribute
    check, so their best-of walls converge to the same floor — but on
    a loaded box 5 rounds leave ~4% scheduler noise between legs
    (observed: the ENABLED leg measuring faster than baseline), which
    would fail a 1% gate on pure jitter."""
    from dataclasses import replace

    from paddle_tpu.profiler.cost import CostObservatory

    def run(co):
        eng = _engine(model, dict(CONFIGS[1][1]))   # two-program paged
        eng.cost = co
        t0 = time.perf_counter()
        outs = eng.generate([replace(r) for r in reqs])
        return time.perf_counter() - t0, [o.tolist() for o in outs]

    run(None)                   # warm every program shape once
    co_off = CostObservatory().disable()
    co_on = CostObservatory()
    best = {"baseline": None, "disabled": None, "enabled": None}
    toks = {}
    for _ in range(repeats):
        for name, co in (("baseline", None), ("disabled", co_off),
                         ("enabled", co_on)):
            dt, out = run(co)
            toks[name] = out
            if best[name] is None or dt < best[name]:
                best[name] = dt
    tokens_equal = (toks["baseline"] == toks["disabled"]
                    == toks["enabled"])
    # the acceptance ratio measures the disabled leg against the FLOOR
    # (fastest of the three legs): all three run identical device work,
    # so the floor is the machine's true wall for the workload and the
    # disabled leg's distance from it bounds the guard's cost — an
    # enabled leg that lands below baseline (scheduler jitter) must
    # not manufacture a >1% "overhead" out of noise
    floor = min(best.values())
    return {
        "baseline_wall_s": round(best["baseline"], 4),
        "disabled_wall_s": round(best["disabled"], 4),
        "enabled_wall_s": round(best["enabled"], 4),
        "disabled_overhead_ratio": round(best["disabled"] / floor, 4),
        "enabled_overhead_ratio": round(best["enabled"] / floor, 4),
        "disabled_vs_baseline_ratio": round(
            best["disabled"] / best["baseline"], 4),
        "tokens_equal": tokens_equal,
        "repeats": repeats,
    }


#: the one-kernel decode legs (README "One-kernel decode"): the SAME
#: trace on the pallas-attention twin (identical weights, seed 7; the
#: paged decode kernel is pinned byte-identical to the jnp oracle), so
#: the jaxpr census counts real ``pallas_call`` launches. fusedmt16 is
#: the headline composition; fusedtp2ov exercises the overlapped
#: collective schedule (census collectives + exact wire ledger — the
#: fused×TP in-kernel collective is the remote-DMA follow-on, so its
#: launch counts stay scanned-shaped).
FUSED_CONFIGS = (
    ("raggedp", dict(paged_attn=True, ragged_step=True)),
    ("fusedmt1", dict(paged_attn=True, ragged_step=True,
                      fused_tick=True)),
    ("fusedmt4", dict(paged_attn=True, ragged_step=True, decode_ticks=4,
                      fused_tick=True)),
    ("fusedmt8", dict(paged_attn=True, ragged_step=True, decode_ticks=8,
                      fused_tick=True)),
    ("fusedmt16", dict(paged_attn=True, ragged_step=True,
                       decode_ticks=16, fused_tick=True)),
    ("fusedtp2ov", dict(paged_attn=True, ragged_step=True,
                        decode_ticks=8, fused_tick=True, tp=2,
                        collective_overlap=True)),
)

#: ISSUE 20 acceptance bar: the fused whole-tick program must cut the
#: census-exact device launches PER DECODE TICK >= 5x vs the scanned
#: tick (O(num_layers) pallas_calls -> exactly 1)
ACCEPT_FUSED_REDUCTION = 5.0


def _fused_legs(quick, reqs, jnp_streams):
    """Run the one-kernel decode ladder on the pallas twin and derive
    the census-exact device-launch metrics."""
    model = _models(quick, attns=("pallas",))["pallas"]
    rows, dev, streams, censuses = {}, {}, {}, {}
    overlap = None
    for name, cfg in FUSED_CONFIGS:
        row, s, co, eng = _run_config(model, name, cfg, reqs)
        launches = _device_launches(co, eng)
        row["device_launches"] = launches
        row["device_launches_per_decoded_token"] = round(
            launches / max(row["decoded_tokens"], 1), 4)
        row["mtick_pure_syncs"] = eng.stats["mtick_pure_syncs"]
        rows[name] = row
        dev[name] = row["device_launches_per_decoded_token"]
        streams[name] = s
        censuses[name] = {
            k: c for k, c in co.snapshot_full()["censuses"].items()}
        if name == "fusedtp2ov":
            led = co.snapshot_full()["collectives"]
            dt = eng.collective_dtype
            body = [c["loop_bodies"] for c in censuses[name].values()
                    if c and c["loop_bodies"]]
            overlap = {
                "collective_dtype": dt,
                "wire_ops": led[dt]["ops"], "wire_bytes": led[dt]["bytes"],
                "census_collectives_per_tick":
                    body[0][-1]["collectives"] if body else 0,
            }
    # per-tick launch counts, straight from the census: the scanned
    # tick-at-a-time program vs the fused while body
    scanned_tick = next(
        c["pallas_calls"] for c in censuses["raggedp"].values()
        if c and c["pallas_calls"])
    fused_body = next(
        c["loop_bodies"][-1]["pallas_calls"]
        for c in censuses["fusedmt16"].values()
        if c and c["loop_bodies"])
    return {
        "configs": rows,
        "streams_equal_to_scanned_legs": all(
            s == jnp_streams for s in streams.values()),
        "exact_vs_program_accessors": all(
            r["exact"] for r in rows.values()),
        "compile_once": all(r["decode_compilations"] == 1
                            for r in rows.values()),
        # THE headline: census-exact launches per decode tick
        "scanned_per_tick_device_launches": scanned_tick,
        "fused_per_tick_device_launches": fused_body,
        "fused_tick_launch_reduction": round(
            scanned_tick / max(fused_body, 1), 2),
        "accept_fused_reduction": ACCEPT_FUSED_REDUCTION,
        # end-to-end on the banked mixed trace (cold 89-token chunked
        # prompt interleaved with running decodes): the 3 mixed syncs
        # keep the packed forward for their chunk spans, so the
        # end-to-end number sits below the pure per-tick reduction
        "device_launches_per_decoded_token": dev,
        "end_to_end_device_launch_reduction": round(
            dev["raggedp"] / max(dev["fusedmt16"], 1e-9), 2),
        # the host-sync ladder must NOT move: the fused program changes
        # what runs inside a launch, never how often the host syncs
        "host_ladder_matches_scanned": None,   # filled by the caller
        "collective_overlap": overlap,
    }


def measure_dispatch_cost(quick=True, max_new=None):
    model = _models(quick)["jnp"]
    reqs = _requests(model.config.vocab_size,
                     max_new=max_new or (12 if quick else 32))
    configs = {}
    streams = {}
    for name, cfg in CONFIGS:
        configs[name], streams[name], _, _ = _run_config(model, name,
                                                         cfg, reqs)
    tokens_equal = all(s == streams["dense"] for s in streams.values())
    overhead = _overhead_leg(model, reqs)
    exact = all(c["exact"] for c in configs.values())
    compile_once = all(c["decode_compilations"] == 1
                       for c in configs.values())
    # multi-tick ladder: dispatches per decoded token by fused tick
    # count — decode_ticks=1 IS the ragged row. The reduction is a
    # ratio of two EXACT observatory counts (the same counter /metrics
    # serves live as serving_dispatches_per_decoded_token), not a model.
    ladder = {
        "1": configs["ragged"]["dispatches_per_decoded_token"],
        "4": configs["mtick4"]["dispatches_per_decoded_token"],
        "8": configs["mtick8"]["dispatches_per_decoded_token"],
    }
    mtick_reduction = round(
        ladder["1"] / max(ladder["8"], 1e-9), 2)
    # one-kernel decode legs (ISSUE 20): same trace, pallas twin, the
    # census-exact device-launch ladder. The host-sync ladder is pinned
    # AGAINST the scanned legs above: fused changes what one launch
    # contains, never how often the host syncs.
    fused = _fused_legs(quick, reqs, streams["dense"])
    fcfg = fused["configs"]
    fused["host_ladder_matches_scanned"] = (
        fcfg["raggedp"]["dispatches"] == configs["ragged"]["dispatches"]
        and fcfg["fusedmt1"]["dispatches"]
        == configs["ragged"]["dispatches"]
        and fcfg["fusedmt4"]["dispatches"]
        == configs["mtick4"]["dispatches"]
        and fcfg["fusedmt8"]["dispatches"]
        == configs["mtick8"]["dispatches"])
    fused_ok = bool(
        fused["streams_equal_to_scanned_legs"]
        and fused["exact_vs_program_accessors"]
        and fused["compile_once"]
        and fused["host_ladder_matches_scanned"]
        and fused["fused_tick_launch_reduction"]
        >= ACCEPT_FUSED_REDUCTION
        and fused["collective_overlap"] is not None
        and fused["collective_overlap"]["wire_bytes"] > 0)
    return {
        "configs": configs,
        "tokens_equal_across_configs": tokens_equal,
        "exact_vs_program_accessors": exact,
        "compile_once": compile_once,
        "disabled_overhead": overhead,
        # the headline the mega-kernel PR must beat, on the default
        # (ragged) configuration
        "baseline_dispatches_per_decoded_token":
            configs["ragged"]["dispatches_per_decoded_token"],
        "dispatches_per_decoded_token_by_ticks": ladder,
        "multitick_dispatch_reduction": mtick_reduction,
        "accept_multitick_reduction": ACCEPT_MTICK_REDUCTION,
        "fused": fused,
        "accepted": bool(
            tokens_equal and exact and compile_once
            and mtick_reduction >= ACCEPT_MTICK_REDUCTION
            and overhead["tokens_equal"]
            and overhead["disabled_overhead_ratio"]
            <= ACCEPT_DISABLED_RATIO
            and fused_ok),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "dispatch": measure_dispatch_cost(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0 if res["dispatch"]["accepted"] else 1


if __name__ == "__main__":
    sys.exit(main())
