"""Hand-sweep extra bench configs beyond bench.py's CONFIGS list.

Round-5 on-chip tuning: the driver sweep found bhsd+hd128+noremat+accum4
+chunk at 0.4548 MFU; this script probes the neighborhood (batch size,
accum depth, loss-chunk size, flash block sizes) one killable child per
config, appending every result to BENCH_EXTRA_r05.json as it lands.

Usage:
  python scripts/bench_extra.py            # parent: run the sweep
  python scripts/bench_extra.py --one IDX  # child: measure one config
"""
import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "BENCH_EXTRA_r05.json")

BASE = {"attention_layout": "bhsd", "num_attention_heads": 8,
        "num_key_value_heads": 8, "use_recompute": False,
        "loss_chunk": 512, "_accum": 4}

EXTRA = [
    # batch scaling: 2x tokens/step at the same microbatch size (accum 8)
    ("winner+B16+accum8", dict(BASE, _B=16, _accum=8)),
    # bigger microbatch (4 instead of 2): better MXU fill if memory allows
    ("winner+B16+accum4", dict(BASE, _B=16, _accum=4)),
    ("winner+accum2", dict(BASE, _accum=2)),
    # loss-chunk size: vocab-proj chunking trades live memory for launches
    ("winner+chunk1024", dict(BASE, loss_chunk=1024)),
    ("winner+chunk256", dict(BASE, loss_chunk=256)),
    # no chunking at all (loss_chunk=0 -> whole-row vocab projection)
    ("winner+nochunk", dict(BASE, loss_chunk=0)),
    # flash block sweep around the default
    ("winner+fbq512k256", dict(BASE, flash_block_q=512, flash_block_k=256)),
    ("winner+fbq256k512", dict(BASE, flash_block_q=256, flash_block_k=512)),
]


def main_one(idx):
    import bench
    name, overrides = EXTRA[idx]
    print(json.dumps(bench._measure_config(name, dict(overrides))))
    return 0


def main():
    import bench
    results = []
    if os.path.exists(OUT):
        try:
            results = json.load(open(OUT))["configs"]
        except Exception:
            pass
    # only successful measurements block a re-run: a transient tunnel hang
    # (mfu=0 err entry) is retried on the next invocation
    done = {r["name"] for r in results if r.get("mfu")}
    results = [r for r in results if r.get("mfu")]
    for i, (name, _) in enumerate(EXTRA):
        if name in done:
            continue
        t0 = time.time()
        rc, out, err = bench._run(
            [os.path.abspath(__file__), "--one", str(i)], 420)
        r = bench._parse_result(rc, out)  # tolerant of truncated stdout
        if r is not None and r.get("mfu"):
            results.append(r)
            print(f"{name}: mfu={r['mfu']:.4f} step={r['step_ms']:.1f}ms "
                  f"({time.time()-t0:.0f}s)")
        else:
            results.append({"name": name, "mfu": 0.0,
                            "err": (f"rc={rc}" + (" hang" if rc == 124 else "")
                                    + f"; stderr tail: {err.strip()[-200:]}")})
            print(f"{name}: FAILED rc={rc}")
        tmp = OUT + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"configs": results}, f, indent=1)
        os.replace(tmp, OUT)
    best = max((r for r in results if r.get("mfu")), key=lambda r: r["mfu"],
               default=None)
    if best:
        print(f"BEST extra: {best['name']} mfu={best['mfu']:.4f}")
    return 0


if __name__ == "__main__":
    if "--one" in sys.argv:
        sys.exit(main_one(int(sys.argv[sys.argv.index("--one") + 1])))
    sys.exit(main())
