"""Engine-fleet routing benchmark (README "Engine fleet" /
serving/server/README.md).

Question answered: when one gateway fans a shared-prefix trace out
over N shared-nothing engine replicas — each with its OWN prefix trie
— how much of the single-engine prefix-cache hit-rate does each
routing policy preserve, and at what throughput? Replication without
affinity scatters a prefix family across tries that each re-prefill
the shared preamble from scratch; the affinity router's whole job is
keeping the aggregate hit-rate at the single-engine level (the
AlpaServe-style observation, PAPERS.md: placement/routing policy —
not the kernel — dominates fleet goodput).

Workload: ``groups`` prompt families, each sharing a ``prefix_len``-
token preamble (the system-prompt pattern) with unique tails. One SEED
request per family runs first and retires — donating the family's
preamble blocks to whichever replica served it — then every FOLLOWER
submits, and the router decides whether it lands on the replica whose
trie holds its family's blocks.

Legs (same model, same requests, same per-replica geometry):

- **single** — one engine with the fleet's total slots: the hit-rate
  ceiling every policy is measured against;
- **round-robin** — load-blind rotation: followers scatter across
  tries and the aggregate hit-rate collapses toward (1/N of families
  warm per replica);
- **least-loaded** — live KV blocks + queue depth: better packing,
  still affinity-blind;
- **affinity** — longest cached-prefix match within a load band: the
  acceptance leg.

Every leg's token streams are asserted byte-identical to the single-
engine baseline (routing must place work, never change it), and
``decode_compilations() == 1`` is asserted per replica (the fleet's
per-geometry shared jit cache).

Acceptance: the affinity leg's aggregate hit-rate is within 10% of
the single-engine hit-rate (the ISSUE 12 gate), and strictly above
round-robin's.

Usage:
  python scripts/bench_fleet.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_decode import _models  # noqa: E402

REPLICAS = 2
SLOTS_PER_REPLICA = 2
S_MAX = 256
BLOCK_SIZE = 8
PREFIX_LEN = 4 * BLOCK_SIZE       # 4 shared blocks per family
CHUNK = 64
ACCEPT_HIT_RATE_FRACTION = 0.9    # within 10% of single-engine


def _workload(vocab, groups=4, followers=5, max_new=8):
    """(seeds, followers): one seed per prompt family + its followers,
    every member sharing the family's PREFIX_LEN-token preamble."""
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(31)
    seeds, tails = [], []
    for g in range(groups):
        preamble = rng.randint(0, vocab, (PREFIX_LEN,)).astype(np.int32)
        seeds.append(GenerationRequest(
            prompt=preamble.copy(), max_new_tokens=max_new))
        for _ in range(followers):
            tail = rng.randint(0, vocab, (6,)).astype(np.int32)
            tails.append(GenerationRequest(
                prompt=np.concatenate([preamble, tail]),
                max_new_tokens=max_new))
    return seeds, tails


def _clone(r):
    from paddle_tpu.serving import GenerationRequest
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens,
                             temperature=r.temperature, top_k=r.top_k,
                             seed=r.seed)


def _single_leg(model, seeds, tails):
    """The hit-rate ceiling: one engine with the fleet's total slots,
    seeds first (publish the family preambles), then every follower."""
    from paddle_tpu.serving import ContinuousBatchingEngine
    eng = ContinuousBatchingEngine(
        model, num_slots=REPLICAS * SLOTS_PER_REPLICA, max_seq_len=S_MAX,
        decode_chunk=1, prefix_cache=True, prefix_block_size=BLOCK_SIZE,
        prefill_chunk=CHUNK,
        jit_cache=model.__dict__.setdefault("_serving_jit_fleetbench", {}))
    t0 = time.perf_counter()
    seed_outs = eng.generate([_clone(r) for r in seeds])
    tail_outs = eng.generate([_clone(r) for r in tails])
    wall = time.perf_counter() - t0
    st = eng.prefix_cache.stats
    tokens = eng.stats["tokens_generated"]
    return {
        "hits": st["hits"], "misses": st["misses"],
        "hit_rate": round(st["hits"] / max(st["hits"] + st["misses"], 1),
                          4),
        "prefill_tokens_saved": eng.stats["prefill_tokens_saved"],
        "tokens": tokens, "wall_s": round(wall, 4),
        "tok_s": round(tokens / wall, 2),
    }, [o.tolist() for o in seed_outs] + [o.tolist() for o in tail_outs]


def _fleet_leg(model, policy, seeds, tails):
    """One fleet pass under ``policy``: seeds submit + drain first
    (each family's preamble lands in exactly one replica's trie), then
    every follower submits at once and the router places it."""
    from paddle_tpu.serving.fleet import EngineFleet
    fleet = EngineFleet(
        model, replicas=REPLICAS, router=policy,
        num_slots=SLOTS_PER_REPLICA, max_seq_len=S_MAX, decode_chunk=1,
        prefix_cache=True, prefix_block_size=BLOCK_SIZE,
        prefill_chunk=CHUNK, max_queue=len(tails) + len(seeds) + 4,
        start=True)
    try:
        t0 = time.perf_counter()
        seed_streams = [fleet.submit(_clone(r)) for r in seeds]
        seed_outs = [st.result()[0].tolist() for st in seed_streams]
        tail_streams = [fleet.submit(_clone(r)) for r in tails]
        tail_outs = [st.result()[0].tolist() for st in tail_streams]
        wall = time.perf_counter() - t0
        hits = sum(r.gateway._pc_stat("hits") for r in fleet.replicas)
        misses = sum(r.gateway._pc_stat("misses")
                     for r in fleet.replicas)
        tokens = sum(r.gateway._stat("tokens_generated")
                     for r in fleet.replicas)
        saved = sum(r.gateway._stat("prefill_tokens_saved")
                    for r in fleet.replicas)
        compilations = [r.gateway.engine.decode_compilations()
                        for r in fleet.replicas]
        per_replica = {str(r.index): sum(
            1 for _, i in fleet.decisions if i == r.index)
            for r in fleet.replicas}
        return {
            "policy": policy,
            "hits": hits, "misses": misses,
            "hit_rate": round(hits / max(hits + misses, 1), 4),
            "prefill_tokens_saved": saved,
            "tokens": tokens, "wall_s": round(wall, 4),
            "tok_s": round(tokens / wall, 2),
            "decisions_per_replica": per_replica,
            "decode_compilations_per_replica": compilations,
            "compile_once": all(c == 1 for c in compilations),
        }, seed_outs + tail_outs
    finally:
        fleet.shutdown(drain=True, timeout=60)


def measure_fleet(quick=True, groups=None, followers=None, max_new=None):
    model = _models(quick)["jnp"]
    seeds, tails = _workload(
        model.config.vocab_size,
        groups=groups or (4 if quick else 6),
        followers=followers or (5 if quick else 8),
        max_new=max_new or (8 if quick else 16))
    single, base_streams = _single_leg(model, seeds, tails)
    legs = {}
    streams_equal = True
    for policy in ("round-robin", "least-loaded", "affinity"):
        legs[policy], streams = _fleet_leg(model, policy, seeds, tails)
        streams_equal = streams_equal and streams == base_streams
    aff = legs["affinity"]["hit_rate"]
    rr = legs["round-robin"]["hit_rate"]
    accepted = bool(
        streams_equal
        and all(leg["compile_once"] for leg in legs.values())
        and aff >= ACCEPT_HIT_RATE_FRACTION * single["hit_rate"]
        and aff > rr)
    return {
        "replicas": REPLICAS, "slots_per_replica": SLOTS_PER_REPLICA,
        "block_size": BLOCK_SIZE, "shared_prefix_tokens": PREFIX_LEN,
        "requests": len(seeds) + len(tails),
        "single_engine": single,
        "fleet": legs,
        "streams_identical_across_policies": streams_equal,
        "affinity_hit_rate_fraction_of_single": round(
            aff / max(single["hit_rate"], 1e-9), 4),
        "accepted": accepted,
        "workload": "per-family seed publishes the shared preamble to "
                    "ONE replica's trie, then followers fan out and "
                    "the router decides whether they land on it; "
                    "hit-rate aggregates hits/(hits+misses) across "
                    "replica tries (carried across rebuilds).",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "fleet": measure_fleet(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0 if res["fleet"]["accepted"] else 1


if __name__ == "__main__":
    sys.exit(main())
