"""Block-table paged attention benchmark: the zero-copy prefix-hit story.

Question answered: on the shared-system-prompt trace (the dominant
serving pattern), what does replacing the dense per-slot KV cache with
block-table paged attention (``serving/kv_cache.PagedKVCache``) buy —
and are the token streams still byte-identical?

Both legs run the SAME model, kernel, scheduling (``decode_chunk=1``),
prefix-cache configuration, and request set — the only difference is
``paged_attn=True``:

- **dense** — prefix-cache hits COPY their matched blocks into the
  slot (one ``copy_block_in`` dispatch per block), every sequence
  holds a private copy of the shared prefix, and the per-slot dense
  arrays materialize ``num_slots * max_seq_len`` rows of HBM no matter
  what is live;
- **paged** — hits install by REFERENCE (zero dispatches), concurrent
  holders physically share prefix blocks (one block, N refs), and HBM
  holds only the blocks actually in use.

Headline metrics (deterministic — counted, not timed):

- ``copy_dispatches_eliminated``: the dense engine's install-copy
  dispatches, all of which the paged path removes
  (``prefill_copy_dispatches`` stays 0);
- ``peak_hbm_blocks``: peak KV HBM in block units. Dense = the always-
  materialized slot arrays (``num_slots * max_blocks``) + its pool's
  peak; paged = just its pool's peak — shared prefixes collapse to one
  physical block across all concurrent holders.

Wall-clock ratio rides along (noisy on a shared CPU box; the counters
are the gate).

Usage:
  python scripts/bench_paged.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_decode import _models  # noqa: E402  (same model as the other legs)

BLOCK_SIZE = 16


def _trace(quick=True, n_sys=2, n_req=12, sys_len=48, tail_len=16):
    """Shared-system-prompt requests: ``n_sys`` distinct system prompts,
    requests round-robin over them with unique tails — after each system
    prompt's first retirement, every later request on it is a hit."""
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(11)
    sys_prompts = [rng.randint(0, 2048, (sys_len,)).astype(np.int32)
                   for _ in range(n_sys)]
    max_new = 8 if quick else 16
    reqs = []
    for i in range(n_req):
        tail = rng.randint(0, 2048, (tail_len,)).astype(np.int32)
        reqs.append(GenerationRequest(
            prompt=np.concatenate([sys_prompts[i % n_sys], tail]),
            max_new_tokens=max_new))
    return reqs


def _clone(r):
    from paddle_tpu.serving import GenerationRequest
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens)


def _run(model, reqs, num_slots, s_max, paged):
    from paddle_tpu.serving import ContinuousBatchingEngine
    # ragged_step=False: PAGED_BENCH's banked dense-vs-paged comparison
    # was measured on the two-program step; the unified ragged default
    # must not silently drift the paged leg
    eng = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_seq_len=s_max, decode_chunk=1,
        prefix_cache=True, prefix_block_size=BLOCK_SIZE,
        paged_attn=paged, ragged_step=False, spec_decode=False,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}))
    t0 = time.perf_counter()
    outs = eng.generate([_clone(r) for r in reqs])
    wall = time.perf_counter() - t0
    pool = eng.prefix_cache.pool
    max_blocks = -(-s_max // BLOCK_SIZE)
    # dense materializes the per-slot arrays permanently on top of its
    # pool; paged KV lives ONLY in the pool
    slot_blocks = 0 if paged else num_slots * max_blocks
    res = {"wall_s": wall,
           "copy_dispatches": eng.stats["prefill_copy_dispatches"],
           "peak_hbm_blocks": slot_blocks + pool.peak_used,
           "pool_peak_used": pool.peak_used,
           "slot_array_blocks": slot_blocks,
           "hit_rate": eng.prefix_cache.hit_rate(),
           "prefill_tokens": eng.stats["prefill_tokens"],
           "decode_compilations": eng.decode_compilations()}
    if paged:
        res["donated_blocks"] = eng.prefix_cache.stats["donated_blocks"]
    return res, [o.tolist() for o in outs]


def measure_paged_attn(quick=True, num_slots=4, repeats=9):
    # repeats=9 (was 3): the wall-clock ratio column rides along with
    # the deterministic counters, and best-of-3 flaked ~4% on this
    # host under box load — same best-of-9 floor as the PR 11/12
    # bench hardening (bench_trace/bench_dispatch)
    s_max = 128 if quick else 256
    model = _models(quick)["jnp"]
    reqs = _trace(quick)
    # warm every program (prefill buckets, suffix buckets, copy
    # programs, both decode kinds) before timing
    _run(model, reqs, num_slots, s_max, False)
    _run(model, reqs, num_slots, s_max, True)
    dense = paged = None
    tokens_equal = True
    for _ in range(repeats):   # interleave; keep each leg's best wall
        d, d_toks = _run(model, reqs, num_slots, s_max, False)
        p, p_toks = _run(model, reqs, num_slots, s_max, True)
        tokens_equal = tokens_equal and d_toks == p_toks
        dense = d if dense is None or d["wall_s"] < dense["wall_s"] else dense
        paged = p if paged is None or p["wall_s"] < paged["wall_s"] else paged
    return {
        "dense": dense, "paged": paged, "repeats": repeats,
        "tokens_equal": tokens_equal,
        "copy_dispatches_eliminated": dense["copy_dispatches"],
        "paged_copy_dispatches": paged["copy_dispatches"],
        "peak_hbm_blocks_dense": dense["peak_hbm_blocks"],
        "peak_hbm_blocks_paged": paged["peak_hbm_blocks"],
        "hbm_reduction":
            dense["peak_hbm_blocks"] / max(paged["peak_hbm_blocks"], 1),
        "hit_rate": paged["hit_rate"],
        "wall_ratio": dense["wall_s"] / paged["wall_s"],
        "block_size": BLOCK_SIZE, "num_slots": num_slots,
        "trace": "12 reqs round-robin over 2 shared 48-token system "
                 "prompts + unique 16-token tails",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "paged_attn": measure_paged_attn(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
