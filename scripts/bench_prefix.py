"""Automatic prefix caching benchmark: the shared-system-prompt story.

Question answered: on a trace where most requests share a system prompt
(the dominant serving pattern), how much device prefill work does the
block-granular prefix cache (``serving/prefix_cache.py``) remove, at
what hit-rate, and are the token streams still byte-identical to the
cache-disabled engine?

Both legs run the SAME engine configuration, kernel, scheduling
(``decode_chunk=1``), and request set — the only difference is
``prefix_cache=True``:

- **cold** — every admission prefills its full prompt;
- **cached** — admissions matching published block chains install them
  with the compile-once copy programs and prefill only the uncovered
  suffix.

The headline is **prefill-work reduction**: device prefill tokens
processed cold / cached (deterministic — counted by the engine, not
timed), plus the lookup hit-rate and the wall-clock ratio of the full
runs (noisy on a shared CPU box; the token count is the gate).

Usage:
  python scripts/bench_prefix.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_decode import _models  # noqa: E402  (same model as the other legs)

BLOCK_SIZE = 16


def _trace(quick=True, n_sys=2, n_req=12, sys_len=48, tail_len=16):
    """Shared-system-prompt requests: ``n_sys`` distinct system prompts,
    requests round-robin over them with unique tails — after each system
    prompt's first retirement, every later request on it is a hit."""
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(11)
    sys_prompts = [rng.randint(0, 2048, (sys_len,)).astype(np.int32)
                   for _ in range(n_sys)]
    max_new = 8 if quick else 16
    reqs = []
    for i in range(n_req):
        tail = rng.randint(0, 2048, (tail_len,)).astype(np.int32)
        reqs.append(GenerationRequest(
            prompt=np.concatenate([sys_prompts[i % n_sys], tail]),
            max_new_tokens=max_new))
    return reqs


def _clone(r):
    from paddle_tpu.serving import GenerationRequest
    return GenerationRequest(prompt=r.prompt,
                             max_new_tokens=r.max_new_tokens)


def _run(model, reqs, num_slots, s_max, prefix_cache):
    from paddle_tpu.serving import ContinuousBatchingEngine
    eng = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_seq_len=s_max, decode_chunk=1,
        prefix_cache=prefix_cache, prefix_block_size=BLOCK_SIZE,
        # pin the DENSE engine: this leg measures the install-copy
        # prefill-work reduction the committed PREFIX_BENCH.json
        # baselined (PR 3), which the paged default would silently
        # replace with the zero-copy hit path (bench_paged.py owns that)
        paged_attn=False, spec_decode=False,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}))
    t0 = time.perf_counter()
    outs = eng.generate([_clone(r) for r in reqs])
    wall = time.perf_counter() - t0
    res = {"wall_s": wall,
           "prefill_tokens": eng.stats["prefill_tokens"],
           "prefill_tokens_saved": eng.stats["prefill_tokens_saved"],
           "decode_compilations": eng.decode_compilations()}
    if eng.prefix_cache is not None:
        pc = eng.prefix_cache.stats
        res.update(hit_rate=eng.prefix_cache.hit_rate(),
                   hits=pc["hits"], misses=pc["misses"],
                   evictions=pc["evictions"],
                   published_blocks=pc["published_blocks"])
    return res, [o.tolist() for o in outs]


def measure_prefix_cache(quick=True, num_slots=4, repeats=9):
    # repeats=9 (was 3): the wall-clock ratio column rides along with
    # the deterministic counters, and best-of-3 flaked ~4% on this
    # host under box load — same best-of-9 floor as the PR 11/12
    # bench hardening (bench_trace/bench_dispatch)
    s_max = 128 if quick else 256
    model = _models(quick)["jnp"]
    reqs = _trace(quick)
    # warm every program (cold prefill buckets, suffix buckets, copy
    # programs, decode) before timing
    _run(model, reqs, num_slots, s_max, False)
    _run(model, reqs, num_slots, s_max, True)
    cold = cached = None
    tokens_equal = True
    for _ in range(repeats):   # interleave; keep each leg's best wall
        c, c_toks = _run(model, reqs, num_slots, s_max, False)
        h, h_toks = _run(model, reqs, num_slots, s_max, True)
        tokens_equal = tokens_equal and c_toks == h_toks
        cold = c if cold is None or c["wall_s"] < cold["wall_s"] else cold
        cached = h if cached is None or h["wall_s"] < cached["wall_s"] \
            else cached
    return {
        "cold": cold, "cached": cached, "repeats": repeats,
        "tokens_equal": tokens_equal,
        "hit_rate": cached["hit_rate"],
        "prefill_work_reduction":
            cold["prefill_tokens"] / max(cached["prefill_tokens"], 1),
        "prefill_tokens_saved": cached["prefill_tokens_saved"],
        "wall_ratio": cold["wall_s"] / cached["wall_s"],
        "block_size": BLOCK_SIZE, "num_slots": num_slots,
        "trace": "12 reqs round-robin over 2 shared 48-token system "
                 "prompts + unique 16-token tails",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "prefix_cache": measure_prefix_cache(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
