"""Unified-ragged-step benchmark: program launches per serving step.

Question answered: when the serving engine collapses its per-step
chunk-call + fused-decode-call pair into ONE unified ragged program
(``ragged_step=True``, README "Unified ragged attention"), what happens
to device program launches, short-request p95 TTFT and mixed-trace
throughput on the same traffic ``bench_chunked.py`` measures — and are
the token streams still byte-identical?

Both legs run the SAME paged+chunked engine geometry, model, scheduling
(``decode_chunk=1``, fixed-cap chunk pacing ``headroom_mult=None`` so
the chunk plans are deterministic and IDENTICAL across legs) and the
same arrival trace — the only difference is ``ragged_step``:

- **two_program** — the PR-5 baseline: a step that advances a prefill
  chunk AND live decode rows dispatches two device programs (the chunk
  suffix call, then the fused decode call), and every mid-prefill slot
  rides the decode program as a dead full-length row whose output is
  discarded;
- **unified** — the same step content dispatches ONE ragged program
  (decode rows = spans of 1, the chunk = a span of n, packed into one
  token buffer), and the mid-prefill slot contributes its chunk span
  instead of a dead decode row.

Methodology: the calibrated discrete-event replay of
``bench_chunked.py``, verbatim — the per-call costs {decode tick,
short/long cold prefill, chunk call} are measured warm best-of-N on the
two-program engine, then both legs replay the same virtual-time arrival
schedule, instrumented with EXACT per-step program-launch counters.
Steps are charged identical content costs from that shared table (the
chunk plans and decode sets are identical by construction, asserted via
byte-identical streams); a unified step that collapsed a chunk+decode
pair is charged the pair MINUS one measured dispatch floor
(``t_dispatch``: a warm no-op jitted call, best-of-N — a LOWER bound on
what a real program launch costs in argument marshaling + runtime
dispatch, so the credit is conservative; the baseline's dead decode
rows stay charged to the unified leg too). The launch counters — the
actual structural claim — are not modeled: they count real dispatches
through the engines' program accessors.

Why the unified leg's own wall time is NOT the clock: on this CPU
correctness substrate the engine's jnp attention oracle computes the
packed token buffer DENSELY — padding rows and all — so a unified step
pays [token_budget x max_seq_len] einsums where the TPU Pallas kernel's
span-block gating + ragged DMA skip (kernels/pallas_ragged_attention)
computes only live spans. The raw CPU wall numbers are banked anyway
under ``cpu_oracle_wall_ms`` so the substrate artifact is on record,
not hidden.

Headline: ``launches_saved_per_mixed_step`` (acceptance gate: >= 1,
exact counters) with p95 short-request TTFT and mixed-trace tok/s
at-or-better than the two-program leg on the shared clock.

Usage:
  python scripts/bench_ragged.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_chunked import (BLOCK_SIZE, CHUNK, LONG_LEN, SHORT_LEN,  # noqa: E402
                           SHORT_NEW, _calibrate_costs, _clone, _model,
                           _p95, _timed, _trace)

ACCEPT_LAUNCHES_SAVED = 1   # ISSUE 6: >= 1 fewer launch per mixed step


def _mk_engine(model, num_slots, s_max, ragged):
    from paddle_tpu.serving import ContinuousBatchingEngine
    return ContinuousBatchingEngine(
        model, num_slots=num_slots, max_seq_len=s_max, decode_chunk=1,
        prefix_block_size=BLOCK_SIZE, prefill_chunk=CHUNK,
        ragged_step=ragged, headroom_mult=None, spec_decode=False,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}))


def _instrument_launches(eng):
    """Exact device-program dispatch counters, wrapped around the
    engine's program accessors (every device call goes through one):
    cold-prefill, chunk-suffix, fused-decode, unified-ragged."""
    calls = {"cold": 0, "suffix": 0, "decode": 0, "ragged": 0}
    orig_prefill, orig_suffix = eng._prefill_fn, eng._suffix_fn

    def prefill_fn(*a, **kw):
        calls["cold"] += 1
        return orig_prefill(*a, **kw)

    def suffix_fn(*a, **kw):
        calls["suffix"] += 1
        return orig_suffix(*a, **kw)

    eng._prefill_fn = prefill_fn
    eng._suffix_fn = suffix_fn
    if eng.ragged_step:
        orig_ragged = eng._ragged_fn
        eng._ragged_fn = lambda n: (
            calls.__setitem__("ragged", calls["ragged"] + 1)
            or orig_ragged(n))
    else:
        orig_decode = eng._decode_fn
        eng._decode_fn = lambda n: (
            calls.__setitem__("decode", calls["decode"] + 1)
            or orig_decode(n))
    return calls


def _dispatch_floor():
    """Warm dispatch cost of one device program launch, measured as a
    no-op jitted call (best-of-N): argument intake + runtime dispatch +
    result plumbing with zero compute. A strict LOWER bound on a real
    program launch, so crediting only this much to the collapsed pair
    is conservative."""
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda x: x)
    x = jnp.zeros((8,), jnp.float32)
    f(x).block_until_ready()
    best = None
    for _ in range(50):
        t0 = time.perf_counter()
        f(x).block_until_ready()
        dt = time.perf_counter() - t0
        best = dt if best is None else min(best, dt)
    return best


def _replay(model, sched, num_slots, s_max, ragged, costs, t_dispatch):
    """Drive one engine through the arrival schedule on the calibrated
    virtual clock (bench_chunked semantics: a token is visible at the
    END of the step that computed it). Content costs come from the
    shared two-program table; a unified step that collapsed a
    chunk+decode pair is credited one dispatch floor. Returns
    (ttft-by-kind, streams, per-leg stats, engine)."""
    eng = _mk_engine(model, num_slots, s_max, ragged)
    calls = _instrument_launches(eng)
    clock = 0.0
    ttft = {"short": [], "long": []}
    seen = set()
    newly_first = []
    arrivals = {}

    def on_token(seq, tok):
        if seq.request_id not in seen:
            seen.add(seq.request_id)
            newly_first.append(seq.request_id)

    eng.on_token = on_token
    pending = list(sched)
    seqs = []
    launches_total = 0
    mixed_steps = 0
    mixed_launches = 0
    dead_decode_rows = 0
    gen_tokens = 0
    while pending or eng.has_work():
        while pending and pending[0][0] <= clock:
            t0, kind, req = pending.pop(0)
            seq = eng.submit(_clone(req))
            arrivals[seq.request_id] = (t0, kind)
            seqs.append(seq)
        if not eng.has_work():
            clock = pending[0][0]
            continue
        before = dict(calls)
        st0 = {k: eng.stats[k] for k in
               ("prefill_chunks", "decode_calls", "tokens_generated")}
        prefilling_before = sum(
            1 for s in eng._slots
            if s is not None and s.status == "prefilling")
        eng.step()
        chunked = eng.stats["prefill_chunks"] > st0["prefill_chunks"]
        decoded = eng.stats["decode_calls"] > st0["decode_calls"]
        gen_tokens = eng.stats["tokens_generated"]
        n_cold = calls["cold"] - before["cold"]
        step_launches = sum(calls[k] - before[k] for k in calls)
        launches_total += step_launches
        # content charge: identical across legs by construction
        cost = n_cold * costs["short"] \
            + (costs["chunk"] if chunked else 0.0) \
            + (costs["decode"] if decoded else 0.0)
        if chunked and decoded:
            mixed_steps += 1
            mixed_launches += step_launches - n_cold
            if ragged:
                cost -= t_dispatch  # the collapsed pair's launch credit
        if decoded and not ragged:
            # baseline dead rows: mid-prefill slots ride the decode
            # program as full-length rows whose output is discarded
            dead_decode_rows += prefilling_before
        clock += cost
        for rid in newly_first:
            t0, kind = arrivals[rid]
            ttft[kind].append(clock - t0)
        newly_first.clear()
    streams = [s.tokens for s in seqs]
    stats = {"launches_total": launches_total,
             "mixed_steps": mixed_steps,
             "launches_per_mixed_step":
                 (mixed_launches / mixed_steps) if mixed_steps else 0.0,
             "dead_decode_rows": dead_decode_rows,
             "tok_s": gen_tokens / clock if clock > 0 else 0.0,
             "wall_virtual_s": clock,
             "calls": dict(calls)}
    return ttft, streams, stats, eng


def _raw_step_wall(model, num_slots, s_max):
    """The unmodeled CPU wall numbers, banked for the record: warm
    decode-only and chunk-carrying (mixed) step costs on each engine.
    On this substrate the unified step's jnp oracle computes the packed
    buffer densely (padding rows included) — the TPU kernel's span
    gating removes exactly that, so these columns are a CPU-substrate
    artifact, not the launch-structure claim."""
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(11)

    def _req(n, new=4):
        return GenerationRequest(
            prompt=rng.randint(0, 2048, (n,)).astype(np.int32),
            max_new_tokens=new)

    out = {}
    for name, ragged in (("two_program", False), ("unified", True)):
        eng = _mk_engine(model, num_slots, s_max, ragged)
        for _ in range(num_slots):
            eng.submit(_req(SHORT_LEN, new=60))
        eng.step()
        eng.step()
        # best-of-9 floor (the bench_dispatch/bench_trace repeat
        # discipline, ISSUE 13): best-of-5-ish rounds flake ~4% on
        # this host, and these walls are banked as absolute ms
        t_dec = min(_timed(eng.step) for _ in range(9))
        for s in list(eng._slots):
            if s is not None:
                eng.cancel(s)
        for _ in range(num_slots - 1):
            eng.submit(_req(SHORT_LEN, new=60))
        eng.step()
        eng.step()
        longy = eng.submit(_req(LONG_LEN, new=4))
        ts = []
        while longy.prefilled < longy.prompt_len:
            ts.append(_timed(eng.step))
        while eng.has_work():
            eng.step()
        out[name] = {"decode_only_step_ms": round(t_dec * 1e3, 2),
                     "mixed_step_ms": round(min(ts) * 1e3, 2)}
    return out


def measure_ragged_step(quick=True, num_slots=4):
    s_max = 1024 if quick else 2048
    model = _model(quick)
    # warm every program both legs touch before any timed calibration
    zero = {"decode": 0.0, "short": 0.0, "long": 0.0, "chunk": 0.0}
    warm = _trace(0.0, n_short=8, long_at=(2,))
    _replay(model, warm, num_slots, s_max, False, zero, 0.0)
    _replay(model, warm, num_slots, s_max, True, zero, 0.0)
    costs = _calibrate_costs(model, num_slots, s_max)
    t_dispatch = _dispatch_floor()
    sched = _trace(short_every_s=costs["decode"] * 10.0)
    legs = {}
    streams = {}
    for name, ragged in (("two_program", False), ("unified", True)):
        ttft, strm, stats, eng = _replay(model, sched, num_slots, s_max,
                                         ragged, costs, t_dispatch)
        streams[name] = strm
        legs[name] = {"p95_ttft_short_s": _p95(ttft["short"]),
                      "mean_ttft_short_s": float(np.mean(ttft["short"])),
                      "ttft_long_s": float(np.mean(ttft["long"])),
                      "prefill_chunks": eng.stats["prefill_chunks"],
                      "unified_steps": eng.stats["unified_steps"],
                      "decode_compilations": eng.decode_compilations(),
                      **stats}
    # determinism spot-check: schedule + calibration table in, exact
    # same streams and clock out
    ttft2, strm2, stats2, _ = _replay(model, sched, num_slots, s_max,
                                      True, costs, t_dispatch)
    deterministic = strm2 == streams["unified"] and \
        _p95(ttft2["short"]) == legs["unified"]["p95_ttft_short_s"]
    tokens_equal = streams["two_program"] == streams["unified"]
    two, uni = legs["two_program"], legs["unified"]
    launches_saved = two["launches_per_mixed_step"] \
        - uni["launches_per_mixed_step"]
    ttft_ok = uni["p95_ttft_short_s"] <= two["p95_ttft_short_s"]
    tps_ok = uni["tok_s"] >= two["tok_s"]
    return {
        "two_program": two, "unified": uni,
        "tokens_equal": tokens_equal,
        "deterministic": bool(deterministic),
        "launches_saved_per_mixed_step": launches_saved,
        "launches_eliminated_total":
            two["launches_total"] - uni["launches_total"],
        "dead_decode_rows_eliminated": two["dead_decode_rows"],
        "p95_ttft_at_or_better": bool(ttft_ok),
        "tok_s_at_or_better": bool(tps_ok),
        "accept_launches_saved": ACCEPT_LAUNCHES_SAVED,
        "accepted": bool(tokens_equal and ttft_ok and tps_ok
                         and launches_saved >= ACCEPT_LAUNCHES_SAVED),
        "prefill_chunk": CHUNK, "block_size": BLOCK_SIZE,
        "num_slots": num_slots,
        "call_costs_ms": {k: round(v * 1e3, 2) for k, v in costs.items()},
        "t_dispatch_ms": round(t_dispatch * 1e3, 4),
        "cpu_oracle_wall_ms": _raw_step_wall(model, num_slots, s_max),
        "clock_model": "bench_chunked calibrated replay; identical "
                       "per-step content costs both legs (plans "
                       "byte-identical); a unified step that collapsed "
                       "a chunk+decode pair is credited ONE measured "
                       "dispatch floor; launch counts are real "
                       "dispatches, not modeled. cpu_oracle_wall_ms "
                       "records the unmodeled dense-oracle wall costs "
                       "(CPU substrate artifact; the TPU kernel's "
                       "span gating computes live spans only).",
        "trace": f"three {LONG_LEN}-token cold prompts amid 30 "
                 f"{SHORT_LEN}-token/{SHORT_NEW}-new short requests "
                 f"arriving every 10 decode-steps, calibrated "
                 f"virtual-clock replay",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "ragged_step": measure_ragged_step(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0 if res["ragged_step"]["accepted"] else 1


if __name__ == "__main__":
    sys.exit(main())
