"""HTTP serving-gateway overhead benchmark.

Question answered: what does the async gateway (driver thread + token
queues + stdlib HTTP + SSE-capable front door) cost over driving the
``ContinuousBatchingEngine`` directly in-process?

Both legs run the SAME engine configuration, kernel, and request set
(seeded greedy, so token equality is asserted as a side effect):

- **direct** — ``engine.generate(requests)`` on this thread;
- **http** — the same requests as concurrent blocking
  ``POST /v1/completions`` calls from client threads against a
  localhost :func:`paddle_tpu.serving.server.serve` instance.

The measured ratio isolates the gateway+HTTP layer: same decode
programs (shared jit cache), same scheduling (decode_chunk=1), same
host. Reported per-token overhead is the wall-clock delta spread over
the generated tokens.

Usage:
  python scripts/bench_serve.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_decode import _models  # noqa: E402  (same model both benches)


def _requests(n, max_new, vocab, plen=16):
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(5)
    return [GenerationRequest(
        prompt=rng.randint(0, vocab, (plen,)).astype(np.int32),
        max_new_tokens=max_new) for _ in range(n)]


def _post(url, prompt, max_new, timeout=120):
    body = json.dumps({"prompt": [int(t) for t in prompt],
                       "max_tokens": int(max_new)}).encode()
    req = urllib.request.Request(
        url + "/v1/completions", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.load(r)


def _run_direct(model, reqs, num_slots, s_max):
    from dataclasses import replace

    from paddle_tpu.serving import ContinuousBatchingEngine
    # ragged_step=False: the banked SERVE_BENCH baseline is the
    # two-program engine; gateway overhead must be measured against it
    eng = ContinuousBatchingEngine(
        model, num_slots=num_slots, max_seq_len=s_max, decode_chunk=1,
        ragged_step=False, spec_decode=False,
        jit_cache=model.__dict__.setdefault("_serving_jit", {}))
    t0 = time.perf_counter()
    outs = eng.generate([replace(r) for r in reqs])
    dt = time.perf_counter() - t0
    tokens = sum(len(o) for o in outs)
    return {"wall_s": dt, "tokens": tokens, "tok_s": tokens / dt}, \
        [o.tolist() for o in outs]


def _run_http(server, reqs):
    outs = [None] * len(reqs)

    def worker(i):
        doc = _post(server.url, reqs[i].prompt, reqs[i].max_new_tokens)
        outs[i] = doc["choices"][0]["token_ids"]

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(len(reqs))]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    tokens = sum(len(o) for o in outs)
    return {"wall_s": dt, "tokens": tokens, "tok_s": tokens / dt}, outs


def measure_serve_http(quick=True, n_requests=8, max_new=None,
                       num_slots=4, repeats=3):
    from paddle_tpu.serving.server import serve
    max_new = max_new or (24 if quick else 64)
    s_max = 128 if quick else 256
    model = _models(quick)["jnp"]
    reqs = _requests(n_requests, max_new, model.config.vocab_size)
    server = serve(model, port=0, num_slots=num_slots, max_seq_len=s_max,
                   max_queue=2 * n_requests, model_name="bench")
    try:
        # warm every program + the HTTP path end to end
        _run_direct(model, reqs[:2], num_slots, s_max)
        _run_http(server, reqs[:2])
        direct = http = None
        tokens_equal = True
        for _ in range(repeats):   # interleave; best wall of each leg
            d, d_toks = _run_direct(model, reqs, num_slots, s_max)
            h, h_toks = _run_http(server, reqs)
            tokens_equal = tokens_equal and d_toks == h_toks
            direct = d if direct is None or d["wall_s"] < direct["wall_s"] \
                else direct
            http = h if http is None or h["wall_s"] < http["wall_s"] else http
    finally:
        server.shutdown(drain=False, timeout=30)
    return {
        "direct": direct, "http": http, "repeats": repeats,
        # direct and HTTP legs share the engine default (paged since
        # PR 5): overhead_ratio stays like-vs-like; recorded so
        # absolute numbers vs the dense-era bank are attributable
        "paged_attn": True,
        "tokens_equal": tokens_equal,
        "overhead_ratio": http["wall_s"] / direct["wall_s"],
        "gateway_overhead_ms_per_token":
            (http["wall_s"] - direct["wall_s"]) / http["tokens"] * 1e3,
        "n_requests": n_requests, "max_new": max_new,
        "num_slots": num_slots,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "serve_http": measure_serve_http(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
