"""Multi-tenant SLO serving benchmark (README "Multi-tenant SLO
serving").

Question answered: when a latency-class trickle shares the engine with
a batch flood, what does the policy scheduler (priority classes +
deadline-aware admission + SLO-driven preemption) buy the latency
tenant, and what does it cost the batch tenant?

One workload, two legs, identical requests: a batch flood wide enough
to hold every KV slot for the whole run, plus staggered latency-class
arrivals with an 80ms TTFT target. Both legs replay the same
virtual-time submission schedule under a ``VirtualClock`` advanced a
fixed ``DT`` per engine step, so every latency figure is a pure
SCHEDULING measure (steps-waited x DT) — no wall-clock noise, and the
whole bench replays byte-identically.

- **policy off** — the FIFO baseline (no class table; the
  ``priority_class`` labels are stripped, exactly the legacy engine):
  a latency arrival waits for a natural slot behind the flood.
- **policy on** — the three-way class table: the same arrival turns
  URGENT at half its TTFT budget and displaces one batch victim by
  recompute (chain donated, PRNG snapshotted).

Acceptance (all gates must hold):

- policy-on latency TTFT p95 <= the 80ms class target;
- policy-off latency TTFT p95 degrades >= ACCEPT_DEGRADE_RATIO x the
  policy-on p95 (the win is real, not noise);
- batch virtual throughput under policy >= ACCEPT_BATCH_RATIO x the
  policy-off leg (preemption-by-recompute taxes the flood, bounded);
- ZERO lost requests either leg (every stream finishes length|stop);
- per-request token streams BYTE-IDENTICAL across the legs (policy
  moves work in time, never changes tokens — the transparency gate);
- ``decode_compilations() == 1`` per leg, preemption/restore included;
- the policy leg REPLAYS identically (streams, TTFTs, preemption
  count) when run twice.

Usage:
  python scripts/bench_slo.py --quick [--json PATH]   # CPU-sized
"""
import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_decode import _models  # noqa: E402

NUM_SLOTS = 4
S_MAX = 128
BS = 8                            # KV block size
CHUNK = 16                        # chunked-prefill budget
DT = 0.005                        # virtual seconds per engine step
TTFT_SLO_S = 0.08                 # the latency class target (16 steps)
CLASSES = "latency,standard,batch*"
SLO_TTFT_MS = "80,400,0"
ACCEPT_DEGRADE_RATIO = 3.0        # policy-off p95 / policy-on p95
ACCEPT_BATCH_RATIO = 0.8          # batch tok/s(policy) / tok/s(fifo)


def _workload(vocab, flood, trickle, batch_new):
    """(virtual_time, tag, request) triples: a batch flood submitted at
    t=0 (greedy rows plus one seeded-sampled row — the PRNG-snapshot
    path must be exercised under preemption), then latency arrivals
    staggered AFTER the flood owns every slot."""
    from paddle_tpu.serving import GenerationRequest
    rng = np.random.RandomState(61)
    jobs = []
    for i in range(flood):
        kw = {"temperature": 0.9, "top_k": 5, "seed": 123} if i == 1 else {}
        jobs.append((0.0, "batch", GenerationRequest(
            prompt=rng.randint(0, vocab, (12,)).astype(np.int32),
            max_new_tokens=batch_new, priority_class="batch", **kw)))
    for i in range(trickle):
        jobs.append((0.02 + 0.05 * i, "latency", GenerationRequest(
            prompt=rng.randint(0, vocab, (8,)).astype(np.int32),
            max_new_tokens=4, priority_class="latency")))
    return jobs


def _strip(req):
    from paddle_tpu.serving import GenerationRequest
    return GenerationRequest(
        prompt=req.prompt, max_new_tokens=req.max_new_tokens,
        temperature=req.temperature, top_k=req.top_k, seed=req.seed)


def _leg(model, jobs, policy):
    """Replay the schedule on one engine; virtual time advances DT per
    step (and per idle tick between arrivals)."""
    from paddle_tpu.serving import (ClassTable, ContinuousBatchingEngine,
                                    VirtualClock)
    clk = VirtualClock()
    table = ClassTable.parse(CLASSES, slo_ttft_ms=SLO_TTFT_MS) \
        if policy else None
    eng = ContinuousBatchingEngine(
        model, num_slots=NUM_SLOTS, max_seq_len=S_MAX, decode_chunk=1,
        prefix_cache=True, prefix_block_size=BS, prefill_chunk=CHUNK,
        step_clock=clk, priority_classes=table,
        jit_cache=model.__dict__.setdefault("_serving_jit_slobench", {}))
    pending = sorted(jobs, key=lambda j: j[0])
    seqs, i = [], 0
    while i < len(pending) or eng.has_work():
        while i < len(pending) and pending[i][0] <= clk():
            t, tag, req = pending[i]
            seqs.append((tag, eng.submit(
                req if policy else _strip(req))))
            i += 1
        if eng.has_work():
            eng.step()
        clk.advance(DT)

    lat_ttft = sorted(s.ttft_s for tag, s in seqs if tag == "latency")
    batch = [s for tag, s in seqs if tag == "batch"]
    batch_tokens = sum(len(s.tokens) for s in batch)
    batch_makespan = max(s.t_finish for s in batch)
    return {
        "latency_ttft_p50_ms": round(
            float(np.percentile(lat_ttft, 50)) * 1e3, 3),
        "latency_ttft_p95_ms": round(
            float(np.percentile(lat_ttft, 95)) * 1e3, 3),
        "latency_ttft_max_ms": round(lat_ttft[-1] * 1e3, 3),
        "batch_tokens": batch_tokens,
        "batch_makespan_virtual_s": round(batch_makespan, 4),
        "batch_tok_per_virtual_s": round(
            batch_tokens / max(batch_makespan, 1e-9), 2),
        "policy_preemptions": eng.stats["policy_preemptions"],
        "restores": eng.stats["restores"],
        "finish_reasons": sorted({s.finish_reason for _, s in seqs}),
        "lost": sum(1 for _, s in seqs
                    if s.finish_reason not in ("length", "stop")),
        "decode_compilations": eng.decode_compilations(),
    }, [s.tokens for _, s in seqs], [round(t, 6) for t in lat_ttft]


def measure_slo(quick=True, flood=None, trickle=None, batch_new=None):
    model = _models(quick)["jnp"]
    jobs = _workload(model.config.vocab_size,
                     flood=flood or (4 if quick else 8),
                     trickle=trickle or (4 if quick else 8),
                     batch_new=batch_new or (64 if quick else 96))

    fifo, fifo_streams, _ = _leg(model, jobs, policy=False)
    pol, pol_streams, pol_ttfts = _leg(model, jobs, policy=True)
    # deterministic-replay pin: the whole policy leg, rerun
    pol2, pol2_streams, pol2_ttfts = _leg(model, jobs, policy=True)

    degrade = fifo["latency_ttft_p95_ms"] / max(
        pol["latency_ttft_p95_ms"], 1e-9)
    batch_ratio = pol["batch_tok_per_virtual_s"] / max(
        fifo["batch_tok_per_virtual_s"], 1e-9)
    replay_ok = (pol_streams == pol2_streams and pol_ttfts == pol2_ttfts
                 and pol["policy_preemptions"] == pol2["policy_preemptions"])
    tokens_equal = fifo_streams == pol_streams
    compile_once = (fifo["decode_compilations"] == 1
                    and pol["decode_compilations"] == 1)
    accepted = bool(
        tokens_equal and replay_ok and compile_once
        and fifo["lost"] == 0 and pol["lost"] == 0
        and pol["latency_ttft_p95_ms"] <= TTFT_SLO_S * 1e3
        and degrade >= ACCEPT_DEGRADE_RATIO
        and pol["policy_preemptions"] > 0
        and batch_ratio >= ACCEPT_BATCH_RATIO)
    return {
        "num_slots": NUM_SLOTS,
        "dt_virtual_s": DT,
        "classes": CLASSES,
        "ttft_slo_ms": TTFT_SLO_S * 1e3,
        "requests": len(jobs),
        "fifo": fifo,
        "policy": pol,
        "ttft_p95_degrade_ratio_fifo_over_policy": round(degrade, 4),
        "batch_throughput_ratio_policy_over_fifo": round(batch_ratio, 4),
        "tokens_equal": tokens_equal,
        "replay_identical": replay_ok,
        "compile_once": compile_once,
        "accepted": accepted,
        "workload": "batch flood (greedy + one seeded row) holding every "
                    "slot for the whole run + staggered latency arrivals "
                    "with an 80ms TTFT target, replayed on a VirtualClock "
                    "(DT per step) policy-off vs policy-on; latency "
                    "figures are pure scheduling measures.",
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CPU-sized model + short budgets")
    ap.add_argument("--json", default=None, help="also write result here")
    args = ap.parse_args()
    import jax
    res = {"platform": jax.default_backend(), "quick": bool(args.quick),
           "slo": measure_slo(quick=args.quick)}
    print(json.dumps(res, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(res, f, indent=1)
    return 0 if res["slo"]["accepted"] else 1


if __name__ == "__main__":
    sys.exit(main())
